"""Fixed-key length-doubling PRG on 128-bit seeds, as batched TPU array ops.

The reference implements this with a fixed-key AES-128 in Davies-Meyer mode
(``AES_0(seed) ^ seed`` with the seed loaded as the counter; ref:
src/prg.rs:92-122, 199-270, with 8-way block batching for throughput).  AES
without AES-NI is a table-lookup cipher — gathers are the worst op class on a
TPU's vector unit — so the TPU-native design swaps the primitive, not the
construction: a fixed-key **ChaCha** permutation with the 128-bit seed as the
input block, feed-forward add (the ChaCha block function's built-in
Davies-Meyer structure), which is pure 32-bit add/xor/rotate — exactly what
the VPU executes at full width.  Every (client, dim, side, level) expansion is
one batched call; there is no per-key loop anywhere.

Semantics preserved from the reference (pinned by tests/oracle.py):

- length-doubling ``expand``: seed -> (left child seed, right child seed,
  2 "t" bits, 2 "y" bits)  (prg.rs:92-122);
- the seed's low 4 bits of byte 0 are masked to zero before expansion
  (prg.rs:97: ``key_short``), so seeds carry 124 bits of entropy;
- the reference then derives the t/y bits from the *masked* byte
  (prg.rs:103-104), making them the constants (1,1)/(1,1).  Honest
  seed-derived bits (``DERIVED_BITS = True``) are the production default
  here; setting it False reproduces the reference's constant-bit quirk for
  parity work.  Protocol correctness holds either way (the bits cancel in
  correction words), and the test-suite runs both.
- a CTR-mode stream over the same fixed-key block function for sampling
  field elements / random bytes (prg.rs:184-270 ``FixedKeyPrgStream``).

Security note: fixed-key ChaCha here plays the role fixed-key AES plays in
the reference — a correlation-robust hash for FSS (Guo et al. 2020 model).
``N_ROUNDS = 8`` matches the margin philosophy of the reference's 10-round
fixed-key AES (a reduced-round fixed-key cipher as CR hash): the best
public distinguisher on ChaCha is on 7 rounds, so 8 keeps a one-round
margin in a model where the adversary does not even control the key.
Measured cost of more margin (bench.bench_hash_margin, v5e, BENCH_r04):
in the GC/OT hash role garbling is bandwidth-bound, so 12/20 rounds cost
only +3% / +6% (18.7 -> 19.3 / 19.8 ms per 262144-wire garble) — an
operator wanting standard-cipher margins can raise ``N_ROUNDS`` to 20
nearly free there; the FSS keygen/expand kernels are cipher-bound and pay
~linearly (~1.5x/2.5x), which is why 8 stays the default for the PRG
role.  ``N_ROUNDS`` is read at trace time (one global; all roles move
together — a per-role split is deliberate non-complexity until someone
needs it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

SEED_WORDS = 4  # 128-bit seeds as uint32[..., 4], little-endian word order
N_ROUNDS = 8  # ChaCha double-round count = N_ROUNDS // 2

# Round-loop form, read at TRACE time (set before the first jit call in the
# process; bin/server.py and bench.py set it for the TPU backend):
#   False -> lax.scan over double-rounds: ~4x smaller HLO per call site, the
#            right default on compile-bound hosts (XLA:CPU on small cores);
#   True  -> unrolled rounds: ~6% faster keygen on the TPU chip.
# Both forms compute identical bits (same math, one loop rolled).
CHACHA_UNROLL = False

# "expand 32-byte k" — the standard ChaCha constant words.
_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
# Fixed 256-bit key, public by construction (nothing-up-my-sleeve: the
# reference hardcodes its AES key too — the PRG's security is in the seed,
# the key only needs to be fixed and independent of the data).
_FIXED_KEY = (
    0x243F6A88, 0x85A308D3, 0x13198A2E, 0x03707344,
    0xA4093822, 0x299F31D0, 0x082EFA98, 0xEC4E6C89,
)  # first 8 words of pi's fractional part (as in Blowfish's P-array)

# Production default: honest seed-derived t/y bits.  False reproduces the
# reference's observed constant-bit quirk (prg.rs:103-104 reads the masked
# byte, so its bits are the constants (1,1)/(1,1)) and remains the
# parity/test mode — the suite pins both settings.  Protocol correctness
# holds either way (the bits cancel in correction words); derived bits are
# the evidently *intended* construction, so they ship as the default.
DERIVED_BITS = True

# jax 0.4.x ships optimization_barrier without a vmap batching rule, so any
# jax.vmap over an eval path that crosses the fusion fences below (eval_full
# sweeps, the ibdcf full-domain tests) dies with NotImplementedError.  The
# barrier is semantically the identity, so the batched form is just the
# barrier applied to the batched operands with the batch dims unchanged —
# register that rule once, idempotently, where the fences live.
from jax._src.lax import lax as _lax_internal  # noqa: E402
from jax.interpreters import batching as _batching  # noqa: E402

if _lax_internal.optimization_barrier_p not in _batching.primitive_batchers:

    def _optimization_barrier_batcher(args, dims, **params):
        return (
            _lax_internal.optimization_barrier_p.bind(*args, **params),
            dims,
        )

    _batching.primitive_batchers[_lax_internal.optimization_barrier_p] = (
        _optimization_barrier_batcher
    )


def _rotl(x, n: int):
    return (x << n) | (x >> (32 - n))


def _quarter_round(a, b, c, d):
    a = a + b
    d = _rotl(d ^ a, 16)
    c = c + d
    b = _rotl(b ^ c, 12)
    a = a + b
    d = _rotl(d ^ a, 8)
    c = c + d
    b = _rotl(b ^ c, 7)
    return a, b, c, d


def chacha_block(block: jax.Array) -> jax.Array:
    """ChaCha block function on uint32[..., 4] input blocks -> uint32[..., 16].

    State = 4 constant words | 8 fixed-key words | the 4 input-block words,
    permuted N_ROUNDS rounds, with the standard feed-forward addition (which
    makes the map non-invertible — the Davies-Meyer role of prg.rs:120).

    Column-vectorized (SIMD ChaCha): the 4x4 state's rows live in one
    ``uint32[4, 4, ...]`` tensor; a column round is ONE quarter-round whose
    ops each span all 4 columns, and the diagonal round is the same after
    rolling row k by k — so a block call is ~8 wide quarter-rounds of HLO
    instead of 32 scalar-lane ones.  This quarters both the compile-time
    footprint of every kernel that embeds the PRG (the whole suite is
    compile-bound on 1-core XLA:CPU hosts) and the op-dispatch count at
    runtime.  The math (and thus every output bit) is unchanged.
    """
    block = jnp.asarray(block, jnp.uint32)
    if block.shape[-1] != SEED_WORDS:
        raise ValueError(f"input blocks must be uint32[..., 4], got {block.shape}")
    shape = block.shape[:-1]
    const = jnp.asarray(_SIGMA + _FIXED_KEY, jnp.uint32)
    # XOR with a zero derived from the input: a no-op numerically, but it
    # makes the constant rows data-dependent on `block`, so under shard_map
    # they carry the same varying-axes annotation as the input and the
    # round scan's carry types line up (scan-vma rule).
    rows = jnp.broadcast_to(const, shape + (12,)) ^ (block[..., :1] & jnp.uint32(0))
    # state rows: [..., 4 cols] each; row r holds words 4r..4r+3
    a = rows[..., 0:4]
    b = rows[..., 4:8]
    c = rows[..., 8:12]
    d = block
    init = (a, b, c, d)

    def _double_round(state, _):
        a, b, c, d = state
        # column round: one QR across all 4 columns at once
        a, b, c, d = _quarter_round(a, b, c, d)
        # diagonalize: row k rolls left by k -> diagonal round is a column
        # round on the rolled rows (standard SIMD ChaCha row rotation)
        b = jnp.roll(b, -1, axis=-1)
        c = jnp.roll(c, -2, axis=-1)
        d = jnp.roll(d, -3, axis=-1)
        a, b, c, d = _quarter_round(a, b, c, d)
        b = jnp.roll(b, 1, axis=-1)
        c = jnp.roll(c, 2, axis=-1)
        d = jnp.roll(d, 3, axis=-1)
        return (a, b, c, d), None

    if CHACHA_UNROLL:
        for _ in range(N_ROUNDS // 2):
            (a, b, c, d), _ = _double_round((a, b, c, d), None)
    else:
        (a, b, c, d), _ = jax.lax.scan(
            _double_round, (a, b, c, d), None, length=N_ROUNDS // 2
        )
    return jnp.concatenate(
        [x + y for x, y in zip((a, b, c, d), init)], axis=-1
    )


def mask_seed(seed: jax.Array) -> jax.Array:
    """Clear the low 4 bits of seed byte 0 (prg.rs:97 ``key_short``)."""
    seed = jnp.asarray(seed, jnp.uint32)
    return seed.at[..., 0].set(seed[..., 0] & jnp.uint32(0xFFFFFFF0))


def expand(seed: jax.Array, derived_bits: bool | None = None):
    """Length-doubling expansion of uint32[..., 4] seeds.

    Returns ``(s_l, s_r, bits, y_bits)``: child seeds uint32[..., 4] and
    bool[..., 2] t/y bit pairs, exactly the reference's ``PrgOutput``
    (prg.rs:56-60, 92-122).
    """
    # Resolve the module-global default *eagerly* (outside the jitted core) so
    # toggling DERIVED_BITS is never baked into a cached trace.
    if derived_bits is None:
        derived_bits = DERIVED_BITS
    return _expand_jit(seed, derived_bits)


@partial(jax.jit, static_argnames=("derived_bits",))
def _expand_jit(seed: jax.Array, derived_bits: bool):
    seed = mask_seed(seed)
    out = chacha_block(seed)
    # Fusion fence on the child-seed slices: without it, XLA:CPU's
    # loop-fusion emitter re-evaluates the whole ChaCha DAG once per
    # consumer output element when a consumer slices the block (measured: a
    # shard_mapped expand->slice at [128,32,2,2] hung >300 s compiling,
    # 2.5 s with the fence).  The fence sits HERE, not in chacha_block, so
    # that callers which consume only the t/y bits (the packed share-bit
    # expansion in default bit mode — the bits are constants there) let
    # the entire dead cipher evaluation fall to DCE.
    out = jax.lax.optimization_barrier(out)
    s_l = out[..., 0:4]
    s_r = out[..., 4:8]
    if derived_bits:
        w = out[..., 8]
        bits = jnp.stack([w & 1 == 0, w & 2 == 0], axis=-1)
        y_bits = jnp.stack([w & 4 == 0, w & 8 == 0], axis=-1)
    else:
        # prg.rs:103-104 reads the masked byte -> constants (True, True).
        bits = jnp.ones(seed.shape[:-1] + (2,), bool)
        y_bits = jnp.ones(seed.shape[:-1] + (2,), bool)
    return s_l, s_r, bits, y_bits


@partial(jax.jit, static_argnames=("n_blocks",))
def stream_blocks(seed: jax.Array, n_blocks: int, offset=0) -> jax.Array:
    """CTR-mode stream: uint32[..., 4] seed -> uint32[..., n_blocks, 16].

    The seed is the starting counter block; successive blocks increment word 0
    (mod 2^32 — fine for any stream < 256 GiB), mirroring the reference's
    AES-CTR ``FixedKeyPrgStream`` (prg.rs:184-270) with the seed loaded as the
    initial counter (prg.rs:199-232).  Unlike :func:`expand`, the stream path
    uses the seed **unmasked** — the reference masks only in ``expand_dir``
    (prg.rs:97), not in its CTR stream (prg.rs:136).

    ``offset`` (scalar, may be traced) starts the counter ``offset`` blocks
    in — session streams (OT extension) consume the stream incrementally.
    """
    seed = jnp.asarray(seed, jnp.uint32)
    ctr = jnp.arange(n_blocks, dtype=jnp.uint32) + jnp.asarray(offset, jnp.uint32)
    blocks = jnp.broadcast_to(
        seed[..., None, :], seed.shape[:-1] + (n_blocks, 4)
    )
    blocks = blocks.at[..., 0].add(ctr)
    # same fusion fence as _expand_jit: stream consumers slice the block
    return jax.lax.optimization_barrier(chacha_block(blocks))


def stream_words(seed: jax.Array, n_words: int) -> jax.Array:
    """uint32[..., 4] seed -> uint32[..., n_words] pseudorandom words."""
    n_blocks = -(-n_words // 16)
    out = stream_blocks(seed, n_blocks)
    return out.reshape(out.shape[:-2] + (n_blocks * 16,))[..., :n_words]


# ---------------------------------------------------------------------------
# NumPy mirror (bit-exact, used by the test oracle and host-side tooling)
# ---------------------------------------------------------------------------


def _np_rotl(x, n):
    x = x.astype(np.uint32)
    return ((x << np.uint32(n)) | (x >> np.uint32(32 - n))).astype(np.uint32)


def np_chacha_block(block: np.ndarray) -> np.ndarray:
    """NumPy twin of :func:`chacha_block` (same shapes, bit-exact)."""
    block = np.asarray(block, np.uint32)
    if block.shape[-1] != SEED_WORDS:
        raise ValueError(f"input blocks must be uint32[..., 4], got {block.shape}")
    shape = block.shape[:-1]
    x = [np.broadcast_to(np.uint32(w), shape).copy() for w in _SIGMA + _FIXED_KEY]
    x += [block[..., i].copy() for i in range(4)]
    init = [v.copy() for v in x]

    def qr(a, b, c, d):
        a = (a + b).astype(np.uint32)
        d = _np_rotl(d ^ a, 16)
        c = (c + d).astype(np.uint32)
        b = _np_rotl(b ^ c, 12)
        a = (a + b).astype(np.uint32)
        d = _np_rotl(d ^ a, 8)
        c = (c + d).astype(np.uint32)
        b = _np_rotl(b ^ c, 7)
        return a, b, c, d

    with np.errstate(over="ignore"):  # u32 wraparound is the cipher's add
        for _ in range(N_ROUNDS // 2):
            x[0], x[4], x[8], x[12] = qr(x[0], x[4], x[8], x[12])
            x[1], x[5], x[9], x[13] = qr(x[1], x[5], x[9], x[13])
            x[2], x[6], x[10], x[14] = qr(x[2], x[6], x[10], x[14])
            x[3], x[7], x[11], x[15] = qr(x[3], x[7], x[11], x[15])
            x[0], x[5], x[10], x[15] = qr(x[0], x[5], x[10], x[15])
            x[1], x[6], x[11], x[12] = qr(x[1], x[6], x[11], x[12])
            x[2], x[7], x[8], x[13] = qr(x[2], x[7], x[8], x[13])
            x[3], x[4], x[9], x[14] = qr(x[3], x[4], x[9], x[14])
        return np.stack(
            [(a + b).astype(np.uint32) for a, b in zip(x, init)], axis=-1
        )


def np_expand(seed: np.ndarray, derived_bits: bool | None = None):
    """NumPy twin of :func:`expand` — same shapes and bit-exact outputs.

    Lets host-only paths (client simulators, CPU-mesh dryruns) build key
    material without compiling the device program: XLA:CPU compiles the
    keygen scan pathologically slowly (see tests/conftest.py), and the
    NumPy mirror sidesteps the device entirely.
    """
    if derived_bits is None:
        derived_bits = DERIVED_BITS
    seed = np.array(seed, np.uint32, copy=True)
    seed[..., 0] &= np.uint32(0xFFFFFFF0)  # mask_seed (prg.rs:97)
    out = np_chacha_block(seed)
    s_l = out[..., 0:4]
    s_r = out[..., 4:8]
    if derived_bits:
        w = out[..., 8]
        bits = np.stack([w & 1 == 0, w & 2 == 0], axis=-1)
        y_bits = np.stack([w & 4 == 0, w & 8 == 0], axis=-1)
    else:
        bits = np.ones(seed.shape[:-1] + (2,), bool)
        y_bits = np.ones(seed.shape[:-1] + (2,), bool)
    return s_l, s_r, bits, y_bits


def np_expand_bytes(seed: bytes, derived_bits: bool | None = None):
    """bytes-interface twin of :func:`expand` for the spec oracle.

    seed: 16 bytes -> (s_l bytes, s_r bytes, (t0,t1), (y0,y1)).
    """
    if derived_bits is None:
        derived_bits = DERIVED_BITS
    words = np.frombuffer(bytes([seed[0] & 0xF0]) + seed[1:], dtype="<u4")
    out = np_chacha_block(words)
    s_l = out[0:4].astype("<u4").tobytes()
    s_r = out[4:8].astype("<u4").tobytes()
    if derived_bits:
        w = int(out[8])
        bits = (w & 1 == 0, w & 2 == 0)
        y_bits = (w & 4 == 0, w & 8 == 0)
    else:
        bits = (True, True)
        y_bits = (True, True)
    return s_l, s_r, bits, y_bits


def np_stream_words(seed: np.ndarray, n_words: int) -> np.ndarray:
    """NumPy twin of :func:`stream_words` (seed unmasked, ctr in word 0)."""
    seed = np.asarray(seed, np.uint32)
    n_blocks = -(-n_words // 16)
    ctr = np.arange(n_blocks, dtype=np.uint32)
    blocks = np.broadcast_to(
        seed[..., None, :], seed.shape[:-1] + (n_blocks, 4)
    ).copy()
    with np.errstate(over="ignore"):
        blocks[..., 0] += ctr
    out = np_chacha_block(blocks)
    return out.reshape(out.shape[:-2] + (n_blocks * 16,))[..., :n_words]


def seeds_from_bytes(data: bytes) -> np.ndarray:
    """16-byte chunks -> uint32[n, 4] seed array."""
    assert len(data) % 16 == 0
    return np.frombuffer(data, dtype="<u4").reshape(-1, 4)


def seed_to_bytes(seed) -> bytes:
    return np.asarray(seed, dtype="<u4").tobytes()
