"""Fused Pallas ibDCF eval step — the crawl's per-level chip hot loop.

``advance`` (protocol/collect.py) spends its time in one place: the PRG
expansion of every surviving (node, client, dim, side) state
(ref: ibDCF.rs:208-227 ``eval_bit``; the reference loops it per key,
collect.rs:94-119).  The XLA version runs at ~12 ns per ChaCha block on the
chip; this kernel runs the same step at the keygen kernel's ~4 ns by
keeping the cipher state in registers with the flat state index spread
over (row, sublane, lane) — every ChaCha word is a [R_BLK, 8, 128] vreg
batch (layout family of ops/keygen_pallas.py).

Scope: exactly one level advance on FLAT state tensors — the caller keeps
the parent gather, direction select of correction bits, and reshapes in
XLA (they are bandwidth-trivial), so the kernel is a pure map with no
dynamic indexing.  Bit-exact vs ops/ibdcf._eval_bit_jit in both bit modes
(tests/test_keygen_pallas.py); opt in via ``collect.EVAL_PALLAS = True``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .keygen_pallas import LANES, SUB, _chacha16

R_BLK = 64  # row-groups per grid step: 64 * 8 * 128 = 64Ki states/step


def _kernel(derived_bits: bool,
            seed_ref, t_ref, y_ref, dir_ref, cws_ref, cwb_ref, cwy_ref,
            oseed_ref, obit_ref, oy_ref):
    """One row block, all u32 (flags as 0/1 words, selects as XOR-masks;
    Mosaic rejects vector i1).  Shapes: seed/cw_seed u32[4, R_BLK, 8,
    LANES], everything else u32[R_BLK, 8, LANES]."""
    d = dir_ref[...]
    t = t_ref[...]
    dm = jnp.uint32(0) - d
    tm = jnp.uint32(0) - t

    blk = [seed_ref[w] for w in range(4)]
    blk[0] = blk[0] & jnp.uint32(0xFFFFFFF0)  # prg.rs:97 mask
    out = _chacha16(blk)
    for w in range(4):
        # child seed by direction, then the t-gated correction
        child = out[w] ^ (dm & (out[w] ^ out[4 + w]))
        oseed_ref[w] = child ^ (tm & cws_ref[w])
    if derived_bits:
        w8 = out[8]
        b_l, b_r = (w8 & 1) ^ 1, ((w8 >> 1) & 1) ^ 1
        y_l, y_r = ((w8 >> 2) & 1) ^ 1, ((w8 >> 3) & 1) ^ 1
        tau_b = b_l ^ (d & (b_l ^ b_r))
        tau_y = y_l ^ (d & (y_l ^ y_r))
    else:  # the reference's masked-byte constants (prg.rs:103-104)
        tau_b = jnp.full(d.shape, 1, jnp.uint32)
        tau_y = tau_b
    obit_ref[...] = tau_b ^ (t & cwb_ref[...])
    oy_ref[...] = tau_y ^ (t & cwy_ref[...]) ^ y_ref[...]


@partial(jax.jit, static_argnames=("derived_bits",))
def eval_bit_flat(seed, t, y, direction, cw_seed, cw_b_d, cw_y_d,
                  derived_bits: bool):
    """Advance B flat states one level.

    seed/cw_seed: u32[B, 4]; t, y, direction, cw_b_d, cw_y_d: bool[B]
    (cw bits already direction-selected).  Returns (seed' u32[B, 4],
    bit' bool[B], y' bool[B]) — the same recurrence as
    ibdcf._eval_bit_jit on flattened tensors.
    """
    from jax.experimental import pallas as pl

    B = seed.shape[0]
    group = SUB * LANES
    pad = (-B) % (group * R_BLK)
    bp = B + pad
    rows = bp // group

    def flags(a):
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad,), jnp.uint32)])
        return a.reshape(rows, SUB, LANES)

    def words(a):
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad, 4), jnp.uint32)])
        return jnp.transpose(a.reshape(rows, SUB, LANES, 4), (3, 0, 1, 2))

    z = np.int32(0)
    spec4 = pl.BlockSpec((4, R_BLK, SUB, LANES), lambda j: (z, j, z, z))
    spec1 = pl.BlockSpec((R_BLK, SUB, LANES), lambda j: (j, z, z))
    oseed, obit, oy = pl.pallas_call(
        partial(_kernel, derived_bits),
        grid=(rows // R_BLK,),
        in_specs=[spec4, spec1, spec1, spec1, spec4, spec1, spec1],
        out_specs=[spec4, spec1, spec1],
        out_shape=[
            jax.ShapeDtypeStruct((4, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((rows, SUB, LANES), jnp.uint32),
        ],
    )(words(seed), flags(t), flags(y), flags(direction),
      words(cw_seed), flags(cw_b_d), flags(cw_y_d))
    oseed = jnp.transpose(oseed, (1, 2, 3, 0)).reshape(bp, 4)[:B]
    obit = obit.reshape(bp)[:B] != 0
    oy = oy.reshape(bp)[:B] != 0
    return oseed, obit, oy
