"""COVID-geo workload: county-centroid sampler with spatial jitter
(ref: src/sample_covid_data.rs).

The reference streams a 9 GB case-surveillance CSV (absent from its own tree,
``.MISSING_LARGE_BLOBS``), maps each case's county FIPS to a centroid, adds
uniform jitter inside a km-side square, and emits each coordinate as the
**64 IEEE-754 bits of the f64** MSB-first (``f64_to_bool_vec``,
sample_covid_data.rs:32-35) — so its tree domain for this workload is the
raw float bit pattern.  We reproduce the pipeline incl. that encoding quirk
(with its lexicographic-ordering caveats inherited from upstream), and fall
back to sampling counties uniformly when the big CSV is absent.
"""

from __future__ import annotations

import csv
import os
import struct

import numpy as np


def load_centroids(path: str) -> dict[str, tuple[float, float]]:
    """FIPS -> (lat, lon) from county_centroids.csv
    (ref: sample_covid_data.rs:17-30)."""
    out = {}
    # utf-8-sig: the shipped centroid CSV begins with a UTF-8 BOM
    with open(path, newline="", encoding="utf-8-sig") as f:
        for row in csv.DictReader(f):
            out[row["fips_code"]] = (float(row["latitude"]), float(row["longitude"]))
    return out


def f64_to_bool_vec(value: float) -> np.ndarray:
    """IEEE-754 bits of an f64, MSB-first (ref: sample_covid_data.rs:32-35)."""
    bits = struct.unpack(">Q", struct.pack(">d", value))[0]
    return np.array([(bits >> (63 - i)) & 1 == 1 for i in range(64)], dtype=bool)


def bool_vec_to_f64(bits) -> float:
    v = 0
    for b in np.asarray(bits, bool):
        v = (v << 1) | int(b)
    return struct.unpack(">d", struct.pack(">Q", v))[0]


def uniform_in_square(
    lat: float, lon: float, side_length_km: float, rng: np.random.Generator
) -> tuple[float, float]:
    """Uniform jitter in a km-side square at this latitude
    (ref: sample_covid_data.rs:45-62)."""
    km_per_deg_lat = 111.32
    km_per_deg_lon = 111.32 * np.cos(np.radians(lat))
    a_lat = (side_length_km / 2.0) / km_per_deg_lat
    a_lon = (side_length_km / 2.0) / km_per_deg_lon
    return (
        float(np.clip(lat + rng.uniform(-a_lat, a_lat), -90.0, 90.0)),
        float(np.clip(lon + rng.uniform(-a_lon, a_lon), -180.0, 180.0)),
    )


def sample_covid_locations(
    covid_path: str,
    centroids_path: str,
    sample_size: int,
    fuzz_factor: float | None = None,
    seed: int | None = None,
    fips_column: int = 5,
) -> np.ndarray:
    """bool[sample_size, 2, 64] jittered case locations as f64 bit vectors
    (ref: sample_covid_data.rs:64-175).  When the case CSV is missing —
    as in the reference's own tree — counties are sampled uniformly from the
    centroid file instead (same output distribution family, no 9 GB input)."""
    rng = np.random.default_rng(seed)
    centroids = load_centroids(centroids_path)
    fips_list = sorted(centroids)

    coords = []
    if os.path.exists(covid_path):
        with open(covid_path, newline="") as f:
            reader = csv.reader(f)
            next(reader, None)
            rows = []
            for row in reader:
                if len(row) > fips_column and row[fips_column].strip() in centroids:
                    rows.append(row[fips_column].strip())
        if len(rows) < sample_size:
            raise ValueError(
                f"Need {sample_size} valid samples but only found {len(rows)}"
            )
        take = rng.choice(len(rows), size=sample_size, replace=False)
        coords = [centroids[rows[i]] for i in take]
    else:
        take = rng.choice(len(fips_list), size=sample_size, replace=True)
        coords = [centroids[fips_list[i]] for i in take]

    out = np.empty((sample_size, 2, 64), dtype=bool)
    for i, (lat, lon) in enumerate(coords):
        if fuzz_factor is not None:
            lat, lon = uniform_in_square(lat, lon, fuzz_factor, rng)
        out[i, 0] = f64_to_bool_vec(lat)
        out[i, 1] = f64_to_bool_vec(lon)
    return out
