"""RideAustin geo workload: centidegree codecs, CSV sampler, output writer
(ref: src/sample_driving_data.rs).

Coordinates are centidegrees in i16 (2 decimal places ≈ 1.1 km,
ref: sample_driving_data.rs:8-22).  The protocol-side bit encoding is
offset-binary (see ops/ibdcf.gen_l_inf_ball_from_coords) so zero-crossing
balls work; this module converts between CSV floats, i16 centidegrees, and
those bit paths.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from ..utils import bits as bitutils

CENTIDEGREES_SCALE = 100.0


def geo_to_int(lat: float, lng: float) -> tuple[int, int]:
    """(ref: sample_driving_data.rs:11-15)"""
    return (
        int(np.clip(round(lat * CENTIDEGREES_SCALE), -32768, 32767)),
        int(np.clip(round(lng * CENTIDEGREES_SCALE), -32768, 32767)),
    )


def int_to_geo(lat_int: int, lng_int: int) -> tuple[float, float]:
    """(ref: sample_driving_data.rs:18-22)"""
    return lat_int / CENTIDEGREES_SCALE, lng_int / CENTIDEGREES_SCALE


def sample_start_locations(
    path: str, sample_size: int, seed: int | None = None
) -> np.ndarray:
    """int16[sample_size, 2] (lat, lon) centidegrees sampled from the
    RideAustin CSV — columns 14 (start lat) and 13 (start lon), matching
    the reference's indexing (ref: sample_driving_data.rs:72-97).

    Uses the native streaming reservoir sampler (one pass, O(k) memory —
    the multi-GB-CSV regime the reference's Rust loaders run in,
    fuzzyheavyhitters_tpu/native/) when the toolchain allows, else an
    in-memory NumPy fallback.

    Reproducibility caveat: a fixed ``seed`` pins the sample WITHIN one
    environment, but the two paths are different algorithms (xoshiro
    reservoir vs ``rng.choice``), so the same seed yields different
    samples depending on whether the native library built.  Both are
    uniform without replacement; pin the environment (or force the
    fallback by removing the .so) when cross-machine reproducibility of
    the exact sample matters."""
    from .. import native

    if seed is None:  # stay random per call, matching the NumPy fallback
        seed = int(np.random.default_rng().integers(0, 2**63))
    coords = native.csv_reservoir_sample(
        path, col_a=14, col_b=13, k=sample_size, seed=seed
    )
    if coords is None:  # pure-Python fallback: load-all + choice
        rng = np.random.default_rng(seed)
        with open(path, newline="") as f:
            reader = csv.reader(f)
            next(reader)  # header
            rows = [r for r in reader]
        take = rng.choice(
            len(rows), size=min(sample_size, len(rows)), replace=False
        )
        coords = np.array(
            [[float(rows[i][14]), float(rows[i][13])] for i in take]
        )
    out = [geo_to_int(lat, lon) for lat, lon in coords]
    return np.array(out, dtype=np.int16)


def synthetic_austin_locations(
    sample_size: int, seed: int | None = None, n_hotspots: int = 6
) -> np.ndarray:
    """Synthetic stand-in when the RideAustin CSV is absent (it is not
    shipped with the reference either): clustered pickups around downtown
    Austin (30.26, -97.74) in centidegrees."""
    rng = np.random.default_rng(seed)
    hot = np.array([3026, -9774]) + rng.integers(-60, 60, size=(n_hotspots, 2))
    idx = rng.integers(0, n_hotspots, size=sample_size)
    # tight per-hotspot spread (sigma = 1 centidegree): real pickup data
    # concentrates on street corners, and the shipped config's 7.5%
    # threshold should surface hitters on the synthetic stand-in too
    pts = hot[idx] + rng.normal(0, 1.0, size=(sample_size, 2)).round().astype(int)
    return np.clip(pts, -32768, 32767).astype(np.int16)


def load_or_synthesize_locations(
    path: str, sample_size: int, seed: int | None = None
) -> np.ndarray:
    if os.path.exists(path):
        return sample_start_locations(path, sample_size, seed)
    return synthetic_austin_locations(sample_size, seed)


def paths_to_coords(paths: np.ndarray) -> np.ndarray:
    """bool[H, 2, 16] offset-binary tree paths -> int16[H, 2] centidegrees
    (the decode half of ref: sample_driving_data.rs:135-141)."""
    out = np.empty(paths.shape[:2], dtype=np.int16)
    for i in range(paths.shape[0]):
        for j in range(paths.shape[1]):
            out[i, j] = bitutils.ob_bits_to_i16(paths[i, j])
    return out


def save_heavy_hitters(paths: np.ndarray, output_path: str) -> None:
    """Append heavy hitters as (index, latitude, longitude) CSV rows,
    writing the header only when the file is empty
    (ref: sample_driving_data.rs:117-148)."""
    coords = paths_to_coords(paths)
    new = not os.path.exists(output_path) or os.path.getsize(output_path) == 0
    with open(output_path, "a", newline="") as f:
        w = csv.writer(f)
        if new:
            w.writerow(["index", "latitude", "longitude"])
        for i, (lat_i, lon_i) in enumerate(coords):
            lat, lon = int_to_geo(int(lat_i), int(lon_i))
            w.writerow([i, lat, lon])
