"""RideAustin visualization (ref: src/ride_austin_visualization.py).

The reference script plots the raw 9 GB rides dataset (hexbin start/end
heatmaps, duration histogram) over contextily web basemaps.  This
counterpart works in the environments this framework actually ships to:

- **no giant inputs required** — the primary subject is the protocol's
  OUTPUT, ``data/ride_heavy_hitters.csv`` (index, latitude, longitude;
  workloads/rides.py ``save_heavy_hitters``); raw start locations come
  from the real CSV when present and the seeded synthetic Austin sampler
  otherwise (``load_or_synthesize_locations`` — the same fallback the
  protocol drivers use);
- **no network** — plain matplotlib axes instead of contextily tile
  fetches (zero-egress deployments).

Outputs PNGs under ``data/ride_plots/``::

    python -m fuzzyheavyhitters_tpu.workloads.ride_austin_visualization \
        [--hitters data/ride_heavy_hitters.csv] [--n 20000]
"""

from __future__ import annotations

import argparse
import csv
import os

import numpy as np

from . import rides

DEFAULT_HITTERS = "data/ride_heavy_hitters.csv"
DEFAULT_RAW = "data/RideAustin_Weather.csv"
OUTPUT_DIR = "data/ride_plots"


def load_hitters(path: str) -> np.ndarray:
    """(lat, lon) float rows from the heavy-hitter output CSV."""
    out = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            out.append((float(row["latitude"]), float(row["longitude"])))
    return np.asarray(out, dtype=float).reshape(-1, 2)


def visualize(hitters_path: str = DEFAULT_HITTERS, raw_path: str = DEFAULT_RAW,
              n: int = 20_000, out_dir: str = OUTPUT_DIR) -> list[str]:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    written = []

    coords = rides.load_or_synthesize_locations(raw_path, n, seed=42)
    lat, lon = coords[:, 0] / 100.0, coords[:, 1] / 100.0  # centideg -> deg

    # 1. start-locations heatmap (the reference's hexbin, sans basemap)
    fig, ax = plt.subplots(figsize=(10, 8))
    hb = ax.hexbin(lon, lat, gridsize=60, cmap="viridis", mincnt=1)
    fig.colorbar(hb, ax=ax, label="rides")
    ax.set_xlabel("longitude")
    ax.set_ylabel("latitude")
    ax.set_title("Ride start locations (centidegree resolution)")
    p = os.path.join(out_dir, "start_heatmap.png")
    fig.savefig(p, dpi=120)
    plt.close(fig)
    written.append(p)

    # 2. heavy hitters over the start distribution (protocol output)
    if os.path.exists(hitters_path) and os.path.getsize(hitters_path) > 0:
        hh = load_hitters(hitters_path)
        fig, ax = plt.subplots(figsize=(10, 8))
        ax.hexbin(lon, lat, gridsize=60, cmap="Greys", mincnt=1)
        ax.scatter(hh[:, 1], hh[:, 0], s=60, c="crimson", marker="x",
                   label=f"{len(hh)} fuzzy heavy hitters")
        ax.set_xlabel("longitude")
        ax.set_ylabel("latitude")
        ax.set_title("Fuzzy heavy hitters vs ride starts")
        ax.legend()
        p = os.path.join(out_dir, "heavy_hitters_map.png")
        fig.savefig(p, dpi=120)
        plt.close(fig)
        written.append(p)

    # 3. per-axis marginals (the reference's distribution histograms)
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    axes[0].hist(lat, bins=80, color="steelblue")
    axes[0].set_title("latitude marginal")
    axes[1].hist(lon, bins=80, color="steelblue")
    axes[1].set_title("longitude marginal")
    p = os.path.join(out_dir, "start_marginals.png")
    fig.savefig(p, dpi=120)
    plt.close(fig)
    written.append(p)
    return written


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hitters", default=DEFAULT_HITTERS)
    ap.add_argument("--raw", default=DEFAULT_RAW)
    ap.add_argument("--n", type=int, default=20_000)
    ap.add_argument("--out", default=OUTPUT_DIR)
    args = ap.parse_args()
    for p in visualize(args.hitters, args.raw, args.n, args.out):
        print(f"wrote {p}")


if __name__ == "__main__":
    main()
