"""Client workloads (ref: leader.rs:332-388 distribution selection)."""

from __future__ import annotations

import numpy as np

RIDES_CSV = "data/RideAustin_Weather.csv"
COVID_CSV = "data/COVID-19_Case_Surveillance_Public_Use_Data_with_Geography_20250430.csv"
CENTROIDS_CSV = "data/county_centroids.csv"
OUTPUT_CSV = "data/ride_heavy_hitters.csv"

AUG_LEN = 8  # zipf per-request augmentation bits (ref: leader.rs:331)


def sample_points(cfg, nreqs: int, rng: np.random.Generator) -> np.ndarray:
    """Distribution-selected client points -> bool[nreqs, n_dims, data_len]
    (ref: leader.rs:332, 372) — the one sampling pipeline shared by every
    deployment entry point (bin/leader.py, bin/mesh.py).  The rides flow
    is deterministic (internal seed 42, like the reference's seeded
    sampler), so rides outputs are comparable across deployments; zipf
    and covid draw from the caller's ``rng``."""
    from . import covid, rides, strings
    from ..utils import bits as bitutils

    if cfg.distribution == "zipf":
        pts, _ = strings.zipf_workload(
            rng, cfg.num_sites, cfg.data_len, cfg.n_dims, cfg.zipf_exponent,
            nreqs, AUG_LEN,
        )
        return pts
    if cfg.distribution == "rides":
        assert cfg.data_len == 16 and cfg.n_dims == 2, "rides flow is i16 lat/lon"
        coords = rides.load_or_synthesize_locations(RIDES_CSV, nreqs, seed=42)
        return np.stack(
            [
                np.stack([bitutils.i16_to_ob_bits(int(v)) for v in row])
                for row in coords
            ]
        )
    if cfg.distribution == "covid":
        assert cfg.data_len == 64 and cfg.n_dims == 2, "covid flow is f64-bit coords"
        return covid.sample_covid_locations(
            COVID_CSV, CENTROIDS_CSV, nreqs, fuzz_factor=float(AUG_LEN)
        )
    raise ValueError(f"unknown distribution {cfg.distribution!r}")
