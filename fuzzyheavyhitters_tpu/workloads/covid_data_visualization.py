"""COVID-geo visualization (ref: src/covid_data_visualization.py).

The reference script renders case-density heatmaps and demographic
histograms from the 9 GB case-surveillance CSV (absent even from its own
tree) over contextily basemaps.  This counterpart works from what actually
ships: the county-centroid file plus the same sampler the protocol uses
(``covid.sample_covid_locations`` falls back to uniform county sampling
when the big CSV is missing), and the protocol's decoded heavy-hitter
coordinates when provided.  Plain matplotlib, no network tiles.

Outputs PNGs under ``data/covid_plots/``::

    python -m fuzzyheavyhitters_tpu.workloads.covid_data_visualization \
        [--centroids data/county_centroids.csv] [--n 20000]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from . import covid

DEFAULT_CENTROIDS = "data/county_centroids.csv"
DEFAULT_CASES = "data/COVID-19_Case_Surveillance_Public_Use_Data_with_Geography_20250430.csv"
OUTPUT_DIR = "data/covid_plots"

# continental-US display window (the reference's maps crop to it too)
LON_LIM = (-130.0, -65.0)
LAT_LIM = (23.0, 50.0)


def visualize(centroids_path: str = DEFAULT_CENTROIDS, cases_path: str = DEFAULT_CASES,
              n: int = 20_000, out_dir: str = OUTPUT_DIR,
              hitters: np.ndarray | None = None) -> list[str]:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    written = []

    cents = covid.load_centroids(centroids_path)
    c_lat = np.array([v[0] for v in cents.values()])
    c_lon = np.array([v[1] for v in cents.values()])
    inside = (
        (c_lon >= LON_LIM[0]) & (c_lon <= LON_LIM[1])
        & (c_lat >= LAT_LIM[0]) & (c_lat <= LAT_LIM[1])
    )

    # 1. county centroid map (the sampler's support)
    fig, ax = plt.subplots(figsize=(12, 7))
    ax.scatter(c_lon[inside], c_lat[inside], s=2, c="steelblue", alpha=0.6)
    ax.set_xlim(*LON_LIM)
    ax.set_ylim(*LAT_LIM)
    ax.set_title(f"{inside.sum()} county centroids (sampler support)")
    ax.set_xlabel("longitude")
    ax.set_ylabel("latitude")
    p = os.path.join(out_dir, "county_centroids.png")
    fig.savefig(p, dpi=120)
    plt.close(fig)
    written.append(p)

    # 2. sampled case-location density (real CSV when present, else the
    # uniform-county fallback), decoded from the f64 bit-vector encoding
    pts_bits = covid.sample_covid_locations(
        cases_path, centroids_path, n, fuzz_factor=8.0, seed=7
    )
    lat = np.array([covid.bool_vec_to_f64(b) for b in pts_bits[:, 0]])
    lon = np.array([covid.bool_vec_to_f64(b) for b in pts_bits[:, 1]])
    keep = (
        (lon >= LON_LIM[0]) & (lon <= LON_LIM[1])
        & (lat >= LAT_LIM[0]) & (lat <= LAT_LIM[1])
    )
    fig, ax = plt.subplots(figsize=(12, 7))
    hb = ax.hexbin(lon[keep], lat[keep], gridsize=80, cmap="inferno", mincnt=1)
    fig.colorbar(hb, ax=ax, label="cases")
    if hitters is not None and len(hitters):
        ax.scatter(hitters[:, 1], hitters[:, 0], s=70, c="cyan", marker="x",
                   label=f"{len(hitters)} heavy hitters")
        ax.legend()
    ax.set_xlim(*LON_LIM)
    ax.set_ylim(*LAT_LIM)
    src = "case CSV" if os.path.exists(cases_path) else "uniform-county fallback"
    ax.set_title(f"Case-location density ({src}, 8 km jitter)")
    p = os.path.join(out_dir, "case_density_heatmap.png")
    fig.savefig(p, dpi=120)
    plt.close(fig)
    written.append(p)

    # 3. per-axis marginals of the sampled locations
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    axes[0].hist(lat[keep], bins=80, color="darkorange")
    axes[0].set_title("latitude marginal")
    axes[1].hist(lon[keep], bins=80, color="darkorange")
    axes[1].set_title("longitude marginal")
    p = os.path.join(out_dir, "location_marginals.png")
    fig.savefig(p, dpi=120)
    plt.close(fig)
    written.append(p)
    return written


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--centroids", default=DEFAULT_CENTROIDS)
    ap.add_argument("--cases", default=DEFAULT_CASES)
    ap.add_argument("--n", type=int, default=20_000)
    ap.add_argument("--out", default=OUTPUT_DIR)
    args = ap.parse_args()
    for p in visualize(args.centroids, args.cases, args.n, args.out):
        print(f"wrote {p}")


if __name__ == "__main__":
    main()
