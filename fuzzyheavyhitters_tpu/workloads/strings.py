"""Zipf site-string workload (ref: src/bin/leader.rs:38-66, 130-151).

Host-side client simulation: ``num_sites`` random site strings per dimension,
zipf-distributed site popularity, and per-request random augmentation bits so
distinct clients at the same site still differ in the low bits.
"""

from __future__ import annotations

import string as _string

import numpy as np

from ..utils import bits as bitutils

_ALNUM = np.frombuffer(
    (_string.ascii_uppercase + _string.ascii_lowercase + _string.digits).encode(),
    dtype=np.uint8,
)


def sample_string_bits(rng: np.random.Generator, nbits: int) -> np.ndarray:
    """Random alphanumeric string of ``nbits//8`` chars as per-byte LSB-first
    bits (ref: leader.rs:38-44 ``sample_string`` + lib.rs:90
    ``string_to_bits``), truncated to ``nbits``."""
    nchars = (nbits + 7) // 8
    chars = rng.choice(_ALNUM, size=nchars)
    bits = np.unpackbits(chars[:, None], axis=1, bitorder="little").reshape(-1)
    return bits[:nbits].astype(bool)


def generate_random_bit_vectors(
    rng: np.random.Generator, nbits: int, n_dims: int
) -> np.ndarray:
    """bool[n_dims, nbits] — one random string per dimension
    (ref: leader.rs:45-57)."""
    return np.stack([sample_string_bits(rng, nbits) for _ in range(n_dims)])


def generate_sites(
    rng: np.random.Generator, num_sites: int, data_len: int, n_dims: int, aug_len: int
) -> np.ndarray:
    """bool[num_sites, n_dims, data_len - aug_len] site prefixes
    (ref: leader.rs:60-66 ``generate_strings``)."""
    return np.stack(
        [
            generate_random_bit_vectors(rng, data_len - aug_len, n_dims)
            for _ in range(num_sites)
        ]
    )


def zipf_indices(
    rng: np.random.Generator, num_sites: int, exponent: float, nreqs: int
) -> np.ndarray:
    """Bounded zipf over [0, num_sites): P(k) ∝ 1/(k+1)^exponent — the
    ``zipf::ZipfDistribution`` the reference samples per request
    (ref: leader.rs:140-146, sample-1 as 0-based index)."""
    w = 1.0 / np.arange(1, num_sites + 1, dtype=np.float64) ** exponent
    return rng.choice(num_sites, size=nreqs, p=w / w.sum())


def augment_points(
    rng: np.random.Generator, sites: np.ndarray, idx: np.ndarray, aug_len: int
) -> np.ndarray:
    """Append ``aug_len`` random bits per dimension to each request's site
    string (ref: leader.rs:78-87 ``augment_string``) ->
    bool[nreqs, n_dims, data_len].

    Fully vectorized (one rng.choice + one unpackbits for the whole batch,
    same per-byte-LSB-first alnum-char semantics as
    :func:`sample_string_bits`): the per-request Python loops this replaces
    were minutes of host time at the 1M-client scale."""
    base = sites[idx]  # [nreqs, n_dims, L - aug]
    n, d, _ = base.shape
    if aug_len == 0:
        return base
    nchars = (aug_len + 7) // 8
    chars = rng.choice(_ALNUM, size=(n, d, nchars))
    aug = np.unpackbits(chars[..., None], axis=-1, bitorder="little")
    aug = aug.reshape(n, d, nchars * 8)[..., :aug_len].astype(bool)
    return np.concatenate([base, aug], axis=-1)


def zipf_workload(
    rng: np.random.Generator,
    num_sites: int,
    data_len: int,
    n_dims: int,
    zipf_exponent: float,
    nreqs: int,
    aug_len: int = 8,
) -> tuple[np.ndarray, np.ndarray]:
    """The full zipf client simulation: returns (points bool[nreqs, n_dims,
    data_len], site index per request) — feed the points to
    ``ibdcf.gen_l_inf_ball`` (ref: leader.rs:130-151 ``add_fuzzy_keys``)."""
    sites = generate_sites(rng, num_sites, data_len, n_dims, aug_len)
    idx = zipf_indices(rng, num_sites, zipf_exponent, nreqs)
    return augment_points(rng, sites, idx, aug_len), idx
