"""fhh-lint configuration: defaults + ``[tool.fhh-lint]`` in pyproject.toml.

This interpreter predates :mod:`tomllib` (3.11) and the repo bakes in no
third-party TOML reader, so :func:`_read_toml_subset` parses exactly the
subset the config uses — ``[table.sub]`` headers, ``key = value`` with
string / integer / boolean / list-of-string values (lists may span
lines) — and rejects nothing it doesn't understand (unknown constructs
are skipped line-wise; the linter must never crash on someone's build
metadata living in the same file).

Schema: every :class:`LintConfig` field name is a valid ``[tool.fhh-lint]``
key (list fields as TOML string arrays, ``baseline`` as a string), plus a
``[tool.fhh-lint.severity]`` table mapping rule name -> severity.  The
checked-in ``pyproject.toml`` is the operative copy for THIS repo; the
dataclass defaults below mirror it so the linter behaves identically when
pointed at a tree with no pyproject (a drift test in test_analysis.py
keeps the two in sync — edit pyproject, it will tell you to update here).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

# the fhh-race guard map shipped for THIS repo (mirrored by pyproject
# [tool.fhh-lint.guards] — the drift test keeps the two in sync): each
# shared attribute of the server verb plane / windowed-ingest driver is
# bound to the asyncio lock that owns it.  Module-level globals (obs,
# native, utils/compile_cache) are bound inline via `# fhh-guard:` — a
# dotless key here would apply to every module in scope.
_DEFAULT_GUARDS = {
    # CollectionSession (protocol/sessions.py): everything one
    # collection's verb plane mutates serializes on the SESSION's own
    # _verb_lock; the deliberately-unlocked fast paths (add_keys /
    # submit_keys / the frame-arrival pre-expand / the session-table
    # bind) carry VERIFIED `# fhh-race: atomic` contracts + runtime
    # guards.unguarded() windows.
    "CollectionSession.frontier": "_verb_lock",
    "CollectionSession.keys": "_verb_lock",
    "CollectionSession.keys_parts": "_verb_lock",
    "CollectionSession.alive_keys": "_verb_lock",
    "CollectionSession._children": "_verb_lock",
    "CollectionSession._last_shares": "_verb_lock",
    "CollectionSession._shard_children": "_verb_lock",
    "CollectionSession._shard_last": "_verb_lock",
    "CollectionSession._expand_ready": "_verb_lock",
    "CollectionSession._ingest_pools": "_verb_lock",
    "CollectionSession._admission": "_verb_lock",
    "CollectionSession._sketch_parts": "_verb_lock",
    "CollectionSession._sketch_root": "_verb_lock",
    "CollectionSession._ratchet_digest": "_verb_lock",
    "CollectionSession._window_sketch_root": "_verb_lock",
    # fleet migration stamps (the session_export/session_import verbs
    # dispatch under the session's _verb_lock)
    "CollectionSession._export_epoch": "_verb_lock",
    "CollectionSession._import_seen": "_verb_lock",
    # radix-2^k level fusion: the session's fused-bits-per-verb knob
    # (fixed at construction, read by every crawl verb under the lock)
    "CollectionSession._radix": "_verb_lock",
    # CollectorServer infra: the replay-dedup session table
    "CollectorServer._sessions": "_verb_lock",
    # WindowedIngest: gate-order == mirror-order state serializes on
    # _submit_lock (recovery additionally takes _recover_lock INSIDE it,
    # so every journal access holds _submit_lock)
    "WindowedIngest.window": "_submit_lock",
    "WindowedIngest._journal": "_submit_lock",
    "WindowedIngest._journaled": "_submit_lock",
    "WindowedIngest._sealed": "_submit_lock",
    # FleetDirectory (protocol/fleet.py): host-pair rows + session
    # placements serialize on the directory's own asyncio lock
    "FleetDirectory._hosts": "_lock",
    "FleetDirectory._placements": "_lock",
}


# the fhh-taint source table shipped for THIS repo (mirrored by
# pyproject [tool.fhh-lint.taint] and the runtime twin
# utils/taint_guard._DEFAULT_SOURCES — both drift-tested): dotted keys
# bind attribute READS (matched by attr name, receiver-agnostic — the
# names are chosen distinctive for exactly that reason); dotless keys
# bind function-call RETURNS (matched by last segment, so
# ``secure.derive_seed(...)`` matches cross-module).  Values document
# the key material; the analyzer reports the key as the source label.
_DEFAULT_TAINT = {
    # per-session secrets (protocol/sessions.py; read through the
    # rpc/mesh delegation properties under the same attr names)
    "CollectionSession._sec_seed": "per-session GC/b2a PRG root seed",
    "CollectionSession._sketch_seed": "sketch challenge coin (server-server secret)",
    "CollectionSession._ratchet_digest": "crawl transcript ratchet digest",
    "CollectionSession._last_shares": "expanded field share planes",
    # reconstructed migration payload (protocol/rpc.py session_import
    # rebuilds every pool buffer it lands under this label)
    "CollectionSession._imported_pool_shares":
        "migrated ingest-pool key shares (session_import)",
    # ibDCF/DPF key material (protocol/sketch.py)
    "SketchKeyBatch.root_seed": "sketch DPF root seeds",
    # IKNP OT-extension endpoint state (ops/otext.py)
    "OtExtSender.s_bits": "OT sender choice bits (= free-XOR offset R)",
    "OtExtSender.s_block": "packed OT sender choice block",
    "OtExtSender._s_dev": "device copy of the OT sender choice bits",
    "OtExtSender._seeds": "base-OT seeds selected by s",
    "OtExtReceiver._seeds0": "base-OT seed column 0",
    "OtExtReceiver._seeds1": "base-OT seed column 1",
    # function-return sources
    "derive_seed": "per-(purpose, level, ctr) PRG seed off the session seed",
    "mask_seed": "key_short-masked PRG seed (still key material)",
    "ratchet_seed": "per-level sketch challenge seed",
    "transcript_init": "transcript ratchet digest root",
    "transcript_absorb": "advanced transcript ratchet digest",
    "gen_triples": "Beaver triple shares",
    "_seed_from_point": "base-OT seed H(index, point)",
    "seeds": "base-OT seed columns (ops/baseot.py endpoints)",
}


@dataclass
class LintConfig:
    # host-sync rule: path prefixes whose loop bodies are hot, and
    # function names that ARE the per-level crawl path (their in-module
    # transitive callees inherit hotness)
    hot_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/ops",
        "fuzzyheavyhitters_tpu/parallel",
    )
    hot_roots: tuple = (
        "run_level",
        "tree_crawl",
        "tree_crawl_last",
        "tree_prune",
        "tree_prune_last",
        "sketch_verify",
        "expand_share_bits",
        "expand_share_bits_from_cw",
        "advance_from_children",
        "advance_from_cw",
        # the rpc expand stage: frame-arrival dispatch, per-level work
        "_maybe_pre_expand",
    )
    # secret-to-sink rule: identifier segments naming key material (split
    # on "_"; an identifier matches when any segment is in the lexicon)
    secret_lexicon: tuple = (
        "seed",
        "seeds",
        "cw",
        "cws",
        "cwf",
        "cwv",
        "delta",
        "label",
        "labels",
        "triples",
        "mac",
        "secret",
    )
    sink_calls: tuple = ("emit", "print")
    # bare-print rule: applies under print_scope minus print_allowed
    print_scope: tuple = ("fuzzyheavyhitters_tpu",)
    print_allowed: tuple = (
        "fuzzyheavyhitters_tpu/workloads/ride_austin_visualization.py",
        "fuzzyheavyhitters_tpu/workloads/covid_data_visualization.py",
        # the linter's own CLI: stdout IS its program-output channel
        "fuzzyheavyhitters_tpu/analysis/cli.py",
        # `ops top` live screen: stdout IS the rendered view
        "fuzzyheavyhitters_tpu/obs/ops.py",
    )
    # unguarded-shared-state rule: modules whose module-level mutables
    # must only be written under a registered lock
    shared_state_modules: tuple = (
        "fuzzyheavyhitters_tpu/obs",
        "fuzzyheavyhitters_tpu/native",
        "fuzzyheavyhitters_tpu/protocol/rpc.py",
    )
    # unbounded-await rule: transport modules where every await on a
    # network read / event wait / dial must carry a timeout or deadline
    # (parallel/ included: mesh-transport awaits are transport awaits)
    await_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/resilience",
        "fuzzyheavyhitters_tpu/parallel",
    )
    # chunked-device-readback rule: secure-kernel hot roots where a loop
    # of per-chunk device readbacks (incl. the sanctioned _fetch helper)
    # must never grow back — the whole-level batching this repo's
    # secure path rests on.  parallel/ (both mesh paths: a readback loop
    # there fetches once per SHARD) and protocol/rpc.py (the crawl
    # verbs' expand/open stages) joined the scope with the multi-chip
    # refactor.
    # protocol/sketch.py + protocol/mpc.py joined with the fused
    # malicious verify: the old chunked sketch_batch_size loop form
    # (per-chunk cor/out fetches) is exactly what this rule exists to
    # keep from growing back.
    readback_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol/secure.py",
        "fuzzyheavyhitters_tpu/protocol/rpc.py",
        "fuzzyheavyhitters_tpu/protocol/sketch.py",
        "fuzzyheavyhitters_tpu/protocol/mpc.py",
        "fuzzyheavyhitters_tpu/ops",
        "fuzzyheavyhitters_tpu/parallel",
    )
    # unbounded-queue rule: ingest/transport modules where every
    # producer/consumer buffer (asyncio.Queue, deque) must carry a
    # maxsize/maxlen bound — the overload-never-OOMs invariant of the
    # streaming front door
    queue_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/resilience",
    )
    # span-discipline rule: modules where obs spans must be context
    # managers and emit()/observe() telemetry must stay out of
    # jit-traced bodies (the obs layer itself + its heaviest consumers)
    span_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/obs",
        "fuzzyheavyhitters_tpu/parallel",
    )
    # metric-naming rule: modules where registry metric names (literal
    # first args of the metric_calls methods) must be valid Prometheus
    # identifier chunks — lowercase ``[a-z][a-z0-9_]*`` with optional
    # ``:sub`` parts (the exporter folds a colon into a ``key`` label) —
    # and where a full ``fhh_...`` exported-series literal must end with
    # a recognized unit suffix (Prometheus consumers key on the unit
    # token; obs/exporter.py appends _total/_seconds itself, so only
    # hand-rolled exposition literals need the suffix spelled out)
    metric_modules: tuple = (
        "fuzzyheavyhitters_tpu/obs",
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/parallel",
        "tests",
    )
    metric_calls: tuple = ("count", "gauge", "observe", "timer_add")
    metric_unit_suffixes: tuple = (
        "_total",
        "_seconds",
        "_bytes",
        "_bucket",
        "_sum",
        "_count",
        "_info",
        "_ratio",
        "_keys",
        "_shards",
        "_entries",
        "_epoch",
        "_active",
    )
    # fhh-race rules (analysis/concurrency.py): modules whose asyncio
    # lock discipline is analyzed interprocedurally — the server verb
    # plane, the driver/ingest plane, and the threading-locked obs/
    # native/compile-cache globals the guard annotations live in
    race_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/resilience",
        "fuzzyheavyhitters_tpu/obs",
        "fuzzyheavyhitters_tpu/native",
        "fuzzyheavyhitters_tpu/utils/compile_cache.py",
    )
    # fhh-race guard map: "ClassName.attr" -> owning lock attribute.
    # The operative copy lives in pyproject [tool.fhh-lint.guards]; the
    # runtime twin maps (rpc._SERVER_GUARDS, leader_rpc._INGEST_GUARDS)
    # are drift-tested against it in tests/test_concurrency.py.
    guards: dict = field(
        default_factory=lambda: dict(_DEFAULT_GUARDS)
    )
    # fhh-taint rules (analysis/taint.py): modules whose secret flows
    # are analyzed interprocedurally — the protocol planes where the
    # sources live, and the obs plane where the sinks live
    taint_modules: tuple = (
        "fuzzyheavyhitters_tpu/protocol",
        "fuzzyheavyhitters_tpu/ops",
        "fuzzyheavyhitters_tpu/parallel",
        "fuzzyheavyhitters_tpu/obs",
    )
    # fhh-taint source table: "Class.attr" -> attribute-read sources,
    # "fn" -> call-return sources.  Operative copy: pyproject
    # [tool.fhh-lint.taint]; runtime twin: utils/taint_guard.
    taint: dict = field(
        default_factory=lambda: dict(_DEFAULT_TAINT)
    )
    # sink boundaries: the obs emit/trace/alert/metric call names taint
    # must never reach (exception messages are always sinks)
    taint_sinks: tuple = (
        "emit",
        "print",
        "instant",
        "span",
        "call_event",
        "fire",
        "_fire",
        "count",
        "gauge",
        "observe",
        "timer_add",
    )
    # wire boundaries for unmasked-wire: the frame-send entry points
    taint_wire_calls: tuple = ("_send", "_dp_send")
    # declared declassifiers: masking/opening operations whose output
    # is public by protocol argument — pad-XOR encryptions, share
    # openings, one-way commitments.  `declassified(reason)` contracts
    # must name one of these (and the analyzer checks it is called).
    taint_declassifiers: tuple = (
        "ot2s_encrypt",
        "ot2s_encrypt_packed",
        "ot4_encrypt",
        "b2a_encrypt",
        "ev_open_ot4",
        "ev_open_level",
        "ev_open_fused",
        "window_root",
        "np_add",
    )
    severity_overrides: dict = field(default_factory=dict)
    baseline: str = "lint_baseline.json"
    default_paths: tuple = ("fuzzyheavyhitters_tpu", "tests")


_KV_RE = re.compile(r"^\s*([A-Za-z0-9_\-.\"']+)\s*=\s*(.+?)\s*$")
_HDR_RE = re.compile(r"^\s*\[([^\]]+)\]\s*$")


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment, respecting string quotes."""
    out = []
    quote = None
    for ch in line:
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            break
        out.append(ch)
    return "".join(out)


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("["):
        inner = raw[1:-1] if raw.endswith("]") else raw[1:]
        items = []
        for part in inner.split(","):
            part = part.strip()
            if len(part) >= 2 and part[0] in "\"'" and part[-1] == part[0]:
                items.append(part[1:-1])
        return items
    if len(raw) >= 2 and raw[0] in "\"'" and raw[-1] == raw[0]:
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        return raw


def _read_toml_subset(path: str) -> dict:
    """pyproject.toml -> nested dict of the subset described above."""
    root: dict = {}
    table = root
    pending_key = None
    pending_buf: list[str] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = _strip_comment(line).rstrip()
            if pending_key is not None:
                pending_buf.append(line)
                if line.strip().endswith("]"):
                    table[pending_key] = _parse_value(" ".join(pending_buf))
                    pending_key, pending_buf = None, []
                continue
            if not line.strip():
                continue
            hdr = _HDR_RE.match(line)
            if hdr:
                table = root
                for part in hdr.group(1).split("."):
                    part = part.strip().strip("\"'")
                    table = table.setdefault(part, {})
                continue
            kv = _KV_RE.match(line)
            if not kv:
                continue
            key = kv.group(1).strip("\"'")
            raw = kv.group(2)
            if raw.startswith("[") and not raw.endswith("]"):
                pending_key, pending_buf = key, [raw]
                continue
            table[key] = _parse_value(raw)
    return root


def find_repo_root(start: str | None = None) -> str:
    """Nearest ancestor holding pyproject.toml (else ``start`` itself)."""
    cur = os.path.abspath(start or os.getcwd())
    probe = cur
    while True:
        if os.path.exists(os.path.join(probe, "pyproject.toml")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return cur
        probe = parent


def load_config(root: str | None = None, pyproject: str | None = None) -> LintConfig:
    """Config from ``[tool.fhh-lint]`` merged over the defaults."""
    cfg = LintConfig()
    if pyproject is None:
        if root is None:
            root = find_repo_root()
        pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return cfg
    try:
        doc = _read_toml_subset(pyproject)
    except OSError:
        return cfg
    section = doc.get("tool", {}).get("fhh-lint", {})
    if not isinstance(section, dict):
        return cfg
    for key in (
        "hot_modules",
        "hot_roots",
        "secret_lexicon",
        "sink_calls",
        "print_scope",
        "print_allowed",
        "shared_state_modules",
        "await_modules",
        "readback_modules",
        "queue_modules",
        "span_modules",
        "metric_modules",
        "metric_calls",
        "metric_unit_suffixes",
        "race_modules",
        "taint_modules",
        "taint_sinks",
        "taint_wire_calls",
        "taint_declassifiers",
        "default_paths",
    ):
        val = section.get(key)
        if isinstance(val, list):
            setattr(cfg, key, tuple(val))
    if isinstance(section.get("baseline"), str):
        cfg.baseline = section["baseline"]
    guards = section.get("guards")
    if isinstance(guards, dict):
        # the table REPLACES the default map (a merge could never retire
        # a default binding from pyproject alone)
        cfg.guards = {
            k: v
            for k, v in guards.items()
            if isinstance(k, str) and isinstance(v, str)
        }
    taint = section.get("taint")
    if isinstance(taint, dict):
        # same table-replaces-default semantics as guards: retiring a
        # source declaration must be possible from pyproject alone
        cfg.taint = {
            k: v
            for k, v in taint.items()
            if isinstance(k, str) and isinstance(v, str)
        }
    sev = section.get("severity")
    if isinstance(sev, dict):
        cfg.severity_overrides = {
            k: v for k, v in sev.items() if isinstance(v, str)
        }
    return cfg
