"""fhh-lint: AST-based static analysis for this codebase's invariants.

A dependency-free lint framework (``ast`` + ``tokenize`` only — it never
imports JAX or the modules under lint) enforcing the three invariant
families reviewer vigilance kept missing:

- **trace-safety / performance** — no host synchronization on the
  per-level crawl path, no per-call jit wrapper churn
  (``host-sync-in-hot-loop``, ``recompile-churn``);
- **secret hygiene** — seeds, correction words, GC labels, and MAC keys
  never flow into logs, metrics, stdout, or exception messages
  (``secret-to-sink``);
- **thread safety + failure honesty** — module-level shared state only
  written under its registered lock; no silent catch-alls; no bare
  print telemetry (``unguarded-shared-state``, ``broad-except``,
  ``bare-print``);
- **asyncio lock discipline** — the interprocedural fhh-race pair
  (:mod:`.concurrency`): guard-mapped shared attributes accessed only
  with their owning lock provably held, and no guarded snapshot used
  across a suspension point the lock did not cover
  (``guarded-state-unlocked``, ``stale-read-across-await``), validated
  at runtime by the ``FHH_DEBUG_GUARDS`` sanitizer
  (:mod:`fuzzyheavyhitters_tpu.utils.guards`).

Usage::

    python -m fuzzyheavyhitters_tpu.analysis [paths] \
        [--format human|json] [--strict] [--update-baseline]

Inline suppression: ``# fhh-lint: disable=<rule>[,<rule>]`` on the
offending line (or alone on the line above).  Grandfathered findings
live in ``lint_baseline.json`` as per-(rule, file) counts that must not
grow; see :mod:`.baseline`.  Config: ``[tool.fhh-lint]`` in
pyproject.toml (:mod:`.config`).
"""

from .baseline import apply_baseline, load_baseline, write_baseline
from .config import LintConfig, find_repo_root, load_config
from .engine import Finding, Rule, SourceModule, lint_paths, lint_source
from .rules import ALL_RULES, RULES_BY_NAME

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintConfig",
    "Rule",
    "RULES_BY_NAME",
    "SourceModule",
    "apply_baseline",
    "find_repo_root",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "load_config",
    "write_baseline",
]
