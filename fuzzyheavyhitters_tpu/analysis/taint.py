"""fhh-taint: interprocedural secret-flow analysis for the obs plane.

The protocol's entire point is that neither server (nor any scrape of
its `/metrics` plane, trace JSONL, alert log, or run report) ever sees
key material in the clear — yet every sink the obs layer grew is a
string builder, and the lexical ``secret-to-sink`` rule can only catch
a secret NAMED at the sink.  It cannot see a seed flow through an
assignment, a helper return, a dict, or an f-string first.  This pass
can: it seeds taint at *declared sources* and pushes it forward through
the module until it reaches a sink, a branch, or the wire.

Sources (the ``[tool.fhh-lint.taint]`` table in pyproject.toml, mirrored
by :data:`fuzzyheavyhitters_tpu.analysis.config._DEFAULT_TAINT` and the
runtime twin ``utils/taint_guard._DEFAULT_SOURCES`` — drift-tested):

- ``"ClassName.attr" = "description"`` — any attribute READ of that
  name taints (receiver-agnostic: ``cs._sec_seed`` matches the
  ``CollectionSession._sec_seed`` entry without type inference; the
  attr names are chosen distinctive for exactly this reason).
- ``"function_name" = "description"`` (no dot) — any call whose last
  segment matches returns tainted (``secure.derive_seed(...)`` matches
  a ``derive_seed`` entry, cross-module).
- inline ``# fhh-taint: source`` — on an assignment, taints its
  targets; on a ``def``, declares the function's return a source.

Propagation: assignments, augmented ops, f-strings / ``format`` /
concat, container literals and subscripts, comprehensions, method calls
on tainted receivers, a fixed set of propagating builtins
(``str``/``bytes``/``np.asarray``/...), and function calls/returns —
per-function summaries (return taint, params-that-reach-a-sink,
params-that-reach-the-wire, params-branched-on) computed to a fixpoint
over the per-module call graph, so a seed that travels through two
helper returns into an f-string into ``emit`` is still one finding.

The three rule families:

- ``secret-to-sink-flow`` — taint reaching a sink call
  (``taint_sinks``: emit / trace / alert / metric label+value) or an
  exception message (``raise`` crosses trust boundaries: RPC error
  frames, logs).  Supersedes lexical ``secret-to-sink`` in
  ``taint_modules`` (which stays on everywhere as a fast pre-filter;
  a subset test pins the supersession).
- ``secret-branch`` — ``if``/``while``/``assert``/ternary conditioned
  on a secret-derived host value: the timing-channel shape MPC code
  must never have.  ``x is None`` / ``is not None`` tests are carved
  out (presence is not content); value comparisons are not.
- ``unmasked-wire`` — taint reaching a ``taint_wire_calls`` frame send
  (``_send`` / ``_dp_send``) without passing a declared declassifier.

Declassifiers (``taint_declassifiers``): the masking/opening operations
whose OUTPUT is public by protocol argument — pad-XOR encryptions,
share openings, one-way window-root commitments.  A call to one clears
taint.  Hashing clears taint structurally (``hashlib.sha256(secret)``
is an unresolved non-propagating call), which is the right semantics:
a digest is a commitment, and the digests that ARE secrets (the
transcript ratchet) are re-declared as sources at their constructors.

Sanctioned flows carry ``# fhh-taint: declassified(reason)`` — a
CHECKED contract, not a suppression: the reason must name a declared
declassifier, and the analyzer verifies that operation is actually
called in the enclosing function, so the justification cannot rot when
the masking step is refactored away (PR-9's ``atomic`` precedent).

Approximations, by design (a linter, not an information-flow prover):
call resolution is name-based within one module (``self.m()`` / bare
``f()``; anything else is unresolved), unresolved calls return
UNtainted unless a propagating builtin or a declared source — the
conservative-for-noise direction, chosen so the repo can be held at
baseline ZERO — and metadata reads (``.shape``/``.dtype``/``len()``)
are public.  The runtime twin (:mod:`fuzzyheavyhitters_tpu.utils.
taint_guard`, ``FHH_DEBUG_TAINT=1``) closes the gap dynamically:
registered source buffers are byte-compared at every obs sink under
the e2e + chaos suites.
"""

from __future__ import annotations

import ast
import re

from .concurrency import _annotation_lines
from .engine import Rule, SourceModule, dotted_name, last_segment

# annotation grammar (fhh-lint placement rules: on the line itself, or
# standing alone on the line above the code it binds to)
_SOURCE_RE = re.compile(r"#\s*fhh-taint:\s*source\b")
_DECLASS_RE = re.compile(r"#\s*fhh-taint:\s*declassified\(([^)]*)\)")

# attribute reads that expose only public metadata of a secret array
_METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "name",
}

# builtins / ubiquitous array constructors whose result carries its
# arguments' bytes (str(seed) in an f-string, np.asarray(seed), ...)
_PROPAGATING_CALLS = {
    "str", "repr", "bytes", "bytearray", "format", "hex", "oct", "bin",
    "int", "float", "bool", "list", "tuple", "set", "dict", "sorted",
    "reversed", "abs", "sum", "min", "max", "next", "iter", "zip",
    "copy", "deepcopy",
    "asarray", "array", "ascontiguousarray", "frombuffer", "stack",
    "concatenate", "copyto", "tobytes", "astype", "reshape",
}

_CTOR_FNS = ("__init__", "__post_init__")


def _in_scope(mod: SourceModule, cfg) -> bool:
    prefixes = getattr(cfg, "taint_modules", ())
    return any(
        mod.relpath == p or mod.relpath.startswith(p.rstrip("/") + "/")
        for p in prefixes
    )


def _span(node: ast.AST):
    return node.lineno, getattr(node, "end_lineno", node.lineno)


def _source_tables(cfg):
    """(attr sources {attr: label}, fn-return sources {name: label})
    from the config taint table.  Dotted keys bind attribute reads by
    the attr segment; dotless keys bind call returns by last segment."""
    attrs: dict[str, str] = {}
    fns: dict[str, str] = {}
    for key, desc in getattr(cfg, "taint", {}).items():
        if not isinstance(key, str):
            continue
        if "." in key:
            attrs[key.rsplit(".", 1)[1]] = key
        else:
            fns[key] = key + "()"
    return attrs, fns


class _TFn:
    """One function/method in the module + its flow summary."""

    __slots__ = (
        "node", "qual", "cls", "params", "declared_source",
        "ret", "sink_params", "wire_params", "branch_params",
    )

    def __init__(self, node, qual, cls, declared_source):
        self.node = node
        self.qual = qual
        self.cls = cls
        self.params = [a.arg for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        )]
        self.declared_source = declared_source
        # summary: return-value origins (source labels + param indices),
        # and the param positions that reach a sink / the wire / a
        # branch inside this function (transitively).  The reason dicts
        # keep the FIRST discovered path per param (monotone: fixpoint
        # converges even through call-graph cycles).
        self.ret: frozenset = frozenset()
        self.sink_params: dict[int, str] = {}
        self.wire_params: dict[int, str] = {}
        self.branch_params: dict[int, int] = {}

    def summary(self):
        return (
            self.ret,
            tuple(sorted(self.sink_params)),
            tuple(sorted(self.wire_params)),
            tuple(sorted(self.branch_params)),
        )


class _TaintInfo:
    __slots__ = (
        "fns", "fn_of_node", "attr_sources", "fn_sources",
        "source_lines", "declass_lines", "findings",
    )


def _is_secret(origins) -> bool:
    """True when the origin set contains a declared source (vs only
    param positions, which matter to summaries, not findings)."""
    return any(isinstance(o, str) for o in origins)


def _secret_label(origins) -> str:
    labels = sorted(o for o in origins if isinstance(o, str))
    return labels[0] if labels else "?"


def _param_origins(origins):
    return [o for o in origins if isinstance(o, int)]


class _FnWalker:
    """Execution-ordered walk of one function (or the module body):
    evaluates expression taint, applies callee summaries, and records
    sink/wire/branch hits.  ``collect`` toggles finding emission (off
    during fixpoint passes, on for the final reporting pass)."""

    def __init__(self, info: _TaintInfo, cfg, fn: _TFn | None, collect: bool):
        self.info = info
        self.cfg = cfg
        self.fn = fn
        self.collect = collect
        self.env: dict[str, frozenset] = {}
        self.ret: set = set()
        self.sink_params: dict[int, str] = {}
        self.wire_params: dict[int, str] = {}
        self.branch_params: dict[int, int] = {}
        self.findings: list = []
        self.sinks = set(getattr(cfg, "taint_sinks", ()))
        self.wires = set(getattr(cfg, "taint_wire_calls", ()))
        self.declass = set(getattr(cfg, "taint_declassifiers", ()))
        if fn is not None:
            for i, name in enumerate(fn.params):
                if name in ("self", "cls"):
                    continue
                self.env[name] = frozenset((i,))

    # -- findings ---------------------------------------------------------

    def _report(self, kind: str, node: ast.AST, message: str):
        if self.collect:
            self.findings.append((kind, *_span(node), message))

    def _sink_hit(self, node, origins, sink_desc: str, kind: str):
        """Taint arrived at a sink/wire boundary: a declared-source
        origin is a finding here; a param origin becomes part of this
        function's summary (the finding then surfaces at the call
        site that feeds it a secret)."""
        if _is_secret(origins):
            if kind == "unmasked-wire":
                self._report(
                    kind, node,
                    f"value derived from declared source "
                    f"'{_secret_label(origins)}' reaches the wire via "
                    f"{sink_desc} without passing a declared "
                    "declassifier (pad-XOR / share-opening / "
                    "window-root commitment) — mask or open it first, "
                    "or annotate the sanctioned path with "
                    "`# fhh-taint: declassified(<op>)`",
                )
            else:
                self._report(
                    kind, node,
                    f"value derived from declared source "
                    f"'{_secret_label(origins)}' flows into "
                    f"{sink_desc} — key material must never reach "
                    "logs, metrics, traces, alerts, or exception "
                    "messages",
                )
        store = self.sink_params if kind == "secret-to-sink-flow" else (
            self.wire_params
        )
        for p in _param_origins(origins):
            store.setdefault(p, sink_desc)

    def _branch_hit(self, node, origins, what: str):
        if _is_secret(origins):
            self._report(
                "secret-branch", node,
                f"{what} conditioned on a value derived from declared "
                f"source '{_secret_label(origins)}' — a host branch on "
                "secret data is a timing channel (and forces a device "
                "sync); compute both paths data-parallel, or annotate "
                "a protocol-sanctioned opening with "
                "`# fhh-taint: declassified(<op>)`",
            )
        for p in _param_origins(origins):
            self.branch_params.setdefault(p, node.lineno)

    # -- expression evaluation -------------------------------------------

    def ev(self, node) -> frozenset:
        if node is None or isinstance(node, ast.Constant):
            return frozenset()
        if isinstance(node, ast.Name):
            return self.env.get(node.id, frozenset())
        if isinstance(node, ast.Attribute):
            label = None
            if isinstance(node.ctx, ast.Load):
                label = self.info.attr_sources.get(node.attr)
            if label is not None:
                return frozenset((label,))
            base = self.ev(node.value)
            if node.attr in _METADATA_ATTRS:
                return frozenset()
            return base
        if isinstance(node, ast.Call):
            return self._ev_call(node)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` expose presence, not
            # content — the standard guard shape all over the verb
            # plane must not read as a timing channel
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                for c in [node.left, *node.comparators]:
                    self.ev(c)
                return frozenset()
            out = self.ev(node.left)
            for c in node.comparators:
                out |= self.ev(c)
            return out
        if isinstance(node, (ast.BinOp,)):
            return self.ev(node.left) | self.ev(node.right)
        if isinstance(node, ast.BoolOp):
            out = frozenset()
            for v in node.values:
                out |= self.ev(v)
            return out
        if isinstance(node, ast.UnaryOp):
            return self.ev(node.operand)
        if isinstance(node, ast.IfExp):
            self._branch_hit(node, self.ev(node.test), "a ternary")
            return self.ev(node.body) | self.ev(node.orelse)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            out = frozenset()
            for e in node.elts:
                out |= self.ev(e)
            return out
        if isinstance(node, ast.Dict):
            out = frozenset()
            for k in node.keys:
                if k is not None:
                    out |= self.ev(k)
            for v in node.values:
                out |= self.ev(v)
            return out
        if isinstance(node, ast.Subscript):
            return self.ev(node.value) | self.ev(node.slice)
        if isinstance(node, ast.Slice):
            return (
                self.ev(node.lower) | self.ev(node.upper) | self.ev(node.step)
            )
        if isinstance(node, ast.JoinedStr):
            out = frozenset()
            for v in node.values:
                out |= self.ev(v)
            return out
        if isinstance(node, ast.FormattedValue):
            out = self.ev(node.value)
            if node.format_spec is not None:
                out |= self.ev(node.format_spec)
            return out
        if isinstance(node, ast.Starred):
            return self.ev(node.value)
        if isinstance(node, ast.Await):
            return self.ev(node.value)
        if isinstance(node, ast.NamedExpr):
            origins = self.ev(node.value)
            if isinstance(node.target, ast.Name):
                self.env[node.target.id] = origins
            return origins
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return self._ev_comp(node)
        if isinstance(node, ast.Lambda):
            return frozenset()  # deferred body: out of linear order
        # conservative default: union over child expressions
        out = frozenset()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                out |= self.ev(child)
        return out

    def _ev_comp(self, node) -> frozenset:
        saved = dict(self.env)
        for gen in node.generators:
            src = self.ev(gen.iter)
            for t in ast.walk(gen.target):
                if isinstance(t, ast.Name):
                    self.env[t.id] = src
            for cond in gen.ifs:
                self._branch_hit(
                    node, self.ev(cond), "a comprehension filter"
                )
        if isinstance(node, ast.DictComp):
            out = self.ev(node.key) | self.ev(node.value)
        else:
            out = self.ev(node.elt)
        self.env = saved
        return out

    def _resolve(self, call: ast.Call) -> _TFn | None:
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
            and self.fn is not None
            and self.fn.cls is not None
        ):
            return self.info.fns.get(f"{self.fn.cls}.{f.attr}")
        if isinstance(f, ast.Name):
            return self.info.fns.get(f.id)
        return None

    def _ev_call(self, call: ast.Call) -> frozenset:
        seg = last_segment(dotted_name(call.func)) or ""
        arg_origins = [self.ev(a) for a in call.args]
        kw_origins = {kw.arg: self.ev(kw.value) for kw in call.keywords}
        all_args = frozenset().union(*arg_origins, *kw_origins.values()) \
            if (arg_origins or kw_origins) else frozenset()
        recv = frozenset()
        if isinstance(call.func, ast.Attribute):
            recv = self.ev(call.func.value)

        # sink / wire boundaries check every argument (and, for a method
        # sink like `log.emit(...)`, a tainted receiver is immaterial)
        if seg in self.sinks:
            for a, o in zip(call.args, arg_origins):
                self._sink_hit(a, o, f"sink '{seg}'", "secret-to-sink-flow")
            for kw in call.keywords:
                self._sink_hit(
                    kw.value, kw_origins[kw.arg], f"sink '{seg}'",
                    "secret-to-sink-flow",
                )
        if seg in self.wires:
            for a, o in zip(call.args, arg_origins):
                self._sink_hit(a, o, f"'{seg}'", "unmasked-wire")
            for kw in call.keywords:
                self._sink_hit(
                    kw.value, kw_origins[kw.arg], f"'{seg}'", "unmasked-wire",
                )

        # declassifiers clear taint: their output is public by protocol
        # argument (pad-XOR ciphertext, opened share, commitment)
        if seg in self.declass:
            return frozenset()

        callee = self._resolve(call)
        if callee is not None:
            self._apply_callee_boundaries(call, callee, arg_origins, kw_origins)

        # declared function-return sources taint unconditionally
        label = self.info.fn_sources.get(seg)
        if label is None and callee is not None and callee.declared_source:
            label = f"{callee.qual}()"
        if label is not None:
            return frozenset((label,))

        if callee is not None:
            out = set(o for o in callee.ret if isinstance(o, str))
            offset = 1 if (
                callee.cls is not None
                and isinstance(call.func, ast.Attribute)
            ) else 0
            for o in callee.ret:
                if not isinstance(o, int):
                    continue
                pos = o - offset
                if 0 <= pos < len(arg_origins):
                    out |= arg_origins[pos]
                elif o < len(callee.params):
                    name = callee.params[o]
                    if name in kw_origins:
                        out |= kw_origins[name]
            return frozenset(out)

        if seg in _PROPAGATING_CALLS:
            return all_args | recv
        if isinstance(call.func, ast.Attribute):
            # unresolved method on a tainted receiver: `seed.copy()`,
            # `digest.hex()` — the result carries the receiver's bytes.
            # (`h.digest()` on an UNtainted hasher stays clean: hashing
            # is structural declassification, see module docstring.)
            return recv
        return frozenset()  # unresolved bare call: untainted by design

    def _apply_callee_boundaries(self, call, callee, arg_origins, kw_origins):
        """Surface the callee's sink/wire/branch summary at this call
        site: an argument that the callee leaks is a leak HERE."""
        offset = 1 if (
            callee.cls is not None and isinstance(call.func, ast.Attribute)
        ) else 0

        def each_bound_arg():
            for pos, (a, o) in enumerate(zip(call.args, arg_origins)):
                yield pos + offset, a, o
            for kw in call.keywords:
                if kw.arg in callee.params:
                    yield callee.params.index(kw.arg), kw.value, \
                        kw_origins[kw.arg]

        for idx, node, origins in each_bound_arg():
            if idx in callee.sink_params:
                self._sink_hit(
                    node, origins,
                    f"{callee.sink_params[idx]} via '{callee.qual}'",
                    "secret-to-sink-flow",
                )
            if idx in callee.wire_params:
                self._sink_hit(
                    node, origins,
                    f"{callee.wire_params[idx]} via '{callee.qual}'",
                    "unmasked-wire",
                )
            if idx in callee.branch_params and _is_secret(origins):
                self._report(
                    "secret-branch", node,
                    f"value derived from declared source "
                    f"'{_secret_label(origins)}' flows into "
                    f"'{callee.qual}', which branches on it "
                    f"(line {callee.branch_params[idx]}) — a host "
                    "branch on secret data is a timing channel",
                )

    # -- statement walk ---------------------------------------------------

    def _bind(self, target, origins):
        if isinstance(target, ast.Name):
            self.env[target.id] = origins
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, origins)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, origins)
        elif isinstance(target, ast.Subscript):
            # weak update: d[k] = secret taints the container
            self.ev(target.slice)
            base = target.value
            if isinstance(base, ast.Name):
                self.env[base.id] = self.env.get(
                    base.id, frozenset()
                ) | origins
        # attribute stores are declarative (the source table binds them)

    def _bind_precise(self, target, value_node, origins):
        """Element-wise binding for `a, b = x, y` tuple-literal RHS."""
        if (
            isinstance(target, (ast.Tuple, ast.List))
            and isinstance(value_node, (ast.Tuple, ast.List))
            and len(target.elts) == len(value_node.elts)
            and not any(isinstance(e, ast.Starred) for e in target.elts)
        ):
            for t, v in zip(target.elts, value_node.elts):
                self._bind(t, self.ev(v))
        else:
            self._bind(target, origins)

    def stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.env[s.name] = frozenset()  # analyzed as its own _TFn
            return
        if isinstance(s, ast.ClassDef):
            return
        if isinstance(s, (ast.Assign, ast.AnnAssign)):
            origins = self.ev(s.value)
            if s.lineno in self.info.source_lines:
                origins = origins | frozenset(
                    (f"inline fhh-taint source (line {s.lineno})",)
                )
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            for t in targets:
                self._bind_precise(t, s.value, origins)
            return
        if isinstance(s, ast.AugAssign):
            origins = self.ev(s.value)
            if isinstance(s.target, ast.Name):
                self.env[s.target.id] = self.env.get(
                    s.target.id, frozenset()
                ) | origins
            else:
                self._bind(s.target, origins)
            return
        if isinstance(s, ast.Return):
            self.ret |= self.ev(s.value)
            return
        if isinstance(s, ast.Expr):
            v = s.value
            if isinstance(v, (ast.Yield, ast.YieldFrom)):
                self.ret |= self.ev(v.value)
            else:
                self.ev(v)
            return
        if isinstance(s, ast.Raise):
            if s.exc is not None:
                if isinstance(s.exc, ast.Call):
                    # `raise Err(f"... {x}")`: the constructor is an
                    # unresolved call (drops taint by design), but its
                    # arguments ARE the exception message
                    origins = frozenset()
                    for a in s.exc.args:
                        node = a.value if isinstance(a, ast.Starred) else a
                        origins |= self.ev(node)
                    for kw in s.exc.keywords:
                        origins |= self.ev(kw.value)
                else:
                    origins = self.ev(s.exc)
                self._sink_hit(
                    s, origins, "an exception message", "secret-to-sink-flow"
                )
            return
        if isinstance(s, ast.Assert):
            self._branch_hit(s, self.ev(s.test), "an assert")
            if s.msg is not None:
                self._sink_hit(
                    s, self.ev(s.msg), "an assert message",
                    "secret-to-sink-flow",
                )
            return
        if isinstance(s, ast.If):
            self._branch_hit(s, self.ev(s.test), "a branch")
            env0 = dict(self.env)
            for b in s.body:
                self.stmt(b)
            env_body = self.env
            self.env = env0
            for b in s.orelse:
                self.stmt(b)
            for k, v in env_body.items():  # may-taint join of both arms
                self.env[k] = self.env.get(k, frozenset()) | v
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._bind(s.target, self.ev(s.iter))
            # two passes: taint bound late in the body reaches a use
            # early in the body on the next iteration
            for _ in range(2):
                for b in s.body:
                    self.stmt(b)
            for b in s.orelse:
                self.stmt(b)
            return
        if isinstance(s, ast.While):
            self._branch_hit(s, self.ev(s.test), "a loop condition")
            for _ in range(2):
                for b in s.body:
                    self.stmt(b)
                self._branch_hit(s, self.ev(s.test), "a loop condition")
            for b in s.orelse:
                self.stmt(b)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                origins = self.ev(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, origins)
            for b in s.body:
                self.stmt(b)
            return
        if isinstance(s, ast.Try):
            for b in s.body:
                self.stmt(b)
            for h in s.handlers:
                if h.name:
                    self.env[h.name] = frozenset()
                for b in h.body:
                    self.stmt(b)
            for b in s.orelse + s.finalbody:
                self.stmt(b)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
            return
        # generic fallback (Match, Global, Import, ...): evaluate child
        # expressions, walk child statements
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self.ev(child)
            elif isinstance(child, ast.stmt):
                self.stmt(child)
            elif hasattr(child, "body"):  # match_case and friends
                for b in getattr(child, "body", ()):
                    if isinstance(b, ast.stmt):
                        self.stmt(b)


def _walk_fn(info, cfg, fn: _TFn, collect: bool):
    w = _FnWalker(info, cfg, fn, collect)
    for s in fn.node.body:
        w.stmt(s)
    return w


def analyze(mod: SourceModule, cfg) -> _TaintInfo:
    """Build (and cache on ``mod``) the module's taint state: function
    table, summary fixpoint over the call graph, and the final findings
    list (pre-contract filtering — the rules apply ``declassified``)."""
    cached = getattr(mod, "_fhh_taint_info", None)
    if cached is not None and cached[0] is cfg:
        return cached[1]
    info = _TaintInfo()
    info.attr_sources, info.fn_sources = _source_tables(cfg)
    info.source_lines = set(_annotation_lines(mod.text, _SOURCE_RE))
    info.declass_lines = {
        line: [g[0].strip() for g in groups]
        for line, groups in _annotation_lines(mod.text, _DECLASS_RE).items()
    }

    class_of_fn: dict[int, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of_fn[id(child)] = node.name

    fns: dict[str, _TFn] = {}
    fn_of_node: dict[int, _TFn] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = class_of_fn.get(id(node))
        qual = f"{cls}.{node.name}" if cls else node.name
        fn = _TFn(node, qual, cls, node.lineno in info.source_lines)
        fns.setdefault(qual, fn)  # first definition wins (documented)
        fn_of_node[id(node)] = fns[qual]
    info.fns = fns
    info.fn_of_node = fn_of_node

    # summary fixpoint: iterate until no function's summary moves (the
    # reason dicts are first-wins, the origin sets grow monotonically,
    # so this converges; the bound is a safety valve, not a truncation)
    for _ in range(max(4, len(fns))):
        changed = False
        for fn in fns.values():
            w = _walk_fn(info, cfg, fn, collect=False)
            before = fn.summary()
            fn.ret = frozenset(w.ret)
            for store, new in (
                (fn.sink_params, w.sink_params),
                (fn.wire_params, w.wire_params),
                (fn.branch_params, w.branch_params),
            ):
                for k, v in new.items():
                    store.setdefault(k, v)
            if fn.summary() != before:
                changed = True
        if not changed:
            break

    # final reporting pass (functions + module top level)
    findings: list = []
    for fn in fns.values():
        findings.extend(_walk_fn(info, cfg, fn, collect=True).findings)
    top = _FnWalker(info, cfg, None, collect=True)
    for s in mod.tree.body:
        top.stmt(s)
    findings.extend(top.findings)
    info.findings = findings

    mod._fhh_taint_info = (cfg, info)
    return info


def _enclosing_fn_node(mod: SourceModule, line: int):
    """Deepest function whose span contains ``line`` (None: module)."""
    best = None
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lo, hi = node.lineno, getattr(node, "end_lineno", node.lineno)
            if lo <= line <= hi and (
                best is None or lo > best.lineno
            ):
                best = node
    return best


def _contract_verified(mod: SourceModule, cfg, line: int, reason: str):
    """A ``declassified(reason)`` contract is verified when the reason
    names a declared declassifier AND that operation is actually called
    in the enclosing function (else module) — the written justification
    must point at a masking/opening step that is really on the path."""
    named = [
        d for d in getattr(cfg, "taint_declassifiers", ())
        if re.search(rf"\b{re.escape(d)}\b", reason)
    ]
    if not named:
        return False, (
            "names no declared declassifier (taint_declassifiers)"
        )
    scope = _enclosing_fn_node(mod, line) or mod.tree
    called = {
        last_segment(dotted_name(n.func))
        for n in ast.walk(scope)
        if isinstance(n, ast.Call)
    }
    missing = [d for d in named if d not in called]
    if missing:
        return False, (
            f"names '{missing[0]}' but that operation is never called "
            "in the enclosing function"
        )
    return True, ""


def _declassified_at(mod, cfg, info, lineno, end_lineno):
    """True when a VERIFIED declassified contract covers the span."""
    for line in range(lineno, (end_lineno or lineno) + 1):
        for reason in info.declass_lines.get(line, ()):
            ok, _ = _contract_verified(mod, cfg, line, reason)
            if ok:
                return True
    return False


class _TaintRule(Rule):
    kind: str = ""

    def check(self, mod: SourceModule, cfg):
        if not _in_scope(mod, cfg):
            return
        info = analyze(mod, cfg)
        for kind, lineno, end_lineno, message in info.findings:
            if kind != self.kind:
                continue
            if _declassified_at(mod, cfg, info, lineno, end_lineno):
                continue
            yield lineno, end_lineno, message


class SecretToSinkFlow(_TaintRule):
    """Interprocedural taint reaching an obs sink or exception message.
    Also the contract checker: every ``declassified(reason)`` in scope
    must name a declared declassifier that is really called — an
    unverifiable justification is itself a finding (PR-9's atomic-
    contract precedent: annotations are checked, never trusted)."""

    name = "secret-to-sink-flow"
    default_severity = "error"
    kind = "secret-to-sink-flow"

    def check(self, mod: SourceModule, cfg):
        if not _in_scope(mod, cfg):
            return
        info = analyze(mod, cfg)
        for line, reasons in sorted(info.declass_lines.items()):
            for reason in reasons:
                ok, why = _contract_verified(mod, cfg, line, reason)
                if not ok:
                    yield (
                        line, line,
                        f"`# fhh-taint: declassified({reason})` {why} — "
                        "the justification must name the masking/opening "
                        "operation on the taint path (one of "
                        "taint_declassifiers), and that operation must "
                        "appear in the enclosing function",
                    )
        yield from super().check(mod, cfg)


class SecretBranch(_TaintRule):
    """Host control flow conditioned on secret-derived data — the
    timing-channel shape MPC code must never have."""

    name = "secret-branch"
    default_severity = "error"
    kind = "secret-branch"


class UnmaskedWire(_TaintRule):
    """Taint reaching a frame send without a declared declassifier."""

    name = "unmasked-wire"
    default_severity = "error"
    kind = "unmasked-wire"


TAINT_RULES = (SecretToSinkFlow(), SecretBranch(), UnmaskedWire())
