"""Baseline machinery: grandfathered findings, count-bounded per file.

The baseline is a checked-in JSON document keyed by (rule, path) with a
COUNT, not line numbers — line-keyed baselines rot on every unrelated
edit, while a count expresses exactly the contract the repo wants:
*"this file carries N known findings of this rule; the number must not
grow."*  Shrinking is celebrated (``stale`` entries in the report tell
you to run ``--update-baseline`` and bank the win); growing fails the
lint.

Schema::

    {
      "schema": "fhh-lint-baseline/1",
      "counts": {"<rule>": {"<relpath>": <count>, ...}, ...}
    }

Matching: findings are grouped by (rule, path); the first ``count`` (in
line order) are absorbed, the rest are NEW.  When the group has fewer
findings than its baseline entry, the surplus is reported as stale.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

SCHEMA = "fhh-lint-baseline/1"


@dataclass
class BaselineResult:
    new: list = field(default_factory=list)  # findings not absorbed
    absorbed: int = 0
    stale: list = field(default_factory=list)  # (rule, path, extra) entries


def load_baseline(path: str) -> dict:
    """-> counts dict {rule: {path: count}}; empty when absent."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"unrecognized baseline schema {doc.get('schema')!r} in {path}"
        )
    counts = doc.get("counts", {})
    return {
        rule: {p: int(n) for p, n in paths.items()}
        for rule, paths in counts.items()
    }


def write_baseline(path: str, findings, keep: dict | None = None) -> dict:
    """Rewrite the baseline to the current findings' counts.  ``keep``
    merges in existing entries that must survive the rewrite (the CLI
    passes the counts of files OUTSIDE the scanned path set, so a partial
    ``--update-baseline`` run cannot erase another subtree's grandfathered
    findings)."""
    counts: dict = {}
    for rule, paths in (keep or {}).items():
        for p, n in paths.items():
            if n:
                counts.setdefault(rule, {})[p] = int(n)
    for f in findings:
        counts.setdefault(f.rule, {})
        counts[f.rule][f.path] = counts[f.rule].get(f.path, 0) + 1
    doc = {
        "schema": SCHEMA,
        "counts": {
            rule: dict(sorted(paths.items()))
            for rule, paths in sorted(counts.items())
        },
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def removed_rules(counts: dict, known_rules) -> list:
    """``(rule, files, findings)`` for baseline entries whose rule id no
    longer names a registered rule.  A rule RENAME used to read as a
    silent shrink at ``--update-baseline`` time — the old id's entries
    just vanished from the rewritten file, indistinguishable from a
    genuine burn-down — so the CLI now names every dropped id loudly and
    tells the operator to check the successor id carries its own
    entries."""
    return [
        (rule, len(paths), sum(paths.values()))
        for rule, paths in sorted(counts.items())
        if rule not in known_rules and paths
    ]


def apply_baseline(findings, counts: dict, scanned=None) -> BaselineResult:
    """Split findings into absorbed-vs-new under the baseline counts.

    ``scanned`` (a set of relpaths, when given) bounds the STALE check to
    files this run actually linted — a partial-scope run must not report
    an unscanned subtree's grandfathered entries as burn-down wins."""
    res = BaselineResult()
    groups: dict = {}
    for f in findings:
        groups.setdefault((f.rule, f.path), []).append(f)
    for (rule, path), group in sorted(groups.items()):
        allowed = counts.get(rule, {}).get(path, 0)
        group.sort(key=lambda f: f.line)
        res.absorbed += min(allowed, len(group))
        res.new.extend(group[allowed:])
    for rule, paths in counts.items():
        for path, allowed in paths.items():
            if scanned is not None and path not in scanned:
                continue  # outside this run's scope: no verdict either way
            have = len(groups.get((rule, path), ()))
            if have < allowed:
                res.stale.append((rule, path, allowed - have))
    res.new.sort(key=lambda f: (f.path, f.line, f.rule))
    res.stale.sort()
    return res
