"""fhh-lint engine: parse, suppressions, rule dispatch, severity.

Dependency-free by design (``ast`` + ``tokenize`` only): the linter must
run in CI images, pre-commit hooks, and the tier-1 test host without
importing JAX — importing the package under lint would both slow every
lint run by seconds and make the linter crash on any tree the rules
exist to catch (a module that host-syncs at import time still parses).

The pieces:

- :class:`SourceModule` — one parsed file: AST with parent links,
  inline-suppression table (``# fhh-lint: disable=<rule>[,<rule>...]``
  on the offending line, or standing alone on the line above), and the
  repo-relative path every rule and baseline entry keys on.
- :class:`Rule` — a named check over a :class:`SourceModule` yielding
  ``(lineno, end_lineno, message)`` triples; the engine attaches
  severity (rule default, overridable per rule in config) and drops
  suppressed findings.
- :func:`lint_paths` / :func:`lint_source` — the two entry points
  (files/trees for the CLI and the self-lint test; raw source for the
  rule-fixture tests).

Baseline semantics live in :mod:`.baseline`; the config schema and its
``pyproject.toml`` loader in :mod:`.config`.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass

SEVERITIES = ("warning", "error")

# rule names, comma-separated; anything after (e.g. a parenthesized
# justification — encouraged) is ignored
_SUPPRESS_RE = re.compile(
    r"#\s*fhh-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


@dataclass(frozen=True)
class Finding:
    """One lint finding.  ``path`` is repo-relative with forward slashes
    (the form baseline entries and suppression workflows key on)."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.severity}] {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
        }


class Rule:
    """Base class: subclasses set ``name``/``default_severity`` and yield
    ``(lineno, end_lineno, message)`` from :meth:`check`."""

    name: str = ""
    default_severity: str = "error"

    def check(self, mod: "SourceModule", cfg):
        raise NotImplementedError
        yield  # pragma: no cover


def _parse_suppressions(text: str) -> dict[int, set[str]]:
    """line -> set of rule names disabled there.  A comment sharing a line
    with code applies to that line; a comment alone on its line applies to
    the next CODE line — blank and comment-only lines between are skipped,
    so a multi-line justification can carry the marker anywhere in it."""
    out: dict[int, set[str]] = {}
    lines = text.splitlines()

    def next_code_line(after: int) -> int:
        for i in range(after, len(lines)):
            stripped = lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1  # 1-indexed
        return after + 1

    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            line = tok.start[0]
            out.setdefault(line, set()).update(rules)
            # standalone comment: nothing but whitespace before it
            if tok.line[: tok.start[1]].strip() == "":
                out.setdefault(next_code_line(line), set()).update(rules)
    except tokenize.TokenError:
        pass  # findings still apply; suppressions in the torn tail are lost
    return out


class SourceModule:
    """One parsed source file with parent links and suppression table."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=relpath)
        self.suppressions = _parse_suppressions(text)
        self._parents: dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST):
        """node's enclosing chain, innermost first (node excluded)."""
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_functions(self, node: ast.AST):
        return [
            a
            for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

    def in_loop_within_function(self, node: ast.AST) -> bool:
        """True when a ``for``/``while`` sits between ``node`` and its
        nearest enclosing function (or module) boundary."""
        for a in self.ancestors(node):
            if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    def is_suppressed(self, rule: str, lineno: int, end_lineno: int | None) -> bool:
        for line in range(lineno, (end_lineno or lineno) + 1):
            if rule in self.suppressions.get(line, ()):
                return True
        return False


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains; None when the base is dynamic
    (a call result, subscript, ...) — rules then match on the final
    attribute alone."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    else:
        return None
    return ".".join(reversed(parts))


def last_segment(dotted: str | None) -> str | None:
    return None if dotted is None else dotted.rsplit(".", 1)[-1]


def _rule_severity(rule: Rule, cfg) -> str:
    sev = cfg.severity_overrides.get(rule.name, rule.default_severity)
    return sev if sev in SEVERITIES else rule.default_severity


def lint_module(mod: SourceModule, cfg, rules=None) -> list[Finding]:
    from .rules import ALL_RULES

    findings: list[Finding] = []
    for rule in rules if rules is not None else ALL_RULES:
        sev = _rule_severity(rule, cfg)
        for lineno, end_lineno, message in rule.check(mod, cfg):
            if mod.is_suppressed(rule.name, lineno, end_lineno):
                continue
            findings.append(
                Finding(rule.name, mod.relpath, lineno, message, sev)
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_source(text: str, relpath: str, cfg, rules=None) -> list[Finding]:
    """Lint raw source text (the fixture-test entry point)."""
    return lint_module(SourceModule(relpath, text), cfg, rules)


def iter_python_files(paths, root: str):
    """Yield (abspath, relpath) for every .py under ``paths`` (files or
    directories), sorted, skipping hidden dirs and __pycache__."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            candidates = [ap]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d
                    for d in dirnames
                    if not d.startswith(".") and d != "__pycache__"
                )
                candidates.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        for c in candidates:
            c = os.path.abspath(c)
            if c in seen or not c.endswith(".py"):
                continue
            seen.add(c)
            yield c, os.path.relpath(c, root).replace(os.sep, "/")


def lint_paths(
    paths, cfg, root: str, rules=None, files=None
) -> tuple[list[Finding], list[str]]:
    """Lint every .py file under ``paths``.  Returns (findings, errors) —
    a file that fails to parse is an error entry, not a crash (the linter
    must survive any tree it is pointed at).  ``files`` (pre-enumerated
    (abspath, relpath) pairs) skips the walk — the CLI enumerates once for
    its scan-scope set and passes the list through."""
    findings: list[Finding] = []
    errors: list[str] = []
    for abspath, relpath in (
        files if files is not None else iter_python_files(paths, root)
    ):
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
            mod = SourceModule(relpath, text)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{relpath}: {type(e).__name__}: {e}")
            continue
        findings.extend(lint_module(mod, cfg, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors
