"""fhh-race: interprocedural asyncio lock-discipline + await-atomicity
analysis.

The 2PC transcript is bit-identical only because both servers execute
verbs in frame-arrival order under a strict lock discipline — and every
review round since the pipelined crawl has hand-caught the same bug
class: shared server/driver state read before an ``await`` and mutated
after, or touched outside its owning lock (the stale-window-id read and
the half-mirrored-seal interleave of the streaming front door, the
ingest-only-checkpoint recovery hole of the multi-chip refactor).  The
intra-function rules structurally cannot see it; these two can:

- ``guarded-state-unlocked`` — an access to a **guarded attribute**
  (the declared guard map binds each shared attribute to its owning
  lock) at a program point where the lock-held set does not contain the
  owner.  The held set is computed interprocedurally: lexical
  ``with``/``async with`` blocks on known locks, plus entry-lock sets
  propagated through the module call graph (a helper called only from
  inside lock blocks inherits them), plus declared dispatch contracts
  (``# fhh-race: holds=<lock>`` for verbs reached via dynamic dispatch
  under a lock the analyzer cannot see).
- ``stale-read-across-await`` — a guarded value bound to a local, then
  a **suspension point** (an ``await``, an ``async with`` lock acquire,
  an ``async for`` step) at which the owning lock was released or never
  held, then a use of the local: the exact PR-7 stale-window-id shape.
  A lock held *across* the await (asyncio locks stay held through
  suspension) keeps the snapshot fresh and is clean.

Guard map sources (merged):

- ``[tool.fhh-lint.guards]`` in pyproject.toml — keys are
  ``"ClassName.attr"`` (checked on ``self.attr`` accesses in that
  class's methods, any file in ``race_modules``); values name the
  owning lock attribute.
- inline ``# fhh-guard: <attr>=<lock>`` — inside a class body binds
  ``self.<attr>`` for that class; at module level binds the module
  global ``<attr>`` to the module-level lock ``<lock>``.

Approximations, by design (this is a linter, not a model checker): call
resolution is name-based within one module (``self.m()`` resolves to the
enclosing class's method, bare ``f()`` to a module function; anything
else is unresolved and contributes no locks), construction code
(``__init__``/``__post_init__``/module top level) is exempt, and
``holds=`` annotations are *declared contracts*, validated dynamically
by the runtime sanitizer (:mod:`fuzzyheavyhitters_tpu.utils.guards`,
``FHH_DEBUG_GUARDS=1``) riding the e2e chaos suites.

Deliberately-unlocked sites declare ``# fhh-race: atomic (reason)`` on
the def: the safety argument for the lock-free ingest fast path, the
frame-arrival pre-expand, and the session table is event-loop atomicity
(they never suspend, so no task interleaves), and the annotation makes
that argument CHECKED — the analyzer verifies the function has no
suspension point and flags the contract the moment someone adds an
``await``.  At runtime the same sites run inside
``guards.unguarded(reason)`` windows, the dynamic twin of the written
justification.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize

from .engine import Rule, SourceModule, dotted_name, last_segment

# annotation grammar (same placement rules as fhh-lint suppressions: on
# the line itself, or standing alone on the line above the code it
# binds to — blank/comment lines between are skipped)
_HOLDS_RE = re.compile(
    r"#\s*fhh-race:\s*holds=([A-Za-z_][A-Za-z0-9_]*"
    r"(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)"
)
# `# fhh-race: atomic (reason)` on a def: this function touches guarded
# state WITHOUT the owning lock, and its safety argument is event-loop
# atomicity — it never suspends, so no other task can interleave.  The
# annotation is a CHECKED contract, not a suppression: the analyzer
# verifies the function body has no suspension point (await / async
# with / async for / async-generator yield) and flags any that appears,
# so the justification cannot silently rot when someone adds an await.
_ATOMIC_RE = re.compile(r"#\s*fhh-race:\s*atomic\b")
_GUARD_RE = re.compile(
    r"#\s*fhh-guard:\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"
    r"([A-Za-z_][A-Za-z0-9_]*)"
)

_LOCK_CTOR_SEGS = {"Lock", "RLock"}
_CTOR_FNS = ("__init__", "__post_init__")


def _annotation_lines(text: str, regex: re.Pattern) -> dict[int, list]:
    """lineno -> list of regex match groups bound there.  A comment
    sharing a line with code binds to that line; a standalone comment
    binds to the next CODE line (so a `# fhh-race: holds=...` above an
    ``async def`` annotates the def)."""
    out: dict[int, list] = {}
    lines = text.splitlines()

    def next_code_line(after: int) -> int:
        for i in range(after, len(lines)):
            stripped = lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1
        return after + 1

    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = regex.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            if tok.line[: tok.start[1]].strip() == "":
                line = next_code_line(line)
            out.setdefault(line, []).append(m.groups())
    except tokenize.TokenError:
        pass
    return out


def _mentions_lock_ctor(node: ast.AST) -> bool:
    """True when ``node`` constructs a lock: ``asyncio.Lock()``,
    ``threading.RLock()``, or a dataclass ``field(default_factory=
    asyncio.Lock)``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            seg = last_segment(dotted_name(n.func))
            if seg in _LOCK_CTOR_SEGS:
                return True
            if seg == "field":
                for kw in n.keywords:
                    if kw.arg == "default_factory":
                        if (
                            last_segment(dotted_name(kw.value))
                            in _LOCK_CTOR_SEGS
                        ):
                            return True
    return False


class _Fn:
    """One function/method in the module."""

    __slots__ = ("node", "qual", "cls", "holds", "atomic", "callsites",
                 "entry", "_shadowed")

    def __init__(self, node, qual: str, cls: str | None, holds, atomic):
        self.node = node
        self.qual = qual
        self.cls = cls
        self.holds = holds  # frozenset | None (declared entry contract)
        self.atomic = atomic  # bool: declared event-loop-atomic
        self.callsites: list = []  # (caller_qual, lexical_held) pairs
        self.entry: frozenset = frozenset()
        self._shadowed: set | None = None  # lazy _fn_locals_without_global

    @property
    def shadowed(self) -> set:
        """This scope's local bindings (memoized: the unlocked-global
        check consults it per Name access — a fresh scope walk per
        access is O(accesses x function size))."""
        if self._shadowed is None:
            self._shadowed = _fn_locals_without_global(self.node)
        return self._shadowed


class _RaceInfo:
    """Everything both rules need, computed once per module."""

    __slots__ = (
        "locks", "class_guards", "module_guards", "fns", "fn_of_node",
        "class_of_fn",
    )


def _guard_maps(mod: SourceModule, cfg, class_spans):
    """(class_guards {cls: {attr: lock}}, module_guards {name: lock})
    from the config table plus inline ``# fhh-guard`` annotations."""
    class_guards: dict[str, dict] = {}
    module_guards: dict[str, str] = {}
    for key, lock in getattr(cfg, "guards", {}).items():
        if not isinstance(key, str) or not isinstance(lock, str):
            continue
        if "." in key:
            cls, attr = key.split(".", 1)
            class_guards.setdefault(cls, {})[attr] = lock
        # a dotless config key would apply to EVERY module in scope —
        # module-level guards are inline-only, where they name one file
    for line, entries in _annotation_lines(mod.text, _GUARD_RE).items():
        owner = None
        for cls, (lo, hi) in class_spans.items():
            if lo < line <= hi:  # inside the class body (below the def)
                owner = cls
                break
        for attr, lock in entries:
            if owner is not None:
                class_guards.setdefault(owner, {})[attr] = lock
            else:
                module_guards[attr] = lock
    return class_guards, module_guards


def _lexical_held(mod: SourceModule, node: ast.AST, locks: set) -> frozenset:
    """Locks held by ``with``/``async with`` blocks lexically enclosing
    ``node``, up to the nearest function boundary (lambdas pass
    through — they execute in the enclosing context here)."""
    held = set()
    for a in mod.ancestors(node):
        if isinstance(a, (ast.With, ast.AsyncWith)):
            held |= _with_locks(a, locks)
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return frozenset(held)


def _with_locks(w, locks: set) -> set:
    out = set()
    for item in w.items:
        for n in ast.walk(item.context_expr):
            if isinstance(n, ast.Name) and n.id in locks:
                out.add(n.id)
            elif isinstance(n, ast.Attribute) and n.attr in locks:
                out.add(n.attr)
    return out


def analyze(mod: SourceModule, cfg) -> _RaceInfo:
    """Build (and cache on ``mod``) the module's race-analysis state:
    lock inventory, guard maps, function table, and the entry-lock
    fixpoint over the module call graph."""
    cached = getattr(mod, "_fhh_race_info", None)
    if cached is not None and cached[0] is cfg:
        return cached[1]
    info = _RaceInfo()

    # -- classes + their line spans (inline guard attribution) ----------
    class_spans: dict[str, tuple] = {}
    class_of_fn: dict[int, str] = {}  # id(fn node) -> class name
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            class_spans[node.name] = (
                node.lineno, getattr(node, "end_lineno", node.lineno)
            )
            for child in node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of_fn[id(child)] = node.name

    info.class_guards, info.module_guards = _guard_maps(
        mod, cfg, class_spans
    )

    # -- lock inventory ---------------------------------------------------
    locks: set[str] = set()
    for stmt in mod.tree.body:  # module-level lock objects
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        if _mentions_lock_ctor(value):
            locks.update(
                t.id for t in targets if isinstance(t, ast.Name)
            )
    for node in ast.walk(mod.tree):  # class/instance lock attributes
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is None or not _mentions_lock_ctor(value):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    locks.add(t.id)
                elif isinstance(t, ast.Attribute):
                    locks.add(t.attr)
    for guards in info.class_guards.values():  # declared owners count too
        locks.update(guards.values())
    locks.update(info.module_guards.values())

    # -- function table + holds/atomic annotations ------------------------
    holds_by_line = _annotation_lines(mod.text, _HOLDS_RE)
    atomic_lines = _annotation_lines(mod.text, _ATOMIC_RE)
    fns: dict[str, _Fn] = {}
    fn_of_node: dict[int, _Fn] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = class_of_fn.get(id(node))
        qual = f"{cls}.{node.name}" if cls else node.name
        holds = None
        for groups in holds_by_line.get(node.lineno, []):
            names = {s.strip() for s in groups[0].split(",") if s.strip()}
            holds = frozenset(names) if holds is None else holds | names
            locks.update(names)
        fn = _Fn(node, qual, cls, holds, node.lineno in atomic_lines)
        # first definition wins on a name collision (rare; documented)
        fns.setdefault(qual, fn)
        fn_of_node[id(node)] = fns[qual]
    info.locks = locks
    info.fns = fns
    info.fn_of_node = fn_of_node
    info.class_of_fn = class_of_fn

    # -- call graph: (callee) <- (caller, lexical locks at the site) ------
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = mod.enclosing_functions(node)
        caller = fn_of_node.get(id(chain[0])) if chain else None
        callee = None
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
            and caller is not None
            and caller.cls is not None
        ):
            callee = fns.get(f"{caller.cls}.{f.attr}")
        elif isinstance(f, ast.Name):
            callee = fns.get(f.id)
        if callee is None:
            continue
        lex = _lexical_held(mod, node, locks)
        callee.callsites.append((caller.qual if caller else None, lex))

    # -- entry-lock fixpoint ----------------------------------------------
    all_locks = frozenset(locks)
    for fn in fns.values():
        if fn.holds is not None:
            fn.entry = fn.holds  # declared contract wins (sanitizer-checked)
        elif fn.callsites:
            fn.entry = all_locks  # start at top, meet downward
        else:
            fn.entry = frozenset()  # public entry point: nothing held
    changed = True
    while changed:
        changed = False
        for fn in fns.values():
            if fn.holds is not None or not fn.callsites:
                continue
            new = None
            for caller_qual, lex in fn.callsites:
                held = lex | (
                    fns[caller_qual].entry if caller_qual in fns else frozenset()
                )
                new = held if new is None else (new & held)
            if new != fn.entry:
                fn.entry = new
                changed = True

    mod._fhh_race_info = (cfg, info)
    return info


def _in_scope(mod: SourceModule, cfg) -> bool:
    prefixes = getattr(cfg, "race_modules", ())
    return any(
        mod.relpath == p or mod.relpath.startswith(p.rstrip("/") + "/")
        for p in prefixes
    )


def _span(node: ast.AST):
    return node.lineno, getattr(node, "end_lineno", node.lineno)


def _suspension_points(fn_node) -> list:
    """(lineno, kind) for every suspension point in the function's OWN
    body — awaits, async-with acquires, async-for steps, and async-
    generator yields.  Nested defs/lambdas are excluded: they run in
    their own execution context, not inline."""
    out: list = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(child, ast.Await):
                out.append((child.lineno, "await"))
            elif isinstance(child, ast.AsyncWith):
                out.append((child.lineno, "async with"))
            elif isinstance(child, ast.AsyncFor):
                out.append((child.lineno, "async for"))
            elif isinstance(child, (ast.Yield, ast.YieldFrom)) and isinstance(
                fn_node, ast.AsyncFunctionDef
            ):
                out.append((child.lineno, "yield"))
            walk(child)

    walk(fn_node)
    return out


def _fn_locals_without_global(fn) -> set[str]:
    """Names the function binds locally (so a same-named module global is
    shadowed, not accessed) — THIS scope's assignment targets and
    parameters minus `global` decls.  Nested def/lambda subtrees are
    skipped (their bindings live in their own scope and shadow nothing
    out here — an `ast.walk` would sweep them in and exempt an outer
    unlocked global read behind an inner parameter); the nested def's
    NAME itself does bind here and counts."""
    decls: set[str] = set()
    assigned: set[str] = {
        a.arg for a in ast.walk(fn.args) if isinstance(a, ast.arg)
    }

    def scope_walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                assigned.add(child.name)
                continue  # inner scope: its bindings are not ours
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Global):
                decls.update(child.names)
            elif isinstance(child, ast.Name) and isinstance(
                child.ctx, (ast.Store, ast.Del)
            ):
                assigned.add(child.id)
            scope_walk(child)

    scope_walk(fn)
    return assigned - decls


class GuardedStateUnlocked(Rule):
    """Guard-map violations: a ``self.<attr>`` access in a method of the
    attribute's class (or a module-global access) at a point where the
    owning lock is provably absent from the interprocedural held set."""

    name = "guarded-state-unlocked"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _in_scope(mod, cfg):
            return
        info = analyze(mod, cfg)
        # atomic contracts verify even with no guard map: the annotation
        # asserts no-suspension, and a rotted assertion must flag
        for fn in info.fns.values():
            if not fn.atomic:
                continue
            for lineno, kind in _suspension_points(fn.node):
                yield (
                    lineno, lineno,
                    f"'{fn.qual}' is declared `# fhh-race: atomic` "
                    f"(event-loop-atomic, lock-free by justification) but "
                    f"contains a suspension point ({kind}) — another task "
                    "can now interleave mid-function; hold the owning "
                    "lock instead, or hoist the suspension out",
                )
        if not info.class_guards and not info.module_guards:
            return
        for node in ast.walk(mod.tree):
            hit = self._guarded_access(mod, info, node)
            if hit is None:
                continue
            fn, owner, lock, label = hit
            if fn.atomic:
                # declared event-loop-atomic (and verified suspension-free
                # above): lock-free access is the documented design
                continue
            held = fn.entry | _lexical_held(mod, node, info.locks)
            if lock in held:
                continue
            contract = (
                "hold it around the access, have every caller hold it "
                "(the call graph propagates), declare the dispatch "
                "contract with `# fhh-race: holds=...`, or suppress "
                "with a written justification"
            )
            yield (
                *_span(node),
                f"guarded state '{label}' accessed in '{fn.qual}' "
                f"without its owning lock '{lock}' held — {contract}",
            )

    @staticmethod
    def _guarded_access(mod, info, node):
        """(fn, owner, lock, label) when ``node`` is a guarded access in
        checkable scope; None otherwise.  Construction code is exempt."""
        if isinstance(node, ast.Attribute):
            if not (
                isinstance(node.value, ast.Name) and node.value.id == "self"
            ):
                return None
            chain = mod.enclosing_functions(node)
            if not chain:
                return None
            fn = info.fn_of_node.get(id(chain[0]))
            if fn is None or fn.cls is None:
                return None
            if fn.node.name in _CTOR_FNS:
                return None
            lock = info.class_guards.get(fn.cls, {}).get(node.attr)
            if lock is None or node.attr == lock:
                return None
            return fn, fn.cls, lock, f"{fn.cls}.{node.attr}"
        if isinstance(node, ast.Name) and node.id in info.module_guards:
            chain = mod.enclosing_functions(node)
            if not chain:
                return None  # module-level init is construction
            fn = info.fn_of_node.get(id(chain[0]))
            if fn is None:
                return None
            if node.id in fn.shadowed:
                return None  # a local shadows the module global
            lock = info.module_guards[node.id]
            if node.id == lock:
                return None
            return fn, None, lock, node.id
        return None


class _Taint:
    __slots__ = ("field", "lock", "line", "crossed", "reported_lines")

    def __init__(self, field, lock, line):
        self.field, self.lock, self.line = field, lock, line
        self.crossed = False
        # every stale USE line reports (dedup per line, not per taint):
        # a one-taint flag would let a suppression on the FIRST use
        # silently absorb every later unsuppressed use of the same local
        self.reported_lines: set[int] = set()


class StaleReadAcrossAwait(Rule):
    """The PR-7 bug shape: a guarded value snapshotted into a local, a
    suspension point crossed while the owning lock was not held (so the
    field may have moved), then the stale local used as if fresh."""

    name = "stale-read-across-await"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _in_scope(mod, cfg):
            return
        info = analyze(mod, cfg)
        if not info.class_guards and not info.module_guards:
            return
        for fn in info.fns.values():
            if not isinstance(fn.node, ast.AsyncFunctionDef):
                continue
            if fn.node.name in _CTOR_FNS:
                continue
            if fn.atomic:
                continue  # suspension-free by verified contract
            guards = info.class_guards.get(fn.cls, {}) if fn.cls else {}
            if not guards and not info.module_guards:
                continue
            yield from self._scan_fn(fn, guards, info)

    def _scan_fn(self, fn, guards, info):
        taints: dict[str, _Taint] = {}
        findings: list = []
        shadowed = fn.shadowed if info.module_guards else set()

        def guarded_reads(expr):
            """(field-label, lock) pairs read directly in ``expr``."""
            out = []
            for n in ast.walk(expr):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and n.attr in guards
                ):
                    out.append((n.attr, guards[n.attr]))
                elif (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in info.module_guards
                    and n.id not in shadowed
                ):
                    out.append((n.id, info.module_guards[n.id]))
            return out

        def suspend(held):
            for t in taints.values():
                if t.lock not in held:
                    t.crossed = True

        def use(node):
            t = taints.get(node.id)
            if t is not None and t.crossed and node.lineno not in t.reported_lines:
                t.reported_lines.add(node.lineno)
                findings.append((
                    *_span(node),
                    f"'{node.id}' snapshots guarded '{t.field}' "
                    f"(line {t.line}) and is used after a suspension "
                    f"point crossed without '{t.lock}' held — the field "
                    "may have moved while this task slept; re-read it "
                    "under the lock after the await (the PR-7 "
                    "stale-window-id shape)",
                ))

        def visit_expr(node, held):
            """Execution-ordered walk: children (argument evaluation)
            before the await's suspension."""
            if isinstance(node, ast.Await):
                visit_expr(node.value, held)
                suspend(held)
                return
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                use(node)
            if isinstance(node, ast.Lambda):
                return  # deferred execution: out of linear order
            for child in ast.iter_child_nodes(node):
                visit_expr(child, held)

        def bind_targets(stmt, held):
            reads = guarded_reads(
                stmt.value if stmt.value is not None else ast.Constant(None)
            )
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    if reads:
                        field, lock = reads[0]
                        taints[t.id] = _Taint(field, lock, stmt.lineno)
                    else:
                        taints.pop(t.id, None)
                else:
                    # tuple/list/starred/attribute targets: every name
                    # bound here is REBOUND — its old snapshot taint is
                    # gone (conservatively untainted even when the RHS
                    # reads guarded state; a linter prefers the miss to
                    # the false positive)
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            taints.pop(n.id, None)

        def visit_stmt(stmt, held):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                if stmt.value is not None:
                    visit_expr(stmt.value, held)
                bind_targets(stmt, held)
                return
            if isinstance(stmt, ast.AugAssign):
                visit_expr(stmt.value, held)
                if isinstance(stmt.target, ast.Name):
                    use(stmt.target)  # read-modify-write reads the local
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = _with_locks(stmt, info.locks)
                for item in stmt.items:
                    visit_expr(item.context_expr, held)
                if isinstance(stmt, ast.AsyncWith):
                    # acquiring a contended asyncio lock suspends; the
                    # lock is NOT yet held at that point
                    suspend(held)
                inner = held | newly
                for s in stmt.body:
                    visit_stmt(s, inner)
                # NB: asyncio.Lock release is synchronous — the exit of
                # an async with is NOT a suspension point
                return
            if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    visit_expr(stmt.iter, held)
                    if isinstance(stmt, ast.AsyncFor):
                        suspend(held)
                    for t in ast.walk(stmt.target):
                        if isinstance(t, ast.Name):
                            taints.pop(t.id, None)
                else:
                    visit_expr(stmt.test, held)
                # two passes over the body: an await late in the body
                # precedes (in execution) a use early in the body on the
                # next iteration — the single-pass scan would miss it
                for _ in range(2):
                    for s in stmt.body:
                        visit_stmt(s, held)
                if isinstance(stmt, ast.While):
                    # the condition re-evaluates AFTER each body pass:
                    # `while w == self.f: await x()` compares a stale
                    # snapshot on iteration 2 (per-line dedup absorbs
                    # the pre-body visit above)
                    visit_expr(stmt.test, held)
                for s in stmt.orelse:
                    visit_stmt(s, held)
                return
            if isinstance(stmt, ast.If):
                visit_expr(stmt.test, held)
                for s in stmt.body:
                    visit_stmt(s, held)
                for s in stmt.orelse:
                    visit_stmt(s, held)
                return
            if isinstance(stmt, ast.Try):
                for s in stmt.body:
                    visit_stmt(s, held)
                for h in stmt.handlers:
                    for s in h.body:
                        visit_stmt(s, held)
                for s in stmt.orelse + stmt.finalbody:
                    visit_stmt(s, held)
                return
            # leaf statements (Expr, Return, Raise, Assert, Delete, ...)
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    visit_expr(child, held)

        for s in fn.node.body:
            visit_stmt(s, fn.entry)
        yield from findings


RACE_RULES = (GuardedStateUnlocked(), StaleReadAcrossAwait())
