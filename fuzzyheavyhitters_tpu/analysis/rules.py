"""The fhh-lint rule set, tuned to this codebase's invariants.

Thirteen rules over twelve concerns (the broad-except/bare-print concern
ships as two rules so suppressions and severities stay per-rule; the
two interprocedural fhh-race rules live in :mod:`.concurrency` and are
registered here):

- ``host-sync-in-hot-loop`` — device->host synchronization primitives
  (``.item()``, ``np.asarray``, ``jax.device_get``,
  ``.block_until_ready()``; plus ``bool``/``int``/``float`` casts under
  jit, which force a tracer sync or fail outright) reachable from the
  per-level crawl path: lexically inside a loop in a hot module, inside
  a configured hot-root function or its in-module transitive callees, or
  inside any jit-decorated function.  The sanctioned fetch path
  (``protocol.rpc._fetch``: off-event-loop, obs-counted) never trips
  this rule — raw syncs in the crawl are exactly the smell.
- ``secret-to-sink`` — identifiers matching the secret lexicon (seeds,
  correction-word planes, GC labels, Δ, MAC keys) flowing into log/emit
  calls, ``print``, or exception messages.  A crawl that logs a seed has
  leaked a client's key share to whoever reads the log.
- ``recompile-churn`` — ``jax.jit``/``jax.pmap`` wrappers created inside
  function bodies (a fresh wrapper per call = a fresh compile cache per
  call), ``pallas_call`` constructed inside a lexical loop, and static
  arguments of module-local jit functions fed unhashable literals or
  loop variables (one XLA compile per iteration).
- ``unguarded-shared-state`` — module-level mutables in the configured
  shared-state modules written outside every registered lock
  (module-level ``threading.Lock/RLock``/``asyncio.Lock``).  The obs
  registries are read by the heartbeat thread concurrently with the
  event loop; an unlocked write there is a data race by construction.
- ``broad-except`` — bare ``except:`` or ``except Exception`` handlers
  that neither re-raise nor call pytest's raising helpers.  Catch-all
  boundaries that are deliberate (an RPC verb handler surfacing errors
  to its caller) carry an inline suppression with a justification.
- ``bare-print`` — ``print()`` in crawl-path package modules (the
  ``test_obs`` stdout-hygiene guard, generalized): telemetry goes
  through ``obs.emit``; stdout stays a clean program-output channel.
- ``chunked-device-readback`` — device->host readbacks (``_fetch``,
  ``np.asarray``, ``jax.device_get``, ``.copy_to_host_async()``) inside
  loops in the secure-kernel hot roots (``readback_modules``).  A loop
  of per-chunk fetches serializes the crawl on one device round trip
  per chunk — the exact pattern the whole-level kernel restructure
  removed; the rule pins it at zero.  Deliberately overlaps host-sync
  on ``np.asarray`` (both fire) and deliberately covers ``_fetch``,
  which host-sync sanctions: counted and off-loop does not make a loop
  of fetches cheap.
- ``unbounded-await`` — ``await`` on network reads (``readexactly``,
  ``read``, ...), ``asyncio.wait``, event waits, or dials carrying no
  timeout/deadline, in the configured transport modules
  (``await_modules``: protocol + resilience).  A black-holed peer (no
  FIN, no RST, frames silently dropped) hangs such an await forever;
  the resilience layer's whole premise is that every wait is bounded —
  by a kwarg timeout, ``asyncio.wait_for``, or a ``Deadline`` — and the
  deliberately-unbounded sites (serve loops waiting for the next
  command) carry inline suppressions with justifications.
- ``unbounded-queue`` — ``asyncio.Queue()``/``queue.Queue()`` without a
  positive ``maxsize`` and ``collections.deque()`` without a ``maxlen``
  in the configured ingest/transport modules (``queue_modules``:
  protocol + resilience).  An unbounded buffer between a fast producer
  (a flooding client) and a slow consumer (the crawl) converts overload
  into OOM — the exact failure class the admission-controlled front
  door exists to prevent; every buffer is bounded or carries an inline
  suppression proving it is bounded by construction.
- ``span-discipline`` — obs spans (``reg.span(...)``) not used as
  context managers (a never-entered span records nothing and reads as
  if it instruments the code; an abandoned one dangles in the
  heartbeat and the merged trace) and ``emit()``/``observe()``
  telemetry inside jit-decorated bodies (runs at trace time: records
  once per compile, never per execution).  Scope ``span_modules``:
  protocol/, obs/, parallel/.
- ``metric-naming`` — exported series names must be valid Prometheus
  identifiers: literal metric names fed to the registry methods
  (``count``/``gauge``/``observe``/``timer_add``) must be lowercase
  ``[a-z][a-z0-9_]*`` chunks (optionally ``:sub``, folded into a
  ``key`` label by obs/exporter.py), and a hand-rolled ``fhh_...``
  series literal must end with a unit suffix (``_seconds``, ``_bytes``,
  ``_total``, ...) — the live /metrics plane's naming contract,
  enforced where the names are born instead of on the wire.
- ``guarded-state-unlocked`` / ``stale-read-across-await`` — the
  fhh-race pair (:mod:`.concurrency`): interprocedural asyncio
  lock-discipline over the declared guard map
  (``[tool.fhh-lint.guards]`` + inline ``# fhh-guard:``), and the
  snapshot-await-use atomicity break that every review round since the
  pipelined crawl has hand-caught.  Validated dynamically by the
  ``FHH_DEBUG_GUARDS=1`` runtime sanitizer
  (:mod:`fuzzyheavyhitters_tpu.utils.guards`).
"""

from __future__ import annotations

import ast
import re

from .concurrency import RACE_RULES
from .taint import TAINT_RULES
from .engine import Rule, SourceModule, dotted_name, last_segment

# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

_JIT_NAMES = ("jit", "pmap")


def _mentions_jit(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _JIT_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _JIT_NAMES:
            return True
    return False


def _is_jit_decorated(fn) -> bool:
    return any(_mentions_jit(dec) for dec in fn.decorator_list)


def _module_functions(mod: SourceModule) -> dict:
    """bare name -> list of (Async)FunctionDef anywhere in the module."""
    defs: dict[str, list] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _callee_names(fn) -> set[str]:
    """Bare names this function calls (``f(...)``, ``obj.f(...)``)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            seg = last_segment(dotted_name(node.func))
            if seg:
                out.add(seg)
    return out


def _hot_functions(mod: SourceModule, cfg) -> set[str]:
    """Configured hot roots plus their in-module transitive callees."""
    defs = _module_functions(mod)
    hot = {name for name in cfg.hot_roots if name in defs}
    work = list(hot)
    while work:
        name = work.pop()
        for fn in defs[name]:
            for callee in _callee_names(fn):
                if callee in defs and callee not in hot:
                    hot.add(callee)
                    work.append(callee)
    return hot


def _under_prefix(relpath: str, prefixes) -> bool:
    return any(
        relpath == p or relpath.startswith(p.rstrip("/") + "/")
        for p in prefixes
    )


def _loop_targets(mod: SourceModule, node: ast.AST) -> set[str]:
    """Names bound as ``for`` targets in loops enclosing ``node`` (within
    the nearest function boundary)."""
    out: set[str] = set()
    for a in mod.ancestors(node):
        if isinstance(a, (ast.For, ast.AsyncFor)):
            for t in ast.walk(a.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return out


def _span(node: ast.AST):
    return node.lineno, getattr(node, "end_lineno", node.lineno)


# ---------------------------------------------------------------------------
# 1. host-sync-in-hot-loop
# ---------------------------------------------------------------------------

_HOST_SYNC_DOTTED = {
    "np.asarray": "np.asarray",
    "numpy.asarray": "np.asarray",
    "jax.device_get": "jax.device_get",
    "device_get": "jax.device_get",
}
_HOST_SYNC_METHODS = {"item", "block_until_ready"}
_TRACER_CASTS = {"bool", "int", "float"}


class HostSyncInHotLoop(Rule):
    name = "host-sync-in-hot-loop"
    default_severity = "warning"

    def check(self, mod: SourceModule, cfg):
        in_hot_module = _under_prefix(mod.relpath, cfg.hot_modules)
        hot_fns = _hot_functions(mod, cfg) if in_hot_module else set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            sync = self._sync_kind(node)
            cast = self._cast_kind(node)
            if sync is None and cast is None:
                continue
            chain = mod.enclosing_functions(node)
            jit_fn = next((f for f in chain if _is_jit_decorated(f)), None)
            if jit_fn is not None:
                what = sync or f"{cast}() cast"
                yield (
                    *_span(node),
                    f"{what} inside jit-compiled function "
                    f"'{jit_fn.name}' forces a host sync on every call",
                )
                continue
            if sync is None or not in_hot_module:
                continue  # bare casts only matter under jit
            hot_fn = next((f.name for f in chain if f.name in hot_fns), None)
            if mod.in_loop_within_function(node):
                yield (
                    *_span(node),
                    f"{sync} inside a loop in hot module "
                    f"{mod.relpath} blocks on a device round trip per "
                    "iteration (batch or hoist it, or route it through "
                    "the counted _fetch helper)",
                )
            elif hot_fn is not None:
                yield (
                    *_span(node),
                    f"{sync} on the per-level crawl path "
                    f"(reachable from hot root via '{hot_fn}') costs a "
                    "device round trip per level (batch or hoist it, or "
                    "route it through the counted _fetch helper)",
                )

    @staticmethod
    def _sync_kind(call: ast.Call) -> str | None:
        dn = dotted_name(call.func)
        if dn in _HOST_SYNC_DOTTED:
            return _HOST_SYNC_DOTTED[dn]
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _HOST_SYNC_METHODS
            and not call.args
            and not call.keywords
        ):
            return f".{call.func.attr}()"
        return None

    @staticmethod
    def _cast_kind(call: ast.Call) -> str | None:
        # only casts of computed expressions (a call result, an element, an
        # attribute) — bool(p) of a plain local is almost always a static
        # Python value, not a tracer
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in _TRACER_CASTS
            and len(call.args) == 1
            and not call.keywords
            and isinstance(call.args[0], (ast.Call, ast.Subscript, ast.Attribute))
        ):
            return call.func.id
        return None


# ---------------------------------------------------------------------------
# 2. secret-to-sink
# ---------------------------------------------------------------------------


def _secret_match(identifier: str, lexicon) -> bool:
    segments = [s for s in identifier.lower().split("_") if s]
    return any(s in lexicon for s in segments)


def _secret_idents(node: ast.AST, lexicon) -> list[str]:
    """Secret-matching identifiers appearing anywhere in an expression
    (f-string holes, call args, attribute chains included)."""
    out = []
    for n in ast.walk(node):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        elif isinstance(n, ast.arg):
            ident = n.arg
        if ident and _secret_match(ident, lexicon):
            out.append(ident)
    return out


class SecretToSink(Rule):
    name = "secret-to-sink"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        lexicon = set(cfg.secret_lexicon)
        sinks = set(cfg.sink_calls)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                seg = last_segment(dotted_name(node.func))
                if seg not in sinks:
                    continue
                leaked = []
                for arg in node.args:
                    leaked += _secret_idents(arg, lexicon)
                for kw in node.keywords:
                    leaked += _secret_idents(kw.value, lexicon)
                    if (
                        kw.arg
                        and _secret_match(kw.arg, lexicon)
                        and not isinstance(kw.value, ast.Constant)
                    ):
                        leaked.append(kw.arg)
                if leaked:
                    yield (
                        *_span(node),
                        f"secret-lexicon identifier(s) "
                        f"{sorted(set(leaked))} flow into sink "
                        f"'{seg}' — key material must never reach "
                        "logs, metrics, or stdout",
                    )
            elif isinstance(node, ast.Raise) and node.exc is not None:
                leaked = _secret_idents(node.exc, lexicon)
                if leaked:
                    yield (
                        *_span(node),
                        f"secret-lexicon identifier(s) "
                        f"{sorted(set(leaked))} flow into an exception "
                        "message — tracebacks cross trust boundaries "
                        "(RPC error responses, logs)",
                    )


# ---------------------------------------------------------------------------
# 3. recompile-churn
# ---------------------------------------------------------------------------

_UNHASHABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


def _jit_static_params(fn) -> set[str] | None:
    """For a jit-decorated function, the parameter names declared static
    (via static_argnames or static_argnums); None when not jit-decorated."""
    if not _is_jit_decorated(fn):
        return None
    statics: set[str] = set()
    arg_names = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        for n in ast.walk(dec):
            if not isinstance(n, ast.Call):
                continue
            for kw in n.keywords:
                if kw.arg == "static_argnames":
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, str):
                            statics.add(c.value)
                elif kw.arg == "static_argnums":
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, int):
                            if 0 <= c.value < len(arg_names):
                                statics.add(arg_names[c.value])
    return statics


class RecompileChurn(Rule):
    name = "recompile-churn"
    default_severity = "warning"

    def check(self, mod: SourceModule, cfg):
        # module-local jit functions and their static params
        jit_statics: dict[str, tuple[set[str], list[str]]] = {}
        for name, fns in _module_functions(mod).items():
            for fn in fns:
                statics = _jit_static_params(fn)
                if statics:
                    arg_names = [
                        a.arg for a in fn.args.posonlyargs + fn.args.args
                    ]
                    jit_statics[name] = (statics, arg_names)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._in_decorator(mod, node):
                continue
            dn = dotted_name(node.func)
            seg = last_segment(dn)
            chain = mod.enclosing_functions(node)
            if seg in _JIT_NAMES and chain:
                # jax.jit(f) inside a function body: a fresh wrapper (and
                # compile cache) per call.  Trace-time creation inside an
                # already-jit-decorated function is fine.
                if not any(_is_jit_decorated(f) for f in chain):
                    yield (
                        *_span(node),
                        f"'{dn}' wrapper created inside function "
                        f"'{chain[0].name}' — hoist to module scope or "
                        "the compile cache is rebuilt on every call",
                    )
                continue
            if seg == "pallas_call" and mod.in_loop_within_function(node):
                yield (
                    *_span(node),
                    "pallas_call constructed inside a loop — hoist the "
                    "kernel wrapper out of the iteration",
                )
                continue
            if seg in jit_statics:
                statics, arg_names = jit_statics[seg]
                loop_vars = _loop_targets(mod, node)
                bindings = []
                for i, arg in enumerate(node.args):
                    if i < len(arg_names) and arg_names[i] in statics:
                        bindings.append((arg_names[i], arg))
                for kw in node.keywords:
                    if kw.arg in statics:
                        bindings.append((kw.arg, kw.value))
                for pname, val in bindings:
                    if isinstance(val, _UNHASHABLE_LITERALS):
                        yield (
                            *_span(node),
                            f"unhashable literal passed for static arg "
                            f"'{pname}' of jit function '{seg}' — jit "
                            "static args must be hashable (use a tuple)",
                        )
                    elif isinstance(val, ast.Name) and val.id in loop_vars:
                        yield (
                            *_span(node),
                            f"loop variable '{val.id}' passed for static "
                            f"arg '{pname}' of jit function '{seg}' — "
                            "one fresh XLA compile per iteration",
                        )

    @staticmethod
    def _in_decorator(mod: SourceModule, node: ast.AST) -> bool:
        child = node
        for a in mod.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                if child in a.decorator_list or any(
                    child is d or child in ast.walk(d) for d in a.decorator_list
                ):
                    return True
            child = a
        return False


# ---------------------------------------------------------------------------
# 4. unguarded-shared-state
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_MUTABLE_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict", "WeakSet",
    "WeakValueDictionary", "WeakKeyDictionary", "Counter",
}
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
}


class UnguardedSharedState(Rule):
    name = "unguarded-shared-state"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.shared_state_modules):
            return
        mutables, locks = self._module_state(mod)
        # names rebound via `global` anywhere count as shared scalars
        global_names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        shared = mutables | global_names
        if not shared:
            return
        for fn in [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            fn_globals = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    fn_globals.update(node.names)
            for node in ast.walk(fn):
                hit = self._write_target(node, shared, mutables, fn_globals)
                if hit is None:
                    continue
                name, verb = hit
                if self._under_lock(mod, node, locks):
                    continue
                lock_hint = (
                    f"hold one of {sorted(locks)}"
                    if locks
                    else "register a module lock and hold it"
                )
                yield (
                    *_span(node),
                    f"module-level shared state '{name}' {verb} in "
                    f"'{fn.name}' outside any registered lock — "
                    f"{lock_hint} around the write",
                )

    @staticmethod
    def _module_state(mod: SourceModule):
        """(mutable names, lock names) assigned at module top level."""
        mutables: set[str] = set()
        locks: set[str] = set()
        for stmt in mod.tree.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if isinstance(value, (ast.Dict, ast.List, ast.Set)):
                    mutables.add(t.id)
                elif isinstance(value, ast.Call):
                    seg = last_segment(dotted_name(value.func))
                    if seg in _LOCK_CTORS:
                        locks.add(t.id)
                    elif seg in _MUTABLE_CTORS:
                        mutables.add(t.id)
        return mutables, locks

    @staticmethod
    def _write_target(node, shared, mutables, fn_globals):
        """(name, verb) when ``node`` writes a shared module name."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                # rebinding a global-declared name
                if isinstance(t, ast.Name) and t.id in fn_globals and t.id in shared:
                    return t.id, "rebound"
                # container element store: m[...] = / m.attr =
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if (
                    isinstance(base, ast.Name)
                    and base.id in mutables
                    and base is not t
                ):
                    return base.id, "mutated (element store)"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in mutables and base is not t:
                    return base.id, "mutated (del)"
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATING_METHODS
                and isinstance(f.value, ast.Name)
                and f.value.id in mutables
            ):
                return f.value.id, f"mutated (.{f.attr})"
        return None

    @staticmethod
    def _under_lock(mod: SourceModule, node, locks) -> bool:
        if not locks:
            return False
        for a in mod.ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    for n in ast.walk(item.context_expr):
                        if isinstance(n, ast.Name) and n.id in locks:
                            return True
                        if isinstance(n, ast.Attribute) and n.attr in locks:
                            return True
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False


# ---------------------------------------------------------------------------
# 5. broad-except  +  6. bare-print
# ---------------------------------------------------------------------------

_BROAD_TYPES = {"Exception", "BaseException"}
_RERAISE_EQUIVALENTS = {"skip", "xfail", "fail", "exit"}  # pytest helpers raise


class BroadExcept(Rule):
    name = "broad-except"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or self._is_broad(node.type)
            if not broad:
                continue
            if self._reraises(node):
                continue
            what = (
                "bare 'except:'"
                if node.type is None
                else f"'except {last_segment(dotted_name(node.type)) or 'Exception'}'"
            )
            yield (
                node.lineno,
                node.lineno,
                f"{what} swallows every failure mode — narrow the "
                "exception types, or re-raise after telemetry",
            )

    @staticmethod
    def _is_broad(type_node) -> bool:
        nodes = (
            type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
        )
        return any(
            last_segment(dotted_name(n)) in _BROAD_TYPES for n in nodes
        )

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                seg = last_segment(dotted_name(n.func))
                if seg in _RERAISE_EQUIVALENTS:
                    return True
        return False


class BarePrint(Rule):
    name = "bare-print"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.print_scope):
            return
        if mod.relpath in cfg.print_allowed:
            return
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield (
                    *_span(node),
                    "bare print() telemetry in a crawl-path module — "
                    "use fuzzyheavyhitters_tpu.obs.emit (stdout is a "
                    "program-output channel)",
                )


# ---------------------------------------------------------------------------
# 7. chunked-device-readback
# ---------------------------------------------------------------------------

# device->host readback entry points: the sanctioned counted fetch
# (protocol.rpc._fetch), the raw bulk fetches, and the async-DMA kickoff
_READBACK_DOTTED = {
    "np.asarray": "np.asarray",
    "numpy.asarray": "np.asarray",
    "jax.device_get": "jax.device_get",
    "device_get": "jax.device_get",
}


class ChunkedDeviceReadback(Rule):
    """Device readbacks inside per-chunk loops in the secure-kernel hot
    roots (``readback_modules``): a loop that fetches (or starts the DMA
    for) one chunk per iteration serializes the crawl on one device
    round trip PER CHUNK — through a remote-chip tunnel each is a full
    ~0.1 s RTT regardless of size.  The whole-level restructure exists
    to batch these into ONE fetch per level; this rule keeps the pattern
    from growing back.  Note the sanctioned ``_fetch`` helper is flagged
    here too — being counted and off-loop does not make a per-chunk loop
    of fetches cheap — which is exactly the gap the host-sync rule (which
    deliberately never flags ``_fetch``) leaves open."""

    name = "chunked-device-readback"
    default_severity = "warning"

    _LOOPY = (
        ast.For, ast.AsyncFor, ast.While,
        # a comprehension of fetches is the same per-chunk pathology
        ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
    )

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.readback_modules):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = self._readback_kind(node)
            if kind is None:
                continue
            if not self._in_chunk_loop(mod, node):
                continue
            yield (
                *_span(node),
                f"{kind} inside a per-chunk loop costs one device round "
                "trip per iteration — batch the chunks into one "
                "whole-level readback (stack on device, fetch once after "
                "the loop)",
            )

    @classmethod
    def _in_chunk_loop(cls, mod: SourceModule, node: ast.AST) -> bool:
        for a in mod.ancestors(node):
            if isinstance(a, cls._LOOPY):
                return True
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    @staticmethod
    def _readback_kind(call: ast.Call) -> str | None:
        dn = dotted_name(call.func)
        if dn in _READBACK_DOTTED:
            return _READBACK_DOTTED[dn]
        if last_segment(dn) == "_fetch":
            return "_fetch"  # self._fetch / rpc._fetch forms
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "copy_to_host_async"
        ):
            return ".copy_to_host_async()"
        return None


# ---------------------------------------------------------------------------
# 8. unbounded-await
# ---------------------------------------------------------------------------

# attribute calls whose await can hang forever on a wedged/black-holed
# peer: stream reads, event/condition waits (incl. asyncio.wait itself)
_AWAIT_NET_METHODS = {"readexactly", "readuntil", "readline", "read", "wait"}


class UnboundedAwait(Rule):
    name = "unbounded-await"
    default_severity = "warning"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.await_modules):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Await) or not isinstance(
                node.value, ast.Call
            ):
                continue
            call = node.value
            dn = dotted_name(call.func)
            seg = last_segment(dn)
            if seg == "wait_for":
                # the bounded wrapper — unless its timeout is literally
                # None, which is an unbounded await in disguise
                if self._timeout_is_none(call):
                    yield (
                        *_span(node),
                        "wait_for with timeout=None is an unbounded "
                        "await in disguise — pass a finite deadline",
                    )
                continue
            if seg == "open_connection":
                yield (
                    *_span(node),
                    "await on a bare dial: the OS SYN timeout is minutes "
                    "— wrap in asyncio.wait_for with a dial timeout "
                    "(resilience.policy.DIAL_TIMEOUT_S)",
                )
                continue
            if (
                seg in _AWAIT_NET_METHODS
                and isinstance(call.func, ast.Attribute)
                and not self._has_finite_timeout(call)
            ):
                yield (
                    *_span(node),
                    f"await on '{dn or seg}(...)' carries no timeout or "
                    "deadline — a black-holed peer hangs this task "
                    "forever (pass timeout=, bound with asyncio.wait_for/"
                    "Deadline, or suppress with a justification)",
                )

    @staticmethod
    def _timeout_kwarg(call: ast.Call):
        for kw in call.keywords:
            if kw.arg == "timeout":
                return kw.value
        return None

    @classmethod
    def _timeout_is_none(cls, call: ast.Call) -> bool:
        t = call.args[1] if len(call.args) >= 2 else cls._timeout_kwarg(call)
        return isinstance(t, ast.Constant) and t.value is None

    @classmethod
    def _has_finite_timeout(cls, call: ast.Call) -> bool:
        t = cls._timeout_kwarg(call)
        if t is None:
            return False
        return not (isinstance(t, ast.Constant) and t.value is None)


# ---------------------------------------------------------------------------
# 9. span-discipline
# ---------------------------------------------------------------------------


class SpanDiscipline(Rule):
    """Telemetry-correctness pair for the obs layer (``span_modules``:
    protocol/, obs/, parallel/):

    1. ``reg.span(...)`` objects not used as ``with`` context managers —
       a span context that is never entered/exited records NOTHING (no
       timer, no trace event) while reading as if it instruments the
       code around it; a span entered but abandoned dangles forever in
       the heartbeat and the merged trace.  The one legitimate
       split-enter/exit site (WindowedIngest's per-window ingest span)
       carries an inline suppression with its justification.
    2. ``emit()``/``observe()`` calls inside jit-decorated functions —
       they run at TRACE time, once per compile, not once per
       execution: the metric silently records compile counts, not run
       counts (hoist the telemetry to the host-side caller)."""

    name = "span-discipline"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.span_modules):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"
            ):
                parent = mod.parent(node)
                if not (
                    isinstance(parent, ast.withitem)
                    and parent.context_expr is node
                ):
                    yield (
                        *_span(node),
                        "span(...) created outside a with statement — a "
                        "span context that never enters/exits records no "
                        "timer and dangles in the heartbeat/trace (use "
                        "`with reg.span(...):`, or suppress with a "
                        "justification where enter/exit are explicitly "
                        "managed)",
                    )
                continue
            seg = last_segment(dotted_name(node.func))
            if seg in ("emit", "observe"):
                chain = mod.enclosing_functions(node)
                jit_fn = next(
                    (f for f in chain if _is_jit_decorated(f)), None
                )
                if jit_fn is not None:
                    yield (
                        *_span(node),
                        f"telemetry call '{seg}(...)' inside jit-compiled "
                        f"function '{jit_fn.name}' runs at trace time — "
                        "it records once per COMPILE, never per "
                        "execution (hoist it to the host-side caller)",
                    )


# ---------------------------------------------------------------------------
# 10. unbounded-queue
# ---------------------------------------------------------------------------

# buffer constructors and the kwarg that bounds each.  SimpleQueue has no
# bound AT ALL, so its mere construction is the finding.
_QUEUE_CTORS = {
    "Queue": "maxsize",
    "LifoQueue": "maxsize",
    "PriorityQueue": "maxsize",
    "deque": "maxlen",
}


class UnboundedQueue(Rule):
    """Unbounded producer/consumer buffers in the ingest/transport
    modules (``queue_modules``).  ``asyncio.Queue()`` with no (or zero)
    ``maxsize`` and ``deque()`` with no ``maxlen`` grow without limit
    when the producer outruns the consumer — under a client flood that
    is an OOM, not backpressure.  The front door's contract is bounded
    pools + explicit shed/reject verdicts; a buffer that is provably
    bounded by construction carries an inline suppression saying why."""

    name = "unbounded-queue"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.queue_modules):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(dotted_name(node.func))
            if seg == "SimpleQueue":
                yield (
                    *_span(node),
                    "SimpleQueue has no maxsize at all — use Queue with "
                    "a positive maxsize (bounded buffers or explicit "
                    "shed, never silent growth)",
                )
                continue
            bound_kw = _QUEUE_CTORS.get(seg)
            if bound_kw is None:
                continue
            bound = self._bound_arg(node, seg, bound_kw)
            if bound is None:
                yield (
                    *_span(node),
                    f"{seg}() constructed without a {bound_kw} bound — "
                    "an overloaded producer grows it without limit "
                    f"(pass a positive {bound_kw}, or suppress with a "
                    "justification if it is bounded by construction)",
                )
            elif isinstance(bound, ast.Constant) and bound.value in (0, None):
                yield (
                    *_span(node),
                    f"{seg}({bound_kw}={bound.value!r}) is unbounded in "
                    f"disguise — pass a positive {bound_kw}",
                )

    @staticmethod
    def _bound_arg(call: ast.Call, seg: str, bound_kw: str):
        """The expression bounding this constructor, or None.  Queue's
        maxsize is its first positional; deque's maxlen is its second."""
        for kw in call.keywords:
            if kw.arg == bound_kw:
                return kw.value
        pos = 0 if bound_kw == "maxsize" else 1
        if len(call.args) > pos:
            return call.args[pos]
        return None


# ---------------------------------------------------------------------------
# 11. metric-naming
# ---------------------------------------------------------------------------

# a registry metric name: lowercase identifier chunk, optional ":sub"
# parts (the exporter folds a colon into a `key` label — obs/exporter.py)
_METRIC_NAME_RE = re.compile(r"[a-z][a-z0-9_]*(?::[a-z0-9_]+)*")
# identifier-LIKE: a literal that was plausibly meant as a metric name
# but is invalid (camelCase, dashes, dots).  Literals outside this shape
# (spaces, arbitrary punctuation) are substring-search arguments to
# str.count()-style calls, never metric names — skipping them keeps the
# rule zero-noise over the shared `count` method name.
_METRIC_LIKE_RE = re.compile(r"[A-Za-z0-9_.:\-]+")
# a hand-rolled exported-series literal (scrape parsers, exposition
# producers); the exporter's own f-string assembly is out of scope
# (JoinedStr fragments are never whole names)
_EXPORTED_RE = re.compile(r"fhh_[a-z0-9_]+")


class MetricNaming(Rule):
    """Exported series names must be valid Prometheus identifiers.

    Two checks over ``metric_modules``: (1) literal first arguments of
    the registry metric methods (``metric_calls``) must match
    ``[a-z][a-z0-9_]*`` with optional ``:sub`` parts — the exporter
    prefixes ``fhh_`` and appends ``_total``/``_seconds`` itself, so a
    conforming internal name IS a conforming series name; (2) a full
    ``fhh_...`` string literal (a hand-rolled exposition line or scrape
    key) must end with a recognized unit suffix
    (``metric_unit_suffixes``), because Prometheus consumers key on the
    unit token and a bare name reads as unitless."""

    name = "metric-naming"
    default_severity = "error"

    def check(self, mod: SourceModule, cfg):
        if not _under_prefix(mod.relpath, cfg.metric_modules):
            return
        # constants living inside f-strings are fragments, not names
        joined: set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.JoinedStr):
                joined.update(id(v) for v in node.values)
        suffixes = tuple(cfg.metric_unit_suffixes)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(node, cfg)
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in joined
                and _EXPORTED_RE.fullmatch(node.value)
                and not node.value.endswith(suffixes)
            ):
                yield (
                    *_span(node),
                    f"exported series literal {node.value!r} carries no "
                    "unit suffix "
                    f"({', '.join(suffixes[:4])}, ...) — Prometheus "
                    "consumers key on the unit token; rename it, or "
                    "suppress with a justification if it is not a "
                    "series name",
                )

    def _check_call(self, node: ast.Call, cfg):
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in cfg.metric_calls:
            return
        if not node.args:
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            return
        s = arg.value
        if _METRIC_NAME_RE.fullmatch(s):
            return
        # not even identifier-like (spaces, lone punctuation, no letter):
        # a substring-search argument, not a metric name attempt
        if not _METRIC_LIKE_RE.fullmatch(s):
            return
        if len(s) < 2 or not any(c.isalpha() for c in s):
            return
        yield (
            *_span(node),
            f"metric name {s!r} is not a valid Prometheus identifier "
            "chunk — use [a-z][a-z0-9_]* (optionally :sub, which the "
            "exporter folds into a key label); uppercase, dashes, and "
            "dots break the fhh_* exposition contract",
        )


ALL_RULES: tuple[Rule, ...] = (
    HostSyncInHotLoop(),
    SecretToSink(),
    RecompileChurn(),
    UnguardedSharedState(),
    BroadExcept(),
    BarePrint(),
    ChunkedDeviceReadback(),
    UnboundedAwait(),
    UnboundedQueue(),
    SpanDiscipline(),
    MetricNaming(),
    # the interprocedural fhh-race pair (analysis/concurrency.py)
    *RACE_RULES,
    # the interprocedural fhh-taint triple (analysis/taint.py) — in
    # taint_modules these supersede lexical secret-to-sink, which stays
    # on everywhere as the fast pre-filter (subset-tested)
    *TAINT_RULES,
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
