"""fhh-lint CLI: ``python -m fuzzyheavyhitters_tpu.analysis [paths ...]``.

Exit codes: 0 clean (every finding suppressed or baselined), 1 new
findings at/above the failure threshold (``error`` by default; any
severity under ``--strict``), 2 usage or internal error.

``--format json`` emits one machine-readable document on stdout (the
artifact scripts/lint.sh archives); human mode prints one line per
finding plus a summary.  ``--update-baseline`` rewrites the baseline to
the current tree's findings and exits 0 — the burn-down workflow is:
fix findings, run ``--update-baseline``, commit the shrunken file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import (
    apply_baseline,
    load_baseline,
    removed_rules,
    write_baseline,
)
from .config import find_repo_root, load_config
from .engine import iter_python_files, lint_paths
from .rules import RULES_BY_NAME


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m fuzzyheavyhitters_tpu.analysis",
        description="fhh-lint: AST static analysis for trace-safety, "
        "secret hygiene, and thread safety",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: from config)",
    )
    p.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="output format (default: human)",
    )
    p.add_argument(
        "--strict",
        action="store_true",
        help="fail on ANY new finding, warnings included",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="baseline JSON path (default: from config, repo-relative)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding is new",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to the current findings and exit 0",
    )
    p.add_argument(
        "--root",
        default=None,
        help="repo root (default: nearest ancestor with pyproject.toml)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    root = os.path.abspath(args.root) if args.root else find_repo_root()
    cfg = load_config(root)
    paths = args.paths or list(cfg.default_paths)
    missing, nonpy = [], []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            missing.append(p)
        elif os.path.isfile(ap) and not ap.endswith(".py"):
            nonpy.append(p)
    if missing:
        print(f"fhh-lint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    if nonpy:
        print(
            f"fhh-lint: not a Python file: {', '.join(nonpy)}",
            file=sys.stderr,
        )
        return 2
    # enumerate ONCE: the file list feeds the lint pass, and its relpath
    # set scopes both the stale-baseline check and --update-baseline's
    # keep logic to what this run actually linted
    files = list(iter_python_files(paths, root))
    scanned = {rel for _, rel in files}
    if not files:
        print(
            "fhh-lint: no Python files found under the given paths",
            file=sys.stderr,
        )
        return 2

    findings, errors = lint_paths(paths, cfg, root, files=files)

    baseline_path = args.baseline or os.path.join(root, cfg.baseline)
    if args.update_baseline:
        if errors:
            # an unparseable file would silently drop its counts from the
            # rewritten baseline — refuse instead
            for e in errors:
                print(f"fhh-lint: PARSE ERROR {e}", file=sys.stderr)
            print(
                "fhh-lint: refusing --update-baseline with parse errors",
                file=sys.stderr,
            )
            return 2
        # entries for files OUTSIDE this run's path set survive the
        # rewrite (a partial-tree update must not erase another subtree's
        # grandfathered findings) — but only while the file still exists:
        # deleted/renamed files' entries must be removable
        try:
            old = load_baseline(baseline_path)
        except (ValueError, OSError, json.JSONDecodeError):
            old = {}
        # a rule RENAME must not read as a silent burn-down: entries
        # under ids no rule carries anymore are named explicitly (the
        # operator verifies the successor id has its own entries — or
        # celebrates an actually-deleted rule)
        for rule, nfiles, n in removed_rules(old, RULES_BY_NAME):
            print(
                f"fhh-lint: dropping baseline entries for UNKNOWN rule "
                f"id '{rule}' ({n} finding(s) across {nfiles} file(s)) — "
                "renamed or removed rule; if renamed, confirm the new "
                "id's entries below",
                file=sys.stderr,
            )
        keep = {
            rule: {
                p: n
                for p, n in per_path.items()
                if p not in scanned and os.path.exists(os.path.join(root, p))
            }
            for rule, per_path in old.items()
            if rule in RULES_BY_NAME
        }
        write_baseline(baseline_path, findings, keep=keep)
        kept = sum(len(v) for v in keep.values() if v)
        print(
            f"fhh-lint: baseline rewritten with {len(findings)} finding(s)"
            + (f" (+{kept} unscanned entr{'y' if kept == 1 else 'ies'} kept)"
               if kept else "")
            + f" -> {os.path.relpath(baseline_path, root)}"
        )
        return 0

    counts = {}
    if not args.no_baseline:
        try:
            counts = load_baseline(baseline_path)
        except (ValueError, OSError, json.JSONDecodeError) as e:
            print(f"fhh-lint: bad baseline: {e}", file=sys.stderr)
            return 2
    res = apply_baseline(findings, counts, scanned=scanned)

    threshold = ("warning", "error") if args.strict else ("error",)
    failing = [f for f in res.new if f.severity in threshold]

    if args.format == "json":
        doc = {
            "schema": "fhh-lint-report/1",
            "root": root,
            "paths": paths,
            "strict": bool(args.strict),
            # the active rule ids: the artifact proves which passes ran
            # (CI asserts the fhh-race pair is among them)
            "rules": sorted(RULES_BY_NAME),
            "findings": [f.to_json() for f in res.new],
            "baselined": res.absorbed,
            "stale_baseline": [
                {"rule": r, "path": p, "extra": n} for r, p, n in res.stale
            ],
            "parse_errors": errors,
            "failing": len(failing),
        }
        print(json.dumps(doc, indent=1))
    else:
        for f in res.new:
            print(f.render())
        for e in errors:
            print(f"PARSE ERROR {e}")
        bits = [f"{len(res.new)} new finding(s)"]
        if res.absorbed:
            bits.append(f"{res.absorbed} baselined")
        if res.stale:
            bits.append(
                f"{len(res.stale)} stale baseline entr"
                f"{'y' if len(res.stale) == 1 else 'ies'} "
                "(run --update-baseline to bank the burn-down)"
            )
        if errors:
            bits.append(f"{len(errors)} parse error(s)")
        print("fhh-lint: " + ", ".join(bits))

    if errors:
        return 2
    return 1 if failing else 0
