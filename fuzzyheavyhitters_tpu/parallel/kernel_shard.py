"""Row-sharded secure-equality kernel stage: IKNP + 1-of-2^S / GC under
``shard_map`` on the server's local ``data`` mesh.

PR 8 sharded the CLIENT axis across each server's mesh but stopped at
the 2PC boundary: the packed share bits gathered over ICI onto ONE
device before the whole-level kernels ran, so the dominant secure phase
— extension, equality encrypt/garble, payload open, b2a — stayed
single-device no matter how many chips the server had.  This module
makes the kernel stage itself mesh-parallel with a **byte-identical
wire**:

- the whole-level planar test batch partitions along its ROW/BLOCK axis
  (units of ``gc_pallas.R_BLK * GROUP`` = 8192 tests — whole planar
  blocks, so each shard's slice of every wire plane is contiguous and
  the pallas grid needs no per-shard padding);
- the IKNP extension row-shards with it: the column PRG streams are
  CTR-mode and the packed butterfly transpose is word-local, so shard i
  computes exactly OT rows ``[t0*S, (t0 + bloc)*S)`` from the seeds +
  the matching u column-word slice (``otext.sender_extend_rows`` /
  ``receiver_extend_rows``) — bit-identical to the corresponding rows
  of a single-device extend;
- every per-test stream draw (b2a payload pair, GC labels + masks)
  seeks to its shard's slice of the SAME per-level stream
  (``gc._carve_label_words_shard``, the b2a block seek below), and every
  per-test pad index enters as ``idx0 + t0`` — so shard outputs are the
  exact planar-row slices of the single-device buffers;
- rows at or past the real batch (the planar pad region, which the
  uniform per-shard shapes cover) are ZERO-masked before anything
  wire-visible, reproducing the single-device ``_pad_tests`` padding
  byte for byte.  Those rows read stream blocks the session cursor has
  not consumed, but nothing derived from them survives the mask, so no
  cross-level stream material can reach the wire;
- the planar Pallas engines run UNDER ``shard_map`` (per-shard block
  shapes): on accelerator hosts the mesh no longer forces a gather to
  feed them — each shard runs the fused kernel on its own block span,
  with the XLA twins remaining the per-shard bit-parity oracle (and the
  CPU/tier-1 engine);
- no step between FSS expansion and the wire serializes onto device 0:
  the sender's frame and the receiver's u-matrix are read back PER
  SHARD (``copy_to_host_async`` double-buffering, the PR 5 pattern) and
  reassembled positionally on the host; the b2a share outputs psum back
  over ICI (``parallel.mesh.field_psum``) like every other pre-wire
  reduction.

Shard-count binding: the planar batch has ``padded_tests(B) // 8192``
blocks; the active kernel shard count is the largest divisor of that
block count that fits the budget (``Config.secure_kernel_shards``,
auto = the mesh's data shards) — a non-dividing batch DEGRADES to fewer
kernel shards (ultimately to the PR 8 gather path at k = 1) instead of
failing.  Byte-identity at every k is asserted in tier-1
(tests/test_kernel_shard.py) and gates the bench legs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import gc, gc_pallas, otext, prg
from ..ops.fields import F255, FE62
from ..ops.gc_pallas import GROUP, LANES, SUB, padded_tests
from .mesh import _shard_map, field_psum
from .server_mesh import DATA, _largest_divisor_leq, _mesh_for

# shard unit: one pallas grid step's worth of tests — the planar wire's
# natural block, so per-shard buffers concatenate along the row axis
BLOCK = gc_pallas.R_BLK * GROUP

# Test hook: force the Pallas engines (interpret mode) under shard_map
# on CPU hosts, where the engine flags normally fall back to the XLA
# twins — the per-shard parity oracle test flips this.
PALLAS_INTERPRET: bool = False

_FIELDS = {"FE62": FE62, "F255": F255}


def kernel_shards(B: int, budget: int) -> int:
    """Active kernel shard count for a ``B``-test level under a device
    ``budget``: the largest divisor of the planar block count <= budget
    (1 = the single-device gather path)."""
    nblk = padded_tests(B) // BLOCK
    return _largest_divisor_leq(nblk, max(1, int(budget)))


def _engine(path: str) -> str:
    """Static engine tag for the per-shard kernels: ``"pallas"`` on real
    chips when the module flags say so (gc.GC_PALLAS / secure.OT2S_PALLAS
    — the same dispatch the single-device packed entry points use),
    ``"pallas_interpret"`` under the test hook, else the XLA twins."""
    from ..protocol import secure
    from ..utils import effective_platform

    if PALLAS_INTERPRET:
        return "pallas_interpret"
    if effective_platform() == "cpu":
        return "xla"
    flag = gc.GC_PALLAS if path == "gc" else secure.OT2S_PALLAS
    return "pallas" if flag else "xla"


def n_msg_planes(path: str, S: int, W: int) -> int:
    """u32 planes of one whole-level wire message (each ``padded_tests``
    words): the 1-of-2^S ciphertext stack, or the packed garbled batch
    (tables | gb_labels | decode | cts)."""
    if path == "ot2s":
        return (1 << S) * W
    return (S - 1) * 2 * 4 + 4 * S + 1 + 2 * W


@dataclass(frozen=True)
class KernelShard:
    """One level's kernel-stage binding: ``k`` mesh devices, ``B`` real
    tests of string width ``S`` on a ``bp``-test planar frame."""

    devices: tuple
    B: int
    S: int

    @property
    def k(self) -> int:
        return len(self.devices)

    @property
    def bp(self) -> int:
        return padded_tests(self.B)

    @property
    def mesh(self):
        return _mesh_for(self.devices)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)


def bind(devices: tuple, B: int, S: int, budget: int) -> KernelShard | None:
    """Bind the kernel stage of a ``B``-test level to the leading mesh
    devices; ``None`` when only one shard fits (the caller keeps the
    single-device gather path)."""
    k = kernel_shards(B, min(int(budget), len(devices)))
    if k < 2:
        return None
    return KernelShard(devices=tuple(devices[:k]), B=B, S=S)


# ---------------------------------------------------------------------------
# Sharded program factories (one compiled SPMD program per shape, shared
# process-wide — warm and live hit the same executables, like
# server_mesh._counts_fn)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _flat_fn(d: int, F: int, N: int, bp: int, radix: int = 1):
    """packed u32[F, N] -> zero-padded flat strings bool[bp, 2*d*radix]
    (the whole-level test order (F, C, N), the planar frame extent).
    radix > 1 reads the fused radix layout: C = 2^(radix*d) children per
    frontier node, string width S' = 2*d*radix."""
    from ..protocol import secure

    def f(packed):
        strs = secure.child_strings_radix(packed, d, radix)  # [F, C, N, S']
        B = F * (1 << (d * radix)) * N
        flat = strs.reshape(B, 2 * d * radix)
        if bp != B:
            flat = jnp.concatenate(
                [flat, jnp.zeros((bp - B, 2 * d * radix), bool)]
            )
        return flat

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per level shape)
    return jax.jit(f)


def shard_flat(ks: KernelShard, packed, d: int, F: int, N: int,
               radix: int = 1):
    """The level's flat share-bit strings, row-sharded over the kernel
    mesh.  ``packed`` may carry any sharding (the client-axis mesh
    layout of the expansion): the flat build runs where packed lives and
    the result reshards onto the kernel submesh — an all-to-all-sized
    move of the SMALL pre-kernel tensor, never a gather onto one
    device."""
    flat = _flat_fn(d, F, N, ks.bp, radix)(packed)
    return jax.device_put(flat, ks.sharding(P(DATA, None)))


@lru_cache(maxsize=None)
def _snd_extend_fn(devices: tuple, B: int, S: int):
    """Row-sharded sender extension: (seeds, s_bits, u_pad, off) ->
    Q rows uint32[bp*S, 4] sharded along rows, global-pad rows zeroed."""
    ks = KernelShard(devices, B, S)
    k, bp = ks.k, ks.bp
    m_loc = bp * S // k
    m_real = B * S

    def body(seeds, s_bits, u_loc, off):
        row0 = jax.lax.axis_index(DATA).astype(jnp.int64) * m_loc
        q = otext.sender_extend_rows(seeds, s_bits, u_loc, off, row0, m_loc)
        live = (row0 + jnp.arange(m_loc)) < m_real
        return jnp.where(live[:, None], q, jnp.uint32(0))

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape))
    return jax.jit(
        _shard_map(
            body, mesh=ks.mesh,
            in_specs=(P(), P(), P(None, DATA), P()),
            out_specs=P(DATA, None),
        )
    )


@lru_cache(maxsize=None)
def _rcv_extend_fn(devices: tuple, B: int, S: int):
    """Row-sharded receiver extension: (seeds0, seeds1, flat, off) ->
    (u columns uint32[128, bp*S/32] sharded along words, T rows
    uint32[bp*S, 4] sharded along rows)."""
    ks = KernelShard(devices, B, S)
    k, bp = ks.k, ks.bp
    m_loc = bp * S // k

    def body(seeds0, seeds1, flat_loc, off):
        row0 = jax.lax.axis_index(DATA).astype(jnp.int64) * m_loc
        choices = flat_loc.reshape(m_loc)
        return otext.receiver_extend_rows(
            seeds0, seeds1, choices, off, row0, m_loc
        )

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape))
    return jax.jit(
        _shard_map(
            body, mesh=ks.mesh,
            in_specs=(P(), P(), P(DATA, None), P()),
            out_specs=(P(None, DATA), P(DATA, None)),
        )
    )


def _b2a_pair_shard(field, b2a_seed, B: int, bloc: int, t0, W: int,
                    garbler: int):
    """Shard slice [t0, t0 + bloc) of :func:`secure.b2a_payload_pair`'s
    per-level stream draw (word ``t*W`` onward for test t; ``t0*W`` is
    block-aligned because shards are whole planar blocks).  Returns
    (r1 — the sender's additive shares, w0, w1 payload words), with the
    payload words ZEROED for global-pad tests (the single-device twin
    pads them the same way)."""
    from ..protocol import secure

    nb = bloc * W // 16
    r_words = prg.stream_blocks(
        jnp.asarray(b2a_seed, jnp.uint32), nb, t0 * W // 16
    ).reshape(bloc, W)
    r0 = field.sample(r_words)
    one = field.from_int(1)
    r1 = field.sub(r0, one) if garbler else field.add(r0, one)
    w0 = secure.field_to_words(field, r0)
    w1 = secure.field_to_words(field, r1)
    live = (t0 + jnp.arange(bloc)) < B
    return r1, jnp.where(live[:, None], w0, 0), jnp.where(live[:, None], w1, 0)


@lru_cache(maxsize=None)
def _gb_kernel_fn(devices: tuple, field_name: str, B: int, S: int, W: int,
                  path: str, garbler: int, engine: str):
    """Row-sharded sender kernel: (q, s_block, flat, gc_seed, b2a_seed,
    idx0) -> (wire planes uint32[n_planes, rows, SUB, LANES] sharded
    along rows, vals — the sender's additive shares, test-sharded)."""
    from ..protocol import secure

    field = _FIELDS[field_name]
    ks = KernelShard(devices, B, S)
    k, bp = ks.k, ks.bp
    bloc = bp // k
    n_planes = n_msg_planes(path, S, W)
    interpret = engine == "pallas_interpret"

    def body(q_loc, s_block, flat_loc, gc_seed, b2a_seed, idx0):
        t0 = jax.lax.axis_index(DATA).astype(jnp.int64) * bloc
        q_rows = q_loc.reshape(bloc, S, 4)
        idx = idx0 + t0.astype(jnp.uint32)
        r1, w0, w1 = _b2a_pair_shard(
            field, b2a_seed, B, bloc, t0, W, garbler
        )
        # result 1 (strings equal) -> receiver learns r0, exactly
        # secure.gb_step_level's payload order (collect.rs:439-456)
        if path == "ot2s":
            if engine == "xla":
                msg = secure._ot2s_encrypt_packed_xla(
                    q_rows, s_block, flat_loc, w1, w0, W, idx
                )
            else:
                from ..ops import otext_pallas

                msg = otext_pallas.ot2s_encrypt(
                    q_rows, s_block, flat_loc, w1, w0, W, idx,
                    domain=secure._OT2S_DOMAIN, interpret=interpret,
                )
        else:
            X0, mask = gc._carve_label_words_shard(gc_seed, B, S, t0, bloc)
            if engine == "xla":
                msg = gc._garble_packed_planes_xla(
                    s_block, q_rows, X0, mask, flat_loc, w1, w0, W, idx
                )
            else:
                msg = gc_pallas.garble_packed_planes(
                    s_block, q_rows, X0, mask, flat_loc, w1, w0, W, idx,
                    interpret=interpret,
                )
        planes = msg.reshape(n_planes, bloc // GROUP, SUB, LANES)
        return planes, r1

    # pallas_call has no shard_map replication rule — drop the rep check
    # for the Pallas engines (the XLA twins keep it; specs are identical
    # either way and the parity test pins engine equality)
    kw = {} if engine == "xla" else {"check_rep": False}
    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape, path, engine))
    return jax.jit(
        _shard_map(
            body, mesh=ks.mesh,
            in_specs=(P(DATA, None), P(), P(DATA, None), P(), P(), P()),
            out_specs=(
                P(None, DATA, None, None),
                P(DATA) if field.limb_shape == () else P(DATA, None),
            ),
            **kw,
        )
    )


@lru_cache(maxsize=None)
def _ev_open_fn(devices: tuple, field_name: str, B: int, S: int, W: int,
                path: str, engine: str):
    """Row-sharded receiver open: (msg planes, t_rows, flat, idx0) ->
    field vals, test-sharded (r0 where equal, else r1; pad slots
    garbage, discarded by the share-sum scatter)."""
    from ..protocol import secure

    field = _FIELDS[field_name]
    ks = KernelShard(devices, B, S)
    k, bp = ks.k, ks.bp
    bloc = bp // k
    interpret = engine == "pallas_interpret"

    def body(msg_loc, t_loc, flat_loc, idx0):
        t0 = jax.lax.axis_index(DATA).astype(jnp.int64) * bloc
        idx = idx0 + t0.astype(jnp.uint32)
        t_rows = t_loc.reshape(bloc, S, 4)
        msg = jnp.ravel(msg_loc)
        if path == "ot2s":
            if engine == "xla":
                pay = secure._ot2s_decrypt_packed_xla(
                    t_rows, flat_loc, msg, S, W, idx
                )
            else:
                from ..ops import otext_pallas

                pay = otext_pallas.ot2s_decrypt(
                    t_rows, flat_loc, msg, W, idx,
                    domain=secure._OT2S_DOMAIN, interpret=interpret,
                )
        else:
            if engine == "xla":
                _, pay = gc._eval_equality_payload_packed_xla(
                    msg, t_rows, S, W, idx
                )
            else:
                _, pay = gc_pallas.eval_equality_payload_packed(
                    msg, t_rows, W, idx, interpret=interpret
                )
        return secure.words_to_field(field, pay)

    # see _gb_kernel_fn: pallas_call has no replication rule
    kw = {} if engine == "xla" else {"check_rep": False}
    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape, path, engine))
    return jax.jit(
        _shard_map(
            body, mesh=ks.mesh,
            in_specs=(P(None, DATA, None, None), P(DATA, None),
                      P(DATA, None), P()),
            out_specs=P(DATA) if field.limb_shape == () else P(DATA, None),
            **kw,
        )
    )


@lru_cache(maxsize=None)
def _share_sums_fn(devices: tuple, field_name: str, F: int, C: int, N: int,
                   B: int, bp: int):
    """Test-sharded b2a vals -> per-(node, pattern) share sums [F, C]:
    each shard scatters its flat slice into the (F, C, N) frame (zeros
    elsewhere — the additive identity), takes the alive-gated partial
    sum, and the partials fold with the overflow-safe split-limb
    ``field_psum`` over ICI.  Exact sum mod p, same value as the
    single-device ``secure.node_share_sums`` (addition mod p is order-
    independent; the leader canonicalizes on reconstruction like the
    PR 8 client-sharded reduction)."""
    from ..protocol import secure

    field = _FIELDS[field_name]
    ks_mesh = _mesh_for(devices)
    k = len(devices)
    bloc = bp // k
    limb = field.limb_shape

    def body(vals_loc, weight):
        t0 = jax.lax.axis_index(DATA).astype(jnp.int64) * bloc
        full = jnp.zeros((bp,) + limb, vals_loc.dtype)
        full = jax.lax.dynamic_update_slice(
            full, vals_loc, (t0,) + (0,) * len(limb)
        )
        v = full[:B].reshape((F, C, N) + limb)
        part = secure.node_share_sums(field, v, weight)
        return field_psum(field, part, DATA)

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape))
    return jax.jit(
        _shard_map(
            body, mesh=ks_mesh,
            in_specs=(
                P(DATA) if limb == () else P(DATA, None),
                P(),
            ),
            out_specs=P(),
        )
    )


# ---------------------------------------------------------------------------
# Protocol-step drivers (what protocol/rpc.py and warmup call)
# ---------------------------------------------------------------------------


def _u32(x) -> jax.Array:
    return jnp.asarray(np.uint32(x & 0xFFFFFFFF))


def snd_extend(ks: KernelShard, snd: otext.OtExtSender, u_np):
    """Sender half of the row-sharded extension: pad the peer's u-matrix
    to the planar word extent, extend per shard, advance the session
    cursor exactly like a single-device ``extend``.  Returns (Q rows
    sharded [bp*S, 4], idx0 — the pre-batch pad index base)."""
    B, S = ks.B, ks.S
    idx0 = snd.consumed
    off = snd.stream_offset
    u_np = np.asarray(u_np, np.uint32)
    wp = ks.bp * S // 32
    u_pad = np.zeros((128, wp), np.uint32)
    u_pad[:, : u_np.shape[1]] = u_np
    u_dev = jax.device_put(u_pad, ks.sharding(P(None, DATA)))
    seeds, s_bits = snd.shard_state
    q = _snd_extend_fn(ks.devices, B, S)(seeds, s_bits, u_dev, _u32(off))
    snd.advance(B * S)
    return q, idx0


def rcv_extend(ks: KernelShard, rcv: otext.OtExtReceiver, flat):
    """Receiver half: per-shard column streams + choices -> (u columns
    sharded [128, bp*S/32], T rows sharded [bp*S, 4], idx0).  The wire
    u-matrix is :func:`u_wire` of the first output."""
    B, S = ks.B, ks.S
    idx0 = rcv.consumed
    off = rcv.stream_offset
    seeds0, seeds1 = rcv.shard_state
    u, t = _rcv_extend_fn(ks.devices, B, S)(seeds0, seeds1, flat, _u32(off))
    rcv.advance(B * S)
    return u, t, idx0


def gb_kernel(ks: KernelShard, s_block, q, flat, gc_seed, b2a_seed, field,
              garbler: int, path: str, idx0: int, engine: str | None = None):
    """Sender whole-level kernel per shard (the 1-of-2^S table or the
    packed garbled batch): returns (wire plane stack sharded along rows,
    vals — the sender's additive shares r1 = r0 ± 1, test-sharded)."""
    from ..protocol import secure

    W = secure.payload_words(field)
    fn = _gb_kernel_fn(
        ks.devices, field.__name__, ks.B, ks.S, W, path, int(garbler),
        engine or _engine(path),
    )
    return fn(
        q, jnp.asarray(s_block, jnp.uint32), flat,
        jnp.asarray(gc_seed, jnp.uint32), jnp.asarray(b2a_seed, jnp.uint32),
        _u32(idx0),
    )


def ev_open(ks: KernelShard, t_rows, flat, msg_np, field, path: str,
            idx0: int, engine: str | None = None):
    """Receiver whole-level open per shard: uploads the wire frame
    row-sharded (host slices land directly on their devices — no
    single-device staging) and opens each shard's slice.  Returns vals
    test-sharded."""
    from ..protocol import secure

    W = secure.payload_words(field)
    path_planes = n_msg_planes(path, ks.S, W)
    planes = np.asarray(msg_np, np.uint32).reshape(
        path_planes, ks.bp // GROUP, SUB, LANES
    )
    msg_dev = jax.device_put(planes, ks.sharding(P(None, DATA, None, None)))
    fn = _ev_open_fn(
        ks.devices, field.__name__, ks.B, ks.S, W, path,
        engine or _engine(path),
    )
    return fn(msg_dev, t_rows, flat, _u32(idx0))


def share_sums(ks: KernelShard, field, vals, weight, F: int, C: int, N: int):
    """Alive-gated per-(node, pattern) share sums of test-sharded b2a
    vals, psum-folded over ICI — replicated [F, C] out (the caller
    fetches once)."""
    w = jax.device_put(np.ascontiguousarray(weight), ks.sharding(P()))
    return _share_sums_fn(
        ks.devices, field.__name__, F, C, N, ks.B, ks.bp
    )(vals, w)


# ---------------------------------------------------------------------------
# Per-shard readback + positional frame assembly (the PR 5 double-buffer
# pattern, one D2H stream per device instead of a gather onto one)
# ---------------------------------------------------------------------------


def start_host_copies(arr) -> int:
    """Kick off every shard's device->host DMA without blocking; returns
    the shard count (the caller's fetch accounting)."""
    shards = arr.addressable_shards
    for s in shards:
        fn = getattr(s.data, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # fhh-lint: disable=broad-except (pure prefetch hint: the sync np.asarray below still does the whole copy)
                pass
    return len(shards)

def assemble(arr) -> np.ndarray:
    """Per-shard device->host readbacks reassembled POSITIONALLY into
    the full frame: each shard's planar-row slice lands at its own index
    span of a preallocated host buffer — the sharded twin of one
    ``np.asarray`` on a single-device array, byte-identical output."""
    out = np.empty(arr.shape, arr.dtype)
    for s in arr.addressable_shards:
        # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (the sharded wire readback itself: one D2H per device, started async above, assembled positionally — this IS the sanctioned fetch)
        out[s.index] = np.asarray(s.data)
    return out


def u_wire(ks: KernelShard, u) -> np.ndarray:
    """Assembled wire u-matrix: the sharded padded columns cut back to
    the real extension width ceil(B*S/32) — byte-identical to the
    single-device ``extend``'s message."""
    wu = -(-ks.B * ks.S // 32)
    start_host_copies(u)
    return np.ascontiguousarray(assemble(u)[:, :wu])


def msg_wire(ks: KernelShard, planes) -> np.ndarray:
    """Assembled wire frame: the sharded plane stack raveled to the flat
    planar buffer (plane-major, rows concatenated in shard order) —
    byte-identical to the single-device packed message."""
    start_host_copies(planes)
    return assemble(planes).reshape(-1)


# ---------------------------------------------------------------------------
# In-process both-role driver (warmup + tests; the live socket path runs
# each half on its own server)
# ---------------------------------------------------------------------------


def run_level_pair(ks: KernelShard, snd: otext.OtExtSender,
                   rcv: otext.OtExtReceiver, flat_snd, flat_rcv,
                   gc_seed, b2a_seed, field, garbler: int, path: str,
                   engine: str | None = None):
    """One sharded whole-level 2PC, both roles in-process, wire arrays
    round-tripped through host numpy exactly like the socket path (jit
    executables key on input placements — see
    secure.warm_level_kernels).  Returns (u_np, msg_np, vals_snd,
    vals_rcv) with the vals still test-sharded on device."""
    u, t_rows, idx0_r = rcv_extend(ks, rcv, flat_rcv)
    u_np = u_wire(ks, u)
    q, idx0_s = snd_extend(ks, snd, u_np)
    planes, vals_s = gb_kernel(
        ks, snd.s_block, q, flat_snd, gc_seed, b2a_seed, field, garbler,
        path, idx0_s, engine=engine,
    )
    msg_np = msg_wire(ks, planes)
    vals_r = ev_open(
        ks, t_rows, flat_rcv, msg_np, field, path, idx0_r, engine=engine
    )
    return u_np, msg_np, vals_s, vals_r
