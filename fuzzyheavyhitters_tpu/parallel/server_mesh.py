"""Per-server multi-chip execution: the client axis sharded over a
LOCAL 1-D device mesh.

parallel/mesh.py is the WHOLE-PROTOCOL mesh (2-D ``{'servers': 2,
'data': k}``: both parties on one runtime, the inter-party exchange as
``ppermute`` collectives) — a single trust domain.  This module is the
production complement for the two-administrative-domain deployment
(protocol/rpc.py sockets): each :class:`CollectorServer` keeps its OWN
pjit mesh over its OWN chips and shards only the client axis across
them.  The paper's crawl cost is linear in clients per level (every
frontier node evaluates every client's ibDCF state, PAPER.md §0), and
the client axis is embarrassingly parallel until the per-node count
reduction — so:

- client key planes, ibDCF eval states, and the per-level FSS expansion
  shard along the client axis with ``NamedSharding`` (the existing jit
  programs partition under GSPMD — every op here is exact integer math,
  so the sharded programs are BIT-identical to the single-device ones);
- per-node partial count / field-share sums reduce across the local
  ``data`` axis over ICI (``shard_map`` bodies reusing
  :func:`parallel.mesh.field_psum`, the overflow-safe split-limb psum)
  BEFORE anything is fetched — the wire then carries the same few-KB
  per-level payloads as a single-device server;
- the GC/OT wire stage is deliberately NOT resharded: the planar wire
  message is the single-device layout by construction, so the 2PC
  transcript (and with it the peer server and the leader) cannot tell a
  sharded server from a single-device one.

This is what turns "millions of users" from a throughput statement into
a memory-capacity statement: per-chip HBM holds ``N / k`` clients'
frontier state, and k grows with the server's chip count.

Layout note: the mesh path pins the XLA expand engine (interleaved
``[F, N, d, 2]`` frontier states — the client axis is a plain named
axis; the planar Pallas engine's pallas_call does not take sharded
operands), exactly like the 2-D mesh bodies do.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ibdcf import EvalState
from .mesh import _shard_map, field_psum

DATA = "data"


@lru_cache(maxsize=None)
def _mesh_for(devices: tuple) -> Mesh:
    """One Mesh object per device tuple, shared by every ServerMesh in
    the process — the reduction kernels below are module-level jit
    objects keyed on it, so two servers (or a warm run and a live run)
    over the same devices share ONE compiled program per shape instead
    of compiling per instance."""
    return Mesh(np.asarray(devices), (DATA,))


@lru_cache(maxsize=None)
def _counts_fn(devices: tuple):
    from ..protocol import collect

    mesh = _mesh_for(devices)

    def body(ps, pp, m, ak, an):
        return jax.lax.psum(
            collect.counts_by_pattern(ps, pp, m, ak, an), DATA
        )

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per device set)
    return jax.jit(
        _shard_map(
            body, mesh=mesh,
            in_specs=(P(None, DATA), P(None, DATA), P(), P(DATA), P()),
            out_specs=P(),
        )
    )


@lru_cache(maxsize=None)
def _share_sums_fn(devices: tuple, field_name: str):
    from ..ops.fields import F255, FE62
    from ..protocol import secure

    field = {"FE62": FE62, "F255": F255}[field_name]
    mesh = _mesh_for(devices)

    def body(v, w):
        return field_psum(field, secure.node_share_sums(field, v, w), DATA)

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per device set and field)
    return jax.jit(
        _shard_map(
            body, mesh=mesh,
            in_specs=(P(None, None, DATA), P(None, None, DATA)),
            out_specs=P(),
        )
    )


def resolve_data_devices(requested: int) -> int:
    """How many local devices a server's mesh should span.

    ``requested`` 0 = auto: all visible local devices on an accelerator
    host, ONE on a CPU host (virtual host-platform devices exist for the
    test/bench meshes; a production CPU server gains nothing from
    sharding its own host).  Explicit requests are capped at the visible
    device count."""
    from ..utils import effective_platform

    try:
        avail = len(jax.local_devices())
    except RuntimeError:  # no backend: single-device semantics
        return 1
    if requested <= 0:
        return avail if effective_platform() != "cpu" else 1
    return max(1, min(int(requested), avail))


def _largest_divisor_leq(n: int, k: int) -> int:
    """Largest divisor of ``n`` that is <= ``k`` (shard counts must tile
    the client batch exactly — shard_map and the checkpoint re-shard
    path both require it)."""
    k = max(1, min(k, n)) if n > 0 else 1
    while n % k:
        k -= 1
    return k


class ServerMesh:
    """One collector server's local data-parallel mesh.

    Construct with the device budget (:func:`resolve_data_devices`),
    then :meth:`bind` to a client batch — the ACTIVE shard count is the
    largest divisor of the batch that fits the budget, so a prime-sized
    batch degrades to fewer shards instead of failing.  All placement
    helpers and the shard_map reduction kernels hang off the bound mesh;
    re-binding (a new collection's batch) rebuilds them.
    """

    def __init__(self, n_devices: int):
        self.devices = tuple(jax.local_devices()[: max(1, n_devices)])
        self.n_devices = len(self.devices)
        self.shards = 1
        self.n_clients: int | None = None
        self.mesh: Mesh | None = None

    def bind(self, n_clients: int) -> "ServerMesh":
        """Fix the active mesh for an ``n_clients`` batch."""
        k = _largest_divisor_leq(n_clients, self.n_devices)
        self.mesh = _mesh_for(self.devices[:k])
        self.shards = k
        self.n_clients = int(n_clients)
        return self

    def occupancy(self) -> list:
        """Clients per shard (uniform by construction: the shard count
        divides the batch)."""
        if self.n_clients is None:
            return []
        return [self.n_clients // self.shards] * self.shards

    # -- placement --------------------------------------------------------

    def put(self, arr, spec: P):
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def shard_keys(self, keys):
        """Key batch leaves ``[N, ...]`` -> client axis sharded."""
        return jax.tree.map(lambda a: self.put(a, P(DATA)), keys)

    def shard_frontier(self, frontier):
        """Interleaved-layout frontier (states ``[F, N, d, 2, ...]``) ->
        client axis sharded, alive mask replicated.  Used at tree_init
        and checkpoint restore; mid-crawl the sharding propagates
        through the jitted advance programs on its own."""
        st = frontier.states
        states = EvalState(
            seed=self.put(st.seed, P(None, DATA)),
            bit=self.put(st.bit, P(None, DATA)),
            y_bit=self.put(st.y_bit, P(None, DATA)),
        )
        return frontier._replace(
            states=states, alive=self.put(frontier.alive, P())
        )

    def gather(self, arr):
        """Collapse a client-axis-sharded array back onto ONE device
        (the mesh's first).  Since PR 10 this is only the kernel stage's
        DEGRADED path: a level whose planar batch yields a single kernel
        shard (``kernel_bind`` -> None) still gathers the packed share
        bits over ICI before string extraction; any level with >= 2
        whole planar blocks runs the row-sharded kernel stage
        (parallel/kernel_shard.py) and never touches this.  A pure
        layout move: values are untouched, bit-identity holds by
        construction."""
        return jax.device_put(arr, self.devices[0])

    def kernel_budget(self, requested: int) -> int:
        """Device budget for the secure kernel stage
        (``Config.secure_kernel_shards``): 0 = auto follows the bound
        data shards; explicit requests cap at them (the kernel mesh is a
        leading submesh of the data mesh)."""
        if requested <= 0:
            return self.shards
        return max(1, min(int(requested), self.shards))

    def kernel_bind(self, B: int, S: int, requested: int):
        """Bind the row-sharded kernel stage for a ``B``-test level
        (parallel/kernel_shard.KernelShard), or None when the batch only
        fills one planar block per the budget — the caller then keeps
        the :meth:`gather` path.  Pure (lru-cached mesh machinery
        underneath): safe from the unlocked frame-arrival pre-expand."""
        from . import kernel_shard

        return kernel_shard.bind(
            self._active_devices(), B, S, self.kernel_budget(requested)
        )

    # -- ICI reductions (the pre-wire psum hooks) -------------------------

    def _active_devices(self) -> tuple:
        return self.devices[: self.shards]

    def counts_by_pattern(self, packed_self, packed_peer, masks,
                          alive_keys, alive_nodes):
        """Trusted-mode per-node counts with the client-axis sum taken
        PER SHARD and psum-reduced over ICI — the [F, 2^d] result is
        replicated, so the wire/leader sees the single-device value
        (uint32 sums are exact; order is irrelevant).

        Inputs are eagerly placed to their canonical shardings first:
        the executable cache keys on input shardings, and the producers
        vary (jit outputs, wire numpy, warmup twins) — normalizing here
        pins ONE compiled program per shape for warm and live alike."""
        return _counts_fn(self._active_devices())(
            self.put(packed_self, P(None, DATA)),
            self.put(jnp.asarray(packed_peer), P(None, DATA)),
            self.put(jnp.asarray(masks), P()),
            self.put(jnp.asarray(alive_keys), P(DATA)),
            self.put(alive_nodes, P()),
        )

    def node_share_sums(self, field, vals, weight):
        """Secure-mode per-(node, pattern) share sums: per-shard
        ``field.sum`` partials folded with the overflow-safe split-limb
        :func:`parallel.mesh.field_psum` over the local ``data`` axis.
        Bit-identical to the single-device ``secure.node_share_sums``
        (both are the exact sum mod p in canonical form).  Inputs are
        canonically placed first (see :meth:`counts_by_pattern`)."""
        return _share_sums_fn(self._active_devices(), field.__name__)(
            self.put(jnp.asarray(vals), P(None, None, DATA)),
            self.put(jnp.asarray(weight), P(None, None, DATA)),
        )
