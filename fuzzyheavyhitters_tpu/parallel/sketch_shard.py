"""Row-sharded, device-resident sketch verification under ``shard_map``.

The malicious-secure sketch verify (protocol/rpc.py ``sketch_verify``)
used to run as a host loop: ``sketch_batch_size`` client chunks, each
chunk a fresh device dispatch + TWO wire round trips (cor exchange, out
exchange) — the exact shape every perf PR since the planar wire removed
from the semi-honest lane.  This module brings the sketch checks into
the same fast lane as the GC/OT kernel stage (parallel/kernel_shard.py):

- the per-level check batch — (client, dim) rows of the three MAC/square
  checks (protocol/mpc.py) — partitions along the CLIENT axis across the
  server's local ``data`` mesh; every per-row computation (sketch inner
  products, Beaver cor/out shares, the verdict) is client-parallel, so
  there is no cross-shard reduction at all;
- the challenge ratchet stream is absorbed PER SHARD deterministically:
  shard i derives exactly its (client·dim)-row slice of the single-device
  challenge stream by CTR seek (``sketch.challenge_rands`` — the same
  seek-by-offset discipline as ``otext.sender_extend_rows``), and the
  per-node r vector from the replicated seed, so shard outputs are the
  exact row slices of the single-device state;
- cor and out openings read back PER SHARD (``copy_to_host_async``
  double-buffering) and reassemble POSITIONALLY into a byte-identical
  wire — the peer cannot tell a sharded verifier from an unsharded one;
- the whole level is ONE fused program per stage per ``f_bucket`` rung
  (the stored pair shares are bucket-padded, so program identity is
  (bucket, batch, field) — ``level`` and the stream offsets enter as
  traced scalars and never recompile), with a single post-level readback
  of the verdict vector.

Shard binding: the active shard count is the largest divisor of the
client batch that fits the budget (``Config.sketch_shards``, auto = the
mesh's data shards) — a non-dividing batch DEGRADES to fewer shards, and
k = 1 (or a meshless server) runs the same fused math as ONE plain jit
program on the default device (:func:`bind` returns None).  Bit-identity
of the challenge stream, both wire messages, and the verdict vector at
every k is asserted in tier-1 (tests/test_sketch_shard.py) and gates the
``bench_sketch`` legs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.fields import F255, FE62
from .kernel_shard import assemble, start_host_copies
from .mesh import _shard_map
from .server_mesh import DATA, _largest_divisor_leq, _mesh_for

_FIELDS = {"FE62": FE62, "F255": F255}


def sketch_shards(n_clients: int, budget: int) -> int:
    """Active shard count for an ``n_clients`` verify under a device
    budget: the largest divisor of the batch <= the budget (1 = the
    single-program path)."""
    return _largest_divisor_leq(n_clients, max(1, int(budget)))


@dataclass(frozen=True)
class SketchShard:
    """One level batch's sketch-verify binding: ``k`` mesh devices over
    an ``N``-client, ``d``-dim check batch."""

    devices: tuple
    N: int
    d: int

    @property
    def k(self) -> int:
        return len(self.devices)

    @property
    def mesh(self):
        return _mesh_for(self.devices)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)


def bind(devices: tuple, N: int, d: int, budget: int) -> SketchShard | None:
    """Bind the sketch verify to the leading mesh devices; ``None`` when
    only one shard fits (the caller runs the single fused program)."""
    k = sketch_shards(N, min(int(budget), len(devices)))
    if k < 2:
        return None
    return SketchShard(devices=tuple(devices[:k]), N=N, d=d)


def _state_specs():
    from ..protocol import mpc

    return mpc.MulStateBatch(
        xs=P(DATA), ys=P(DATA), zs=P(DATA), rs=P(DATA),
        triples=mpc.TripleBatch(a=P(DATA), b=P(DATA), c=P(DATA)),
    )


# ---------------------------------------------------------------------------
# Fused program factories (one compiled program per shape, shared
# process-wide — warm and live hit the same executables)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _cor_state_fn(devices: tuple, field_name: str, m: int, N: int, d: int):
    """Sharded stage 1: (pairs, triple slab, MAC shares, seed, level) ->
    (cor-share wire stack uint/field[2, N, d, CHECKS(, limbs)] sharded
    along clients, the per-shard check state — kept on device for stage
    2).  Each shard derives its own slice of the challenge stream."""
    from ..protocol import mpc, sketch as sketchmod

    field = _FIELDS[field_name]
    k = len(devices)
    n_loc = N // k

    def body(pairs_loc, ta, tb, tc, mk, mk2, seed, level):
        row0 = jax.lax.axis_index(DATA) * (n_loc * d)
        st = sketchmod.level_check_state(
            field, pairs_loc, mpc.TripleBatch(a=ta, b=tb, c=tc), mk, mk2,
            seed, level, row0,
        )
        return jnp.stack(mpc.cor_share(field, st)), st

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape, field))
    return jax.jit(
        _shard_map(
            body, mesh=_mesh_for(devices),
            in_specs=(
                P(None, DATA), P(DATA), P(DATA), P(DATA), P(DATA), P(DATA),
                P(), P(),
            ),
            out_specs=(P(None, DATA), _state_specs()),
        )
    )


@lru_cache(maxsize=None)
def _cor_state_single_fn(field_name: str, m: int, N: int, d: int):
    """The k = 1 twin of :func:`_cor_state_fn`: the same fused math as
    one plain jit program on the default device (no mesh, no placement
    constraints — the meshless server's path and the bit-identity
    reference the sharded form is gated against)."""
    from ..protocol import mpc, sketch as sketchmod

    field = _FIELDS[field_name]

    def f(pairs, ta, tb, tc, mk, mk2, seed, level):
        st = sketchmod.level_check_state(
            field, pairs, mpc.TripleBatch(a=ta, b=tb, c=tc), mk, mk2,
            seed, level, 0,
        )
        return jnp.stack(mpc.cor_share(field, st)), st

    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (shape, field))
    return jax.jit(f)


@lru_cache(maxsize=None)
def _out_fn(devices: tuple | None, field_name: str, N: int, d: int,
            server_idx: bool):
    """Stage 2: (state, own cor stack, peer cor stack) -> this party's
    out shares field[N, d(, limbs)].  The cor opening (add both stacks)
    fuses into the same program, so the peer's wire upload is the only
    host->device move of the stage."""
    from ..protocol import mpc

    field = _FIELDS[field_name]

    def body(xs, ys, zs, rs, ta, tb, tc, cor_mine, cor_peer):
        st = mpc.MulStateBatch(
            xs=xs, ys=ys, zs=zs, rs=rs,
            triples=mpc.TripleBatch(a=ta, b=tb, c=tc),
        )
        opened = (
            field.add(cor_mine[0], cor_peer[0]),
            field.add(cor_mine[1], cor_peer[1]),
        )
        return mpc.out_share(field, server_idx, st, opened)

    if devices is None:
        # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (shape, field, role))
        return jax.jit(body)
    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape, field, role))
    return jax.jit(
        _shard_map(
            body, mesh=_mesh_for(devices),
            in_specs=(
                P(DATA), P(DATA), P(DATA), P(DATA), P(DATA), P(DATA),
                P(DATA), P(None, DATA), P(None, DATA),
            ),
            out_specs=P(DATA),
        )
    )


@lru_cache(maxsize=None)
def _verdict_fn(devices: tuple | None, field_name: str, N: int, d: int):
    """Stage 3: (own out shares, peer out shares) -> per-client verdict
    bool[N] (a client passes iff EVERY dim's checks sum to zero)."""
    from ..protocol import mpc

    field = _FIELDS[field_name]

    def body(o_mine, o_peer):
        return jnp.all(mpc.verify(field, o_mine, o_peer), axis=1)

    if devices is None:
        # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (shape, field))
        return jax.jit(body)
    # fhh-lint: disable=recompile-churn (lru_cached factory: built once per (devices, shape, field))
    return jax.jit(
        _shard_map(
            body, mesh=_mesh_for(devices),
            in_specs=(P(DATA), P(DATA)),
            out_specs=P(DATA),
        )
    )


# ---------------------------------------------------------------------------
# Protocol-step drivers (what protocol/rpc.py and warmup call)
# ---------------------------------------------------------------------------


def _put(ss: SketchShard | None, a, spec: P):
    """Canonical placement: sharded inputs land on their NamedSharding
    eagerly (the executable cache keys on input shardings — warm and
    live must hit ONE program per shape); the single-program path takes
    inputs as-is on the default device."""
    a = jnp.asarray(a)
    if ss is None:
        return a
    return jax.device_put(a, ss.sharding(spec))


def cor_state(ss: SketchShard | None, field, pairs, trip, mk, mk2, seed,
              level: int):
    """Stage 1 dispatch: returns (cor wire stack on device, the check
    state — device-resident, fed to :func:`out_shares`)."""
    m = int(pairs.shape[0])
    N, d = int(pairs.shape[1]), int(pairs.shape[2])
    args = (
        _put(ss, pairs, P(None, DATA)),
        _put(ss, trip.a, P(DATA)), _put(ss, trip.b, P(DATA)),
        _put(ss, trip.c, P(DATA)),
        _put(ss, mk, P(DATA)), _put(ss, mk2, P(DATA)),
        _put(ss, np.asarray(seed, np.uint32), P()),
        _put(ss, np.uint32(level), P()),
    )
    if ss is None:
        return _cor_state_single_fn(field.__name__, m, N, d)(*args)
    return _cor_state_fn(ss.devices, field.__name__, m, N, d)(*args)


def out_shares(ss: SketchShard | None, field, state, cor_mine, cor_peer_np,
               server_idx: bool):
    """Stage 2 dispatch: the peer's cor wire uploads row-sharded (host
    slices land directly per device) and opens against the carried
    state."""
    N, d = int(state.xs.shape[0]), int(state.xs.shape[1])
    fn = _out_fn(
        None if ss is None else ss.devices, field.__name__, N, d,
        bool(server_idx),
    )
    return fn(
        state.xs, state.ys, state.zs, state.rs,
        state.triples.a, state.triples.b, state.triples.c,
        cor_mine, _put(ss, np.asarray(cor_peer_np), P(None, DATA)),
    )


def verdicts(ss: SketchShard | None, field, o_mine, o_peer_np):
    """Stage 3 dispatch: device verdict vector bool[N] — the level's
    SINGLE post-level readback happens at the caller."""
    N, d = int(o_mine.shape[0]), int(o_mine.shape[1])
    fn = _verdict_fn(
        None if ss is None else ss.devices, field.__name__, N, d
    )
    return fn(o_mine, _put(ss, np.asarray(o_peer_np), P(DATA)))


def wire(arr) -> np.ndarray:
    """One wire message: per-shard device->host DMAs kicked off without
    blocking, then reassembled POSITIONALLY into the full frame — the
    sharded twin of one ``np.asarray``, byte-identical output (and
    exactly that for a single-device array: one shard, one copy)."""
    start_host_copies(arr)
    return assemble(arr)


# ---------------------------------------------------------------------------
# Test/bench surface: the trusted challenge stream per shard
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _stream_parts_fn(devices: tuple | None, field_name: str, m: int,
                     N: int, d: int):
    from ..protocol import sketch as sketchmod

    field = _FIELDS[field_name]

    def body(seed, level):
        if devices is None:
            row0 = 0
            n_loc = N
        else:
            n_loc = N // len(devices)
            row0 = jax.lax.axis_index(DATA) * (n_loc * d)
        r = sketchmod.challenge_r(field, seed, level, m)
        rands = sketchmod.challenge_rands(
            field, seed, level, m, row0, n_loc * d
        )
        return r, rands

    if devices is None:
        # fhh-lint: disable=recompile-churn (lru_cached factory: test/bench surface)
        return jax.jit(body)
    # fhh-lint: disable=recompile-churn (lru_cached factory: test/bench surface)
    return jax.jit(
        _shard_map(
            body, mesh=_mesh_for(devices), in_specs=(P(), P()),
            out_specs=(P(), P(DATA)),
        )
    )


def stream_parts(ss: SketchShard | None, field, seed, level: int, m: int,
                 N: int, d: int):
    """(r, rands) of one level's challenge stream, assembled across
    shards — the bit-identity surface tests and the bench gate compare
    against the single-device ``shared_r_stream`` draw."""
    fn = _stream_parts_fn(
        None if ss is None else ss.devices, field.__name__, m, N, d
    )
    r, rands = fn(
        _put(ss, np.asarray(seed, np.uint32), P()),
        _put(ss, np.uint32(level), P()),
    )
    return np.asarray(r), np.asarray(rands)


# ---------------------------------------------------------------------------
# Warmup: compile the fused verify chain without touching live state
# ---------------------------------------------------------------------------


def warm_verify(ss: SketchShard | None, field, m: int, N: int, d: int,
                server_idx: bool) -> None:
    """Run the whole fused cor -> out -> verdict chain on throwaway
    zero inputs at one (bucket ``m``, batch) rung, with both wire
    messages round-tripping through host numpy exactly like the live
    socket path (jit executables key on input placements — see
    ``secure.warm_level_kernels``), so a warmed malicious crawl
    dispatches ZERO fresh compiles at this shape."""
    from ..protocol import mpc

    pairs = field.zeros((m, N, d, 2))
    trip = mpc.TripleBatch(
        a=field.zeros((N, d, mpc.CHECKS)),
        b=field.zeros((N, d, mpc.CHECKS)),
        c=field.zeros((N, d, mpc.CHECKS)),
    )
    mk = field.zeros((N,))
    mk2 = field.zeros((N,))
    seed = np.zeros(4, np.uint32)
    cor, st = cor_state(ss, field, pairs, trip, mk, mk2, seed, 0)
    cor_np = wire(cor)
    o = out_shares(ss, field, st, cor, cor_np, server_idx)
    o_np = wire(o)
    ok = verdicts(ss, field, o, o_np)
    np.asarray(ok)  # the post-level verdict readback path
