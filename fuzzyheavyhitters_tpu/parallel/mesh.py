"""Device-mesh execution of the two-server protocol.

The reference's distribution fabric is processes + sockets: two server
binaries joined by a TCP channel mesh carrying GC/OT traffic
(ref: server.rs:197-262), rayon threads inside each (SURVEY.md §2
parallelism table).  The TPU-native fabric is a 2-D ``jax.sharding.Mesh``:

- axis ``servers`` (size 2): the two-party MPC topology.  Party p's keys and
  frontier live on the devices of mesh row p; the only inter-party traffic —
  one packed uint32 of share bits per (node, client) per level — moves by a
  single ``ppermute`` swap across this axis (the ICI replacement for the
  reference's per-core TCP socket mesh).
- axis ``data`` (size k): client data parallelism.  The client batch ``N``
  is sharded k ways (the reference's rayon ``par_iter`` over clients,
  collect.rs:94-119, become per-shard tensor blocks); per-node counts
  finish with a ``psum`` over this axis.

Every collective rides the mesh; the host (leader) only sees final counts —
mirroring the reference's leader↔server RPC split where per-level counts are
the only thing returned (rpc.rs:60-61).  The sharded kernels are built and
jitted ONCE per runner; ``level`` and the survivor table are traced scalars,
so a full ``data_len``-level crawl compiles exactly two programs.
"""

from __future__ import annotations

import secrets as _secrets
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.31 exposes shard_map at the top level; 0.4.x keeps it
    _shard_map = jax.shard_map  # under experimental — accept both so the
except AttributeError:  # mesh path runs on every baked-in runtime
    from jax.experimental.shard_map import shard_map as _shard_map

from ..obs import metrics as obsmetrics
from ..ops import baseot, gc, otext, prg
from ..ops.fields import F255, FE62
from ..ops.ibdcf import IbDcfKeyBatch
from ..protocol import collect, secure
from ..protocol.collect import EvalState, Frontier

SERVERS = "servers"
DATA = "data"

# sharding spec of a party-stacked key batch [2, N, d, 2, ...]
_KEY_SPEC = IbDcfKeyBatch(
    key_idx=P(SERVERS, DATA),
    root_seed=P(SERVERS, DATA),
    cw_seed=P(SERVERS, DATA),
    cw_bits=P(SERVERS, DATA),
    cw_y_bits=P(SERVERS, DATA),
)


def field_psum(field, v, axis_name):
    """Modular psum: sum field elements over a mesh axis without overflow.

    FE62/U63 values are u64 scalars — a raw psum over k shards can exceed
    2^64; splitting into 32-bit halves keeps every partial sum exact, then
    recombines mod p (the collective twin of field.sum's split trick).
    F255 limbs go through u64 so the k-way limb sums stay exact, then one
    carry chain + 2^256 === 38 fold renormalizes."""
    if field is F255:
        l64 = jax.lax.psum(jnp.asarray(v, jnp.uint64), axis_name)
        limbs, carry = F255._carry_chain(l64)
        for _ in range(2):  # settle 2^256 === 38 wraps (cf. F255.mul's tail)
            limbs, carry = F255._carry_chain(
                limbs.at[..., 0].add(carry * jnp.uint64(38))
            )
        limbs = limbs.astype(jnp.uint32)
        limbs = F255._sub_p_if(limbs, F255._geq_p(limbs))
        return F255._sub_p_if(limbs, F255._geq_p(limbs))
    mask32 = jnp.uint64(0xFFFFFFFF)
    v = jnp.asarray(v, jnp.uint64)
    lo = jax.lax.psum(v & mask32, axis_name)
    hi = jax.lax.psum(v >> 32, axis_name)
    return field.add(field.new(lo), field.mul(field.new(hi), field.from_int(1 << 32)))


def init_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join a multi-host JAX runtime (the DCN scale-out entry point).

    After this, ``jax.devices()`` is the GLOBAL device list,
    :func:`make_mesh` accepts it, and the shard_mapped crawl programs
    compile for the multi-host mesh with XLA routing each collective over
    ICI within a slice and DCN across slices — the scale-out axis the
    reference covers with tarpc + TCP socket meshes (SURVEY.md §2
    "distributed communication backend").  Arguments default to JAX's
    standard env/cluster autodetection (``jax.distributed.initialize``
    semantics).

    Multi-process host seams (tests/test_mesh_multiprocess.py runs them
    for real with two processes): ingest via
    :meth:`MeshRunner.from_process_local` — each process supplies only
    its own mesh row's key batch, combined with
    ``jax.make_array_from_process_local_data`` — and secure-mode session
    material (base-OT seeds, session seed) is agreed from process 0 via
    ``broadcast_one_to_all``.  NB the mesh transport is a single TRUST
    domain (the runtime sees both parties' material; use the socket
    transport, protocol/rpc.py, for two-administrative-domain
    deployments); multi-host here is the SCALE axis.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """2 × (n/2) mesh: first axis the two servers, rest data parallel.

    ``devices`` may be local chips or (after :func:`init_distributed`) the
    global multi-host device list."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devices)
    assert n % 2 == 0, f"need an even device count for the 2-server axis, got {n}"
    arr = np.asarray(devices).reshape(2, n // 2)
    return Mesh(arr, (SERVERS, DATA))


def _stack_parties(t0, t1):
    return jax.tree.map(lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]), t0, t1)


class MeshRunner:
    """Holds both parties' device-resident state, sharded over the mesh.

    Leading axes of every tensor: [party=2, ...] with party sharded over
    ``servers`` and the client axis sharded over ``data``.  The PRG bit mode
    (prg.DERIVED_BITS) is captured at construction; a runner never mixes
    modes mid-crawl.
    """

    def __init__(
        self,
        mesh: Mesh,
        keys0: IbDcfKeyBatch | None,
        keys1: IbDcfKeyBatch | None,
        f_max: int,
        secure_exchange: bool = False,
        min_bucket: int = 1,
        _global_keys: IbDcfKeyBatch | None = None,
    ):
        self.mesh = mesh
        self.f_max = f_max
        self.min_bucket = min_bucket  # pin >1 only on compile-bound hosts
        self.secure = secure_exchange
        self._derived = prg.DERIVED_BITS
        self._key_spec = _KEY_SPEC
        if _global_keys is not None:  # from_process_local path
            self.keys = _global_keys
        else:
            keys = _stack_parties(keys0, keys1)  # [2, N, d, 2, ...]
            self.keys = jax.tree.map(
                lambda a, s: self._host_put(a, s), keys, _KEY_SPEC
            )
        n = self.keys.cw_seed.shape[1]
        self.n_dims = self.keys.cw_seed.shape[2]
        self.data_len = self.keys.cw_seed.shape[-2]
        assert n % mesh.shape[DATA] == 0, (
            f"client count {n} must divide the data axis {mesh.shape[DATA]}"
        )
        self.alive_keys = self._host_put(np.ones((2, n), bool), P(SERVERS, DATA))
        self._frontier_spec = Frontier(
            states=EvalState(
                seed=P(SERVERS, None, DATA),
                bit=P(SERVERS, None, DATA),
                y_bit=P(SERVERS, None, DATA),
            ),
            alive=P(SERVERS, None),
        )
        # child-state cache [2, F, Nl, d, 2, 2(,4)]: party, node, client...
        self._child_spec = EvalState(
            seed=P(SERVERS, None, DATA),
            bit=P(SERVERS, None, DATA),
            y_bit=P(SERVERS, None, DATA),
        )
        self.frontier: Frontier | None = None
        self._children: EvalState | None = None
        self._masks = collect.pattern_masks(self.n_dims)
        self._kernel_cache: dict = {}
        self._build_kernels()
        if secure_exchange:
            self._setup_secure()

    def _host_put(self, arr, spec):
        """Place a host array onto the mesh.  Single-process: device_put.
        Multi-process: every process holds the same global host value
        (replicated or agreed-from-process-0 material) and materializes
        only its addressable shards via ``make_array_from_callback``."""
        sharding = NamedSharding(self.mesh, spec)
        arr = np.asarray(arr)
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    @classmethod
    def from_process_local(
        cls,
        mesh: Mesh,
        my_keys: IbDcfKeyBatch,
        f_max: int,
        secure_exchange: bool = False,
        min_bucket: int = 1,
    ) -> "MeshRunner":
        """Multi-process construction for the two-host deployment shape
        (``configs/amazon.json``): process p hosts mesh row p (party p's
        chips) and supplies ONLY its own party's key batch — the global
        party-stacked arrays are assembled from the process-local rows
        via ``jax.make_array_from_process_local_data``, so no process
        ever materializes the peer party's keys on its host."""
        assert jax.process_count() == 2, "from_process_local is the 2-host shape"
        local = jax.tree.map(lambda a: np.asarray(a)[None], my_keys)  # [1, N, ..]
        keys = jax.tree.map(
            lambda a, s: jax.make_array_from_process_local_data(
                NamedSharding(mesh, s), a
            ),
            local,
            _KEY_SPEC,
        )
        return cls(
            mesh, None, None, f_max,
            secure_exchange=secure_exchange, min_bucket=min_bucket,
            _global_keys=keys,
        )

    def _setup_secure(self):
        """Host-side base-OT setup for the on-mesh 2PC, one session per
        garbling DIRECTION so the leader can alternate the garbler per
        level (the reference's ``gc_sender`` flip, rpc.rs:20-23): in
        session ``g`` party ``g`` (garbler / extension sender) gets its
        ``s``-chosen seeds, the other party the seed-pair columns.  The
        stacked [2, ...] tensors put each party's material in its own
        mesh-row slot; the unused slots are zeros (SPMD runs both roles on
        both parties and discards the wrong-role half — branchless, like
        any 2-way-masked collective)."""
        z = np.zeros((otext.KAPPA, 4), np.uint32)
        host_mats = []
        for g in (0, 1):
            s_bits = otext.fresh_s_bits()
            seeds0, seeds1, chosen = baseot.exchange(s_bits)
            host_mats.append((s_bits, seeds0, seeds1, chosen))
        sec_seed = np.frombuffer(_secrets.token_bytes(16), "<u4").copy()
        if jax.process_count() > 1:
            # session material must be identical everywhere: agree from
            # process 0 (single trust domain — see init_distributed note)
            from jax.experimental import multihost_utils

            host_mats, sec_seed = multihost_utils.broadcast_one_to_all(
                (host_mats, sec_seed)
            )
            host_mats = jax.tree.map(np.asarray, host_mats)
            sec_seed = np.asarray(sec_seed)
        self._sec = {}
        for g, (s_bits, seeds0, seeds1, chosen) in enumerate(host_mats):
            # fhh-lint: disable=host-sync-in-hot-loop,chunked-device-readback (one-time session setup)
            s_bits = np.asarray(s_bits)
            zb = np.zeros_like(s_bits)
            rows = lambda a_g, a_e: np.stack([a_g, a_e] if g == 0 else [a_e, a_g])
            self._sec[g] = {
                "s_bits": self._host_put(rows(s_bits, zb), P(SERVERS, None)),
                "seeds_main": self._host_put(
                    rows(chosen, seeds0).astype(np.uint32), P(SERVERS, None, None)
                ),
                "seeds_aux": self._host_put(
                    rows(z, seeds1).astype(np.uint32), P(SERVERS, None, None)
                ),
                "blocks": 0,  # column-stream block offset (lockstep)
                "sent": 0,  # pad-tweak index base
            }
        self._sec_seed = sec_seed
        self._crawl_ctr = 0

    def _build_kernels(self):
        mesh, f_max, derived = self.mesh, self.f_max, self._derived
        masks = jnp.asarray(self._masks)
        kspec, fspec = self._key_spec, self._frontier_spec

        cspec = self._child_spec

        root_bucket = self.min_bucket

        def init_body(keys):
            keys = jax.tree.map(lambda a: a[0], keys)  # drop party block axis
            # the mesh bodies pin the XLA engine, so pin its layout too
            f = collect.tree_init(keys, root_bucket, planar=False)
            return jax.tree.map(lambda a: a[None], f)

        # fhh-lint: disable=recompile-churn (setup-time factory: built once per mesh)
        self._init_fn = jax.jit(
            _shard_map(init_body, mesh=mesh, in_specs=(kspec,), out_specs=fspec)
        )

        def make_counts_fn(want_children: bool):
            def counts_body(keys, frontier, alive_keys, level):
                keys = jax.tree.map(lambda a: a[0], keys)
                frontier = jax.tree.map(lambda a: a[0], frontier)
                alive = alive_keys[0]
                packed, children = collect._expand_share_bits_jit(
                    keys, frontier, level, derived, want_children
                )
                # one u32 per (node, client): the whole inter-party data plane
                peer = jax.lax.ppermute(packed, SERVERS, perm=[(0, 1), (1, 0)])
                cnt = collect.counts_by_pattern(
                    packed, peer, masks, alive, frontier.alive
                )
                cnt = jax.lax.psum(cnt, DATA)
                # both parties compute identical counts (the compare is
                # symmetric); psum/2 over servers makes replication explicit
                cnt = jax.lax.psum(cnt, SERVERS) // 2
                if not want_children:  # last level: nothing advances past it
                    return cnt
                return cnt, jax.tree.map(lambda a: a[None], children)

            # fhh-lint: disable=recompile-churn (setup-time factory: built once per mesh)
            return jax.jit(
                _shard_map(
                    counts_body,
                    mesh=mesh,
                    in_specs=(kspec, fspec, P(SERVERS, DATA), P()),
                    out_specs=(P(), cspec) if want_children else P(),
                )
            )

        self._counts_fn = make_counts_fn(True)
        self._counts_last_fn = make_counts_fn(False)

        def advc_body(children, parent, pat_bits, n_alive):
            ch = jax.tree.map(lambda a: a[0], children)
            new = collect._advance_children_jit(ch, parent, pat_bits, n_alive)
            return jax.tree.map(lambda a: a[None], new)

        # fhh-lint: disable=recompile-churn (setup-time factory: built once per mesh)
        self._advance_fn = jax.jit(
            _shard_map(
                advc_body,
                mesh=mesh,
                in_specs=(cspec, P(None), P(None, None), P()),
                out_specs=fspec,
            )
        )

    def _secure_counts_fn(self, field, garbler: int = 0, want_children: bool = True):
        """Build (and cache) the one-program secure level crawl for a
        (count field, garbler party) pair: the whole per-level 2PC —
        label extension, equality + b2a, alive-gated share sums — as a
        single shard_mapped program whose only inter-party traffic is
        ``ppermute`` transfers on the ``servers`` axis: the ICI twin of
        protocol/rpc.py's socket flow.  Crawls with S = 2·n_dims ≤
        secure.OT2S_MAX_S take the 1-of-2^S chosen-payload-OT fast path
        — no garbled circuit, TWO transfers per level (u-matrix, payload
        table); wider strings run the GC+OT form with seven (u-matrix,
        tables/labels/decode, b2a u-matrix, ciphertext pair).
        ``garbler`` is static per program
        (the perms are trace-time), two compiles per field.

        Per-data-shard uniqueness: every (0,j)<->(1,j) chip pair runs its
        own extension on the shared base seeds.  Reusing identical column
        streams / garbler randomness across shards would leak XORs of
        secrets between shards (u_A ^ u_B = r_A ^ r_B, and identical X0
        labels reveal x_A ^ x_B), so every seed is tweaked by the shard
        index inside the body — consistently on both parties."""
        key = ("secure", field.__name__, garbler, want_children,
               secure._ot4_use(2 * self.n_dims))
        if key not in self._kernel_cache:
            self._kernel_cache[key] = self._make_secure_body(
                field, garbler, want_children
            )
        return self._kernel_cache[key]

    def _make_secure_body(self, field, g: int, want_children: bool = True):
        mesh, derived, d = self.mesh, self._derived, self.n_dims
        kspec, fspec = self._key_spec, self._frontier_spec
        limb = field.limb_shape
        ev = 1 - g  # evaluator party of this direction

        def body(keys, frontier, alive_keys, s_bits, seeds_main, seeds_aux,
                 gc_seed, b2a_seed, off, sent, level):
            keys_l = jax.tree.map(lambda a: a[0], keys)
            frontier_l = jax.tree.map(lambda a: a[0], frontier)
            alive = alive_keys[0]
            s_bits_l, sm, sa = s_bits[0], seeds_main[0], seeds_aux[0]
            gseed, bseed = gc_seed[0], b2a_seed[0]
            # NB: never tweak word 0 — it is stream_blocks' CTR word, and a
            # small XOR there yields a block-SHIFTED identical stream, not an
            # independent one.  Word 3 is safe for the column seeds; the
            # garbler seeds use word 2 shifted clear of derive_seed's
            # purpose tag.
            shard = jax.lax.axis_index(DATA).astype(jnp.uint32)
            sm = sm.at[..., 3].set(sm[..., 3] ^ shard)
            sa = sa.at[..., 3].set(sa[..., 3] ^ shard)
            gseed = gseed.at[2].set(gseed[2] ^ (shard << 16))
            bseed = bseed.at[2].set(bseed[2] ^ (shard << 16))

            packed, children = collect._expand_share_bits_jit(
                keys_l, frontier_l, level, derived, want_children
            )
            strs = secure.child_strings(packed, d)  # [F, C, Nl, S]
            F_, C, Nl, S = strs.shape
            B = F_ * C * Nl
            m = B * S
            flat = strs.reshape(B, S)

            # label delivery: evaluator's u -> garbler; labels = Δ-OT rows
            u, t_rows = otext._receiver_extend(sm, sa, flat.reshape(m), off, m)
            u0 = jax.lax.ppermute(u, SERVERS, perm=[(ev, g)])
            q = otext._sender_extend(sm, s_bits_l, u0, off, m)
            s_block = otext.pack_bits(s_bits_l)
            if secure._ot4_use(S):
                # 1-of-2^S chosen-payload OT: no circuit, the payload
                # table IS the message — 2 ppermutes per level (u, cts)
                # instead of the GC path's 7 (see secure.py's fast path;
                # S <= secure.OT2S_MAX_S, i.e. n_dims <= 3)
                W = secure.payload_words(field)
                r1, w0, w1 = secure.b2a_payload_pair(field, bseed, B, g)
                cts_g = secure.ot4_encrypt(
                    q.reshape(B, S, 4), s_block, flat, w1, w0, W, sent
                )
                cts = jax.lax.ppermute(cts_g, SERVERS, perm=[(g, ev)])
                w_pay = secure.ot4_decrypt(
                    t_rows.reshape(B, S, 4), flat, cts, W, sent
                )
                v1 = secure.words_to_field(field, w_pay)
            else:
                batch, mask = gc.garble_equality_delta(
                    s_block, q.reshape(B, S, 4), gseed, flat
                )
                ev_batch = gc.GarbledEqBatch(
                    tables=jax.lax.ppermute(batch.tables, SERVERS, perm=[(g, ev)]),
                    gb_labels=jax.lax.ppermute(batch.gb_labels, SERVERS, perm=[(g, ev)]),
                    decode=jax.lax.ppermute(batch.decode, SERVERS, perm=[(g, ev)]),
                )
                e = gc.eval_equality(ev_batch, t_rows.reshape(B, S, 4))

                # b2a conversion (r1 - r0 = 1 trick) under chosen-payload pads
                w_cols = -(-m // 32)
                off2 = off + (-(-w_cols // 16))
                u2, t2_rows = otext._receiver_extend(sm, sa, e, off2, B)
                u2_0 = jax.lax.ppermute(u2, SERVERS, perm=[(ev, g)])
                q2 = otext._sender_extend(sm, s_bits_l, u2_0, off2, B)
                idx0 = sent + m
                c0g, c1g, r1 = secure.b2a_encrypt(
                    field, q2, s_block, mask, bseed, idx0, g
                )
                c0 = jax.lax.ppermute(c0g, SERVERS, perm=[(g, ev)])
                c1 = jax.lax.ppermute(c1g, SERVERS, perm=[(g, ev)])
                v1 = secure.b2a_decrypt(field, t2_rows, idx0, c0, c1, e)

            party = jax.lax.axis_index(SERVERS)
            vals = jnp.where(party == g, r1, v1)  # own additive share per test
            wgt = (
                frontier_l.alive[:, None, None]
                & alive[None, None, :]
            )
            wgt = jnp.broadcast_to(wgt, (F_, C, Nl))
            shares = secure.node_share_sums(
                field, vals.reshape((F_, C, Nl) + limb), wgt
            )
            shares = field_psum(field, shares, DATA)
            # exchange both parties' share rows so the output is REPLICATED
            # [2, F, C(, limbs)] — the leader-side reconstruction then reads
            # a fully-addressable array on every process.  One-hot expand +
            # psum (each slot has exactly one contributor) rather than
            # all_gather: psum's replication is statically certified.
            party_row = jax.lax.axis_index(SERVERS)
            expand = jnp.zeros((2,) + shares.shape, shares.dtype)
            expand = expand.at[party_row].set(shares)
            allsh = jax.lax.psum(expand, SERVERS)
            if not want_children:  # last level: nothing advances past it
                return allsh
            return allsh, jax.tree.map(lambda a: a[None], children)

        # fhh-lint: disable=recompile-churn (setup-time factory: built once per mesh)
        fn = jax.jit(
            _shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    kspec, fspec, P(SERVERS, DATA), P(SERVERS, None),
                    P(SERVERS, None, None), P(SERVERS, None, None),
                    P(SERVERS, None), P(SERVERS, None), P(), P(), P(),
                ),
                out_specs=(
                    (P(), self._child_spec) if want_children else P()
                ),
            )
        )
        return fn

    # -- leader-facing ops --------------------------------------------------

    def tree_init(self):
        self.frontier = self._init_fn(self.keys)
        self._children = None

    def level_counts(self, level: int, last: bool = False) -> np.ndarray:
        """Crawl counts for every child of the current frontier: the
        expand → exchange(ppermute) → compare → psum pipeline.  The
        both-direction child states are cached for :meth:`advance`;
        ``last=True`` (the final level, which nothing advances past)
        skips materializing the cache."""
        if last:
            cnt = self._counts_last_fn(
                self.keys, self.frontier, self.alive_keys, jnp.int32(level)
            )
            self._children = None
        else:
            cnt, self._children = self._counts_fn(
                self.keys, self.frontier, self.alive_keys, jnp.int32(level)
            )
        return np.asarray(cnt)

    def level_count_shares(self, level: int, field=FE62, last: bool = False) -> np.ndarray:
        """Secure crawl: both parties' additive count shares [2, F, 2^d
        (, limbs)] — reconstruct as field.sub(shares[0], shares[1]).  The
        level field mirrors the socket path: FE62 inner levels, F255 last
        (ref: rpc.rs:60-62); the garbler alternates per level (gc_sender
        flip), each direction consuming its own OT-extension session;
        ``last=True`` skips the child-state cache."""
        assert self.secure, "runner built without secure_exchange"
        g = level % 2
        sess = self._sec[g]
        fn = self._secure_counts_fn(field, g, not last)
        self._crawl_ctr += 1
        gseed = secure.derive_seed(self._sec_seed, 1, level, self._crawl_ctr)
        bseed = secure.derive_seed(self._sec_seed, 2, level, self._crawl_ctr)
        z = np.zeros(4, np.uint32)
        # the derived seeds go in the GARBLER's mesh row (the body reads its
        # own row) — with alternation, pinning row 0 would hand odd levels'
        # garbler an all-zero seed and destroy per-level freshness
        put = lambda a: self._host_put(
            np.stack([a, z] if g == 0 else [z, a]), P(SERVERS, None)
        )
        # static per-call shapes -> deterministic stream consumption; the
        # GC/OT batch is sized to the CURRENT frontier bucket, not f_max
        n_local = self.keys.cw_seed.shape[1] // self.mesh.shape[DATA]
        f_cur = self.frontier.alive.shape[1]
        B = f_cur * (1 << self.n_dims) * n_local
        m = B * 2 * self.n_dims
        out = fn(
            self.keys, self.frontier, self.alive_keys,
            sess["s_bits"], sess["seeds_main"], sess["seeds_aux"],
            put(gseed), put(bseed),
            jnp.uint32(sess["blocks"]), jnp.uint32(sess["sent"]),
            jnp.int32(level),
        )
        if last:
            shares, self._children = out, None
        else:
            shares, self._children = out
        w1 = -(-m // 32)
        if secure._ot4_use(2 * self.n_dims):
            # 1-of-2^S fast path: one extension (m rows), per-test pads
            # in their own tweak domain — no second b2a extension
            sess["blocks"] += -(-w1 // 16)
            sess["sent"] += m
        else:
            w2 = -(-B // 32)
            sess["blocks"] += (-(-w1 // 16)) + (-(-w2 // 16))
            sess["sent"] += m + B
        return np.asarray(shares)

    def advance(self, level: int, parent_idx, pattern_bits, n_alive: int):
        assert self._children is not None, "advance before level_counts"
        self.frontier = self._advance_fn(
            self._children,
            jnp.asarray(parent_idx, jnp.int32),
            jnp.asarray(pattern_bits, bool),
            jnp.int32(n_alive),
        )
        self._children = None

    # -- checkpoint / restore (data-plane fault tolerance) ------------------

    def snapshot(self) -> dict:
        """Host-side snapshot of the device-resident crawl state — the
        mesh twin of the socket servers' ``tree_checkpoint`` blob.  ONE
        stacked ``device_get`` (each fetch through a remote-chip tunnel
        is a full round trip); keys are NOT included (the caller holds
        them, and they never change mid-crawl)."""
        assert self.frontier is not None, "snapshot before tree_init"
        st = self.frontier.states
        return jax.device_get(
            {
                "seed": st.seed,
                "bit": st.bit,
                "y_bit": st.y_bit,
                "alive": self.frontier.alive,
                "alive_keys": self.alive_keys,
            }
        )

    def restore(self, snap: dict) -> None:
        """Re-place a :meth:`snapshot` onto the mesh (works after the
        device state was lost — ``_host_put`` reshards from host copies,
        multi-process included).  The child-state cache is dropped: it
        belonged to a level whose advance never happened."""
        fs = self._frontier_spec
        self.frontier = Frontier(
            states=EvalState(
                seed=self._host_put(snap["seed"], fs.states.seed),
                bit=self._host_put(snap["bit"], fs.states.bit),
                y_bit=self._host_put(snap["y_bit"], fs.states.y_bit),
            ),
            alive=self._host_put(snap["alive"], fs.alive),
        )
        self.alive_keys = self._host_put(snap["alive_keys"], P(SERVERS, DATA))
        self._children = None


class MeshLeader:
    """Level-loop driver over a MeshRunner (host-side thresholds/paths,
    ref: leader.rs:185-297 — same bookkeeping as protocol.driver.Leader)."""

    def __init__(self, runner: MeshRunner, min_bucket: int | None = None):
        self.r = runner
        # default: the runner's own pin (so one knob covers init + prune)
        self.min_bucket = runner.min_bucket if min_bucket is None else min_bucket
        self.paths = None
        self.n_nodes = 0
        # telemetry: level spans (the heartbeat names the level a wedged
        # pod crawl died in) + survivor gauges + device-fetch counts
        self.obs = obsmetrics.Registry("mesh")

    def _level_counts(self, level: int) -> np.ndarray:
        """Per-level counts: plaintext compare in trusted mode, or leader
        reconstruction v0 - v1 of the parties' share outputs in secure mode
        (FE62 inner levels, F255 last — ref: rpc.rs:60-62)."""
        r = self.r
        last = level == r.data_len - 1
        self.obs.count("device_fetches", level=level)  # one host fetch per
        # level: the counts (trusted) or the reconstructed share diff
        if not r.secure:
            return r.level_counts(level, last=last)
        if last:
            sh = r.level_count_shares(level, F255, last=True)
            v = np.asarray(F255.sub(sh[0], sh[1]))
            counts = v[..., 0].astype(np.uint32)
            if np.any(v[..., 1:]):
                raise RuntimeError("non-count residue in F255 mesh shares")
            return counts
        sh = r.level_count_shares(level, FE62)
        v = np.asarray(FE62.canon(FE62.sub(sh[0], sh[1])))
        n = r.keys.cw_seed.shape[1]
        if np.any(v > n):  # e.g. a share-sign/role mismatch
            raise RuntimeError("count reconstruction out of range")
        return v.astype(np.uint32)

    def _run_one_level(self, level: int, nreqs: int, threshold: float):
        """One crawl->threshold->prune round; returns the kept counts for
        this level, or None when the crawl died out (no survivors)."""
        r = self.r
        d = r.n_dims
        counts = self._level_counts(level)
        thresh = max(1, int(threshold * nreqs))
        keep = counts >= thresh
        keep[self.n_nodes :, :] = False
        parent, pattern, n_alive = collect.compact_survivors(
            keep, r.f_max, self.min_bucket
        )
        pat_bits = collect.pattern_to_bits(pattern, d)
        self.obs.gauge("survivors", n_alive, level=level)
        if n_alive == 0:
            return None
        if level < r.data_len - 1:  # nothing advances past the leaves
            r.advance(level, parent, pat_bits, n_alive)
        new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + 1), bool)
        for i in range(n_alive):
            new_paths[i, :, :-1] = self.paths[parent[i]]
            new_paths[i, :, -1] = pat_bits[i]
        self.paths = new_paths
        self.n_nodes = n_alive
        return counts[parent[:n_alive], pattern[:n_alive]]

    def run(self, nreqs: int, threshold: float):
        from ..protocol.driver import CrawlResult

        r = self.r
        d = r.n_dims
        r.tree_init()
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        counts_kept = np.zeros(0, np.uint32)
        for level in range(r.data_len):
            with self.obs.span("level", level=level):
                counts_kept = self._run_one_level(level, nreqs, threshold)
            if counts_kept is None:
                return CrawlResult(
                    paths=np.zeros((0, d, level + 1), bool),
                    counts=np.zeros(0, np.uint32),
                )
        return CrawlResult(paths=self.paths, counts=counts_kept)

    def run_supervised(
        self,
        nreqs: int,
        threshold: float,
        *,
        checkpoint_every: int = 2,
        max_recoveries: int = 4,
        chaos=None,
    ):
        """Fault-tolerant twin of :meth:`run` for the ICI path: host-side
        snapshots of the device-resident frontier every
        ``checkpoint_every`` levels, and recovery matched to what a mesh
        fault actually costs:

        - device state INTACT (a dropped data-parallel shard — the
          collective's result can't be trusted but the frontier can):
          re-run just that level;
        - device state LOST (a participant killed mid-collective): restore
          the last snapshot and re-run the lost levels — or restart from
          scratch if none was taken yet.

        ``chaos`` is a :class:`resilience.chaos.MeshChaos` injector (or
        None); its ``before_level`` hook fires the scheduled faults.
        Recovery is exact: counts are deterministic re-runs (secure-mode
        share randomness differs, their reconstruction does not), so a
        recovered crawl is bit-identical to a fault-free one."""
        from ..protocol.driver import CrawlResult
        from ..resilience.chaos import MeshFaultError
        from .. import obs as obsmod

        r = self.r
        d = r.n_dims
        r.tree_init()
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        counts_kept = np.zeros(0, np.uint32)
        # zero-touch the recovery counters: a supervised FAULT-FREE run
        # must still carry the run report's recovery section (as zeros)
        # so its absence can't be mistaken for a fault-free recovery
        for c in ("recoveries", "levels_rerun", "shards_rerun"):
            self.obs.count(c, 0)
        stash = None  # (level, snapshot, paths, n_nodes, counts_kept)
        recoveries = 0
        level = 0
        while level < r.data_len:
            try:
                if chaos is not None:
                    chaos.before_level(r, level)
                with self.obs.span("level", level=level):
                    counts_kept = self._run_one_level(level, nreqs, threshold)
                if counts_kept is None:
                    return CrawlResult(
                        paths=np.zeros((0, d, level + 1), bool),
                        counts=np.zeros(0, np.uint32),
                    )
                if level < r.data_len - 1 and (level + 1) % checkpoint_every == 0:
                    stash = (
                        level,
                        r.snapshot(),
                        self.paths.copy(),
                        self.n_nodes,
                        counts_kept.copy(),
                    )
                    self.obs.count("crawl_checkpoints", level=level)
                level += 1
            except MeshFaultError as err:
                recoveries += 1
                self.obs.count("recoveries")
                obsmod.emit(
                    "resilience.mesh_recover",
                    severity="warn",
                    level=level,
                    attempt=recoveries,
                    state_lost=err.state_lost,
                    error=str(err),
                )
                if recoveries > max_recoveries:
                    raise
                if not err.state_lost and r.frontier is not None:
                    # shard-granular cost: device state survived, only
                    # this level's collective result is suspect
                    self.obs.count("shards_rerun", level=level)
                    continue
                self.obs.count("levels_rerun")
                if stash is not None:
                    lvl, snap, paths, n_nodes, kept = stash
                    r.restore(snap)
                    self.paths = paths.copy()
                    self.n_nodes = n_nodes
                    counts_kept = kept.copy()
                    level = lvl + 1
                else:  # no snapshot yet: restart the crawl from scratch
                    r.tree_init()
                    self.paths = np.zeros((1, d, 0), bool)
                    self.n_nodes = 1
                    counts_kept = np.zeros(0, np.uint32)
                    level = 0
        return CrawlResult(paths=self.paths, counts=counts_kept)
