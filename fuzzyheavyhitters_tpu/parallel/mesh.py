"""Device-mesh execution of the two-server protocol.

The reference's distribution fabric is processes + sockets: two server
binaries joined by a TCP channel mesh carrying GC/OT traffic
(ref: server.rs:197-262), rayon threads inside each (SURVEY.md §2
parallelism table).  The TPU-native fabric is a 2-D ``jax.sharding.Mesh``:

- axis ``servers`` (size 2): the two-party MPC topology.  Party p's keys and
  frontier live on the devices of mesh row p; the only inter-party traffic —
  one packed uint32 of share bits per (node, client) per level — moves by a
  single ``ppermute`` swap across this axis (the ICI replacement for the
  reference's per-core TCP socket mesh).
- axis ``data`` (size k): client data parallelism.  The client batch ``N``
  is sharded k ways (the reference's rayon ``par_iter`` over clients,
  collect.rs:94-119, become per-shard tensor blocks); per-node counts
  finish with a ``psum`` over this axis.

Every collective rides the mesh; the host (leader) only sees final counts —
mirroring the reference's leader↔server RPC split where per-level counts are
the only thing returned (rpc.rs:60-61).  The sharded kernels are built and
jitted ONCE per runner; ``level`` and the survivor table are traced scalars,
so a full ``data_len``-level crawl compiles exactly two programs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import prg
from ..ops.ibdcf import IbDcfKeyBatch
from ..protocol import collect
from ..protocol.collect import EvalState, Frontier

SERVERS = "servers"
DATA = "data"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """2 × (n/2) mesh: first axis the two servers, rest data parallel."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devices)
    assert n % 2 == 0, f"need an even device count for the 2-server axis, got {n}"
    arr = np.asarray(devices).reshape(2, n // 2)
    return Mesh(arr, (SERVERS, DATA))


def _stack_parties(t0, t1):
    return jax.tree.map(lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]), t0, t1)


class MeshRunner:
    """Holds both parties' device-resident state, sharded over the mesh.

    Leading axes of every tensor: [party=2, ...] with party sharded over
    ``servers`` and the client axis sharded over ``data``.  The PRG bit mode
    (prg.DERIVED_BITS) is captured at construction; a runner never mixes
    modes mid-crawl.
    """

    def __init__(self, mesh: Mesh, keys0: IbDcfKeyBatch, keys1: IbDcfKeyBatch, f_max: int):
        self.mesh = mesh
        self.f_max = f_max
        self.n_dims = keys0.cw_seed.shape[1]
        self.data_len = keys0.data_len
        self._derived = prg.DERIVED_BITS
        n = keys0.cw_seed.shape[0]
        assert n % mesh.shape[DATA] == 0, (
            f"client count {n} must divide the data axis {mesh.shape[DATA]}"
        )
        keys = _stack_parties(keys0, keys1)  # [2, N, d, 2, ...]
        key_spec = IbDcfKeyBatch(
            key_idx=P(SERVERS, DATA),
            root_seed=P(SERVERS, DATA),
            cw_seed=P(SERVERS, DATA),
            cw_bits=P(SERVERS, DATA),
            cw_y_bits=P(SERVERS, DATA),
        )
        self._key_spec = key_spec
        self.keys = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), keys, key_spec
        )
        self.alive_keys = jax.device_put(
            jnp.ones((2, n), bool), NamedSharding(mesh, P(SERVERS, DATA))
        )
        self._frontier_spec = Frontier(
            states=EvalState(
                seed=P(SERVERS, None, DATA),
                bit=P(SERVERS, None, DATA),
                y_bit=P(SERVERS, None, DATA),
            ),
            alive=P(SERVERS, None),
        )
        self.frontier: Frontier | None = None
        self._masks = collect.pattern_masks(self.n_dims)
        self._build_kernels()

    def _build_kernels(self):
        mesh, f_max, derived = self.mesh, self.f_max, self._derived
        masks = jnp.asarray(self._masks)
        kspec, fspec = self._key_spec, self._frontier_spec

        def init_body(keys):
            keys = jax.tree.map(lambda a: a[0], keys)  # drop party block axis
            f = collect.tree_init(keys, f_max)
            return jax.tree.map(lambda a: a[None], f)

        self._init_fn = jax.jit(
            jax.shard_map(init_body, mesh=mesh, in_specs=(kspec,), out_specs=fspec)
        )

        def counts_body(keys, frontier, alive_keys, level):
            keys = jax.tree.map(lambda a: a[0], keys)
            frontier = jax.tree.map(lambda a: a[0], frontier)
            alive = alive_keys[0]
            packed = collect._expand_share_bits_jit(keys, frontier, level, derived)
            # one u32 per (node, client): the whole inter-party data plane
            peer = jax.lax.ppermute(packed, SERVERS, perm=[(0, 1), (1, 0)])
            cnt = collect.counts_by_pattern(packed, peer, masks, alive, frontier.alive)
            cnt = jax.lax.psum(cnt, DATA)
            # both parties compute identical counts (the compare is
            # symmetric); psum/2 over servers makes replication explicit
            cnt = jax.lax.psum(cnt, SERVERS) // 2
            return cnt

        self._counts_fn = jax.jit(
            jax.shard_map(
                counts_body,
                mesh=mesh,
                in_specs=(kspec, fspec, P(SERVERS, DATA), P()),
                out_specs=P(),
            )
        )

        def adv_body(keys, frontier, level, parent, pat_bits, n_alive):
            keys = jax.tree.map(lambda a: a[0], keys)
            frontier = jax.tree.map(lambda a: a[0], frontier)
            new = collect._advance_jit(
                keys, frontier, level, parent, pat_bits, n_alive, derived
            )
            return jax.tree.map(lambda a: a[None], new)

        self._advance_fn = jax.jit(
            jax.shard_map(
                adv_body,
                mesh=mesh,
                in_specs=(kspec, fspec, P(), P(None), P(None, None), P()),
                out_specs=fspec,
            )
        )

    # -- leader-facing ops --------------------------------------------------

    def tree_init(self):
        self.frontier = self._init_fn(self.keys)

    def level_counts(self, level: int) -> np.ndarray:
        """Crawl counts for every child of the current frontier: the
        expand → exchange(ppermute) → compare → psum pipeline."""
        return np.asarray(
            self._counts_fn(
                self.keys, self.frontier, self.alive_keys, jnp.int32(level)
            )
        )

    def advance(self, level: int, parent_idx, pattern_bits, n_alive: int):
        self.frontier = self._advance_fn(
            self.keys,
            self.frontier,
            jnp.int32(level),
            jnp.asarray(parent_idx, jnp.int32),
            jnp.asarray(pattern_bits, bool),
            jnp.int32(n_alive),
        )


class MeshLeader:
    """Level-loop driver over a MeshRunner (host-side thresholds/paths,
    ref: leader.rs:185-297 — same bookkeeping as protocol.driver.Leader)."""

    def __init__(self, runner: MeshRunner):
        self.r = runner
        self.paths = None
        self.n_nodes = 0

    def run(self, nreqs: int, threshold: float):
        from ..protocol.driver import CrawlResult

        r = self.r
        d = r.n_dims
        r.tree_init()
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        counts_kept = np.zeros(0, np.uint32)
        for level in range(r.data_len):
            counts = r.level_counts(level)
            thresh = max(1, int(threshold * nreqs))
            keep = counts >= thresh
            keep[self.n_nodes :, :] = False
            parent, pattern, n_alive = collect.compact_survivors(keep, r.f_max)
            pat_bits = collect.pattern_to_bits(pattern, d)
            if n_alive == 0:
                return CrawlResult(
                    paths=np.zeros((0, d, level + 1), bool),
                    counts=np.zeros(0, np.uint32),
                )
            r.advance(level, parent, pat_bits, n_alive)
            new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + 1), bool)
            for i in range(n_alive):
                new_paths[i, :, :-1] = self.paths[parent[i]]
                new_paths[i, :, -1] = pat_bits[i]
            self.paths = new_paths
            self.n_nodes = n_alive
            counts_kept = counts[parent[:n_alive], pattern[:n_alive]]
        return CrawlResult(paths=self.paths, counts=counts_kept)
