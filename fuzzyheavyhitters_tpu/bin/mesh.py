"""Mesh-deployment binary: the whole collection on one device mesh.

The socket deployment (bin/server.py x2 + bin/leader.py) maps the
reference's two-EC2-host shape; THIS binary is the pod shape — both
parties and all client data parallelism live on a ``jax.sharding.Mesh``
(2 x k: servers x data), the entire per-level 2PC rides ``ppermute`` /
``psum`` collectives, and the host runs only the leader's threshold loop
(parallel/mesh.py).  Single trust domain by construction — see
``init_distributed``'s note; use the socket binaries when the two
parties are separate administrative domains.

All three workload distributions run here (zipf site strings, RideAustin
i16 lat/lon, COVID f64-bit coords) via the same shared sampler as the
leader binary; the rides flow is deterministically sampled (seed 42, as
in the leader) and writes the same heavy-hitter CSV as the socket
deployment on the same config.
``malicious`` mode is a documented refusal: sketch verification needs
Beaver-triple rounds between SEPARATE trust domains, and the mesh is one
trust domain — its threat model already includes both parties, so run
the socket binaries (which implement the full sketch+MPC path) when
malicious clients are in scope.

::

    python -m fuzzyheavyhitters_tpu.bin.mesh --config configs/config.json -n 1000

Multi-host: set ``--processes N --process_id I --coordinator HOST:PORT``
on each host; process i supplies only party i's keys when N == 2
(MeshRunner.from_process_local).
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

from .. import obs
from ..ops import ibdcf
from ..parallel import mesh as meshmod
from ..utils import compile_cache
from ..utils import config as configmod
from ..workloads import OUTPUT_CSV, rides, sample_points


def main() -> None:
    p = argparse.ArgumentParser(
        prog="Mesh", description="TPU-mesh private fuzzy heavy hitters."
    )
    p.add_argument("-c", "--config", required=True)
    p.add_argument("-n", "--num_requests", type=int, required=True)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--devices", type=int, default=None,
                   help="use only the first N devices (default: all)")
    p.add_argument("--platform", default=None,
                   help='pin the JAX platform (e.g. "cpu" for a virtual '
                        "host-device mesh; must be set before backend init)")
    args = p.parse_args()
    cfg = configmod.load_config(args.config)
    if cfg.malicious:
        raise SystemExit(
            "mesh binary: malicious mode refused — the mesh co-locates both "
            "parties in one trust domain, so sketch verification adds no "
            "security there; use the socket binaries (bin/server.py x2 + "
            "bin/leader.py), which run the full sketch+MPC path."
        )

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    # persistent XLA compile cache (FHH_COMPILE_CACHE) — after the
    # platform pin so the cache keys against the platform actually used
    compile_cache.enable()
    if args.processes:
        meshmod.init_distributed(
            args.coordinator, args.processes, args.process_id
        )
        if args.processes > 1:
            # both processes inherit the same $FHH_RUN_REPORT and write
            # atomically at exit — the last exiter would clobber the other
            # party's report; give each process its own .p<id> file
            obs.claim_report_path(f"p{args.process_id}")

    # shared exit contract (obs.exit_report): SIGTERM -> SystemExit so a
    # timed-out mesh run still writes its report, with per-level
    # accounting up to the level it died in
    with obs.exit_report():
        _run(cfg, args, jax)


def _run(cfg, args, jax) -> None:
    rng = np.random.default_rng()
    n = args.num_requests
    reg = obs.default_registry()
    obs.emit("sampling", distribution=cfg.distribution, n=n)
    with reg.span("sampling"):
        pts = sample_points(cfg, n, rng)
    with reg.span("keygen"):
        t0 = time.perf_counter()
        k0, k1 = ibdcf.gen_l_inf_ball(
            pts, cfg.ball_size, rng, engine=ibdcf.best_engine(),
        )
    obs.emit(
        "keygen.report", seconds=round(time.perf_counter() - t0, 2), n_keys=n
    )

    mesh = meshmod.make_mesh(args.devices)
    if args.processes == 2:
        my = k0 if jax.process_index() == 0 else k1
        runner = meshmod.MeshRunner.from_process_local(
            mesh, my, cfg.f_max, secure_exchange=cfg.secure_exchange
        )
    else:
        runner = meshmod.MeshRunner(
            mesh, k0, k1, cfg.f_max, secure_exchange=cfg.secure_exchange
        )
    t0 = time.perf_counter()
    res = meshmod.MeshLeader(runner).run(nreqs=n, threshold=cfg.threshold)
    obs.emit("crawl.done", seconds=round(time.perf_counter() - t0, 2))
    for row, c in zip(res.decode_ints(), res.counts):
        obs.emit("hitter", value=str(row.tolist()), count=int(c))
    if cfg.distribution == "rides" and res.paths.shape[0]:
        # identical CSV contract as the socket deployment (bin/leader.py)
        os.makedirs(os.path.dirname(OUTPUT_CSV), exist_ok=True)
        rides.save_heavy_hitters(res.paths, OUTPUT_CSV)
        obs.emit(
            "csv.written", path=OUTPUT_CSV, hitters=int(res.paths.shape[0])
        )


if __name__ == "__main__":
    main()
