"""Leader binary: client simulation + protocol driver (ref: src/bin/leader.rs).

::

    python -m fuzzyheavyhitters_tpu.bin.leader --config configs/config.json -n 1000

Flow (leader.rs:300-440): keygen throughput report, distribution-specific
client sampling (zipf site strings with 8-bit augmentation, RideAustin
coordinates, or COVID-geo), batched key upload, level loop, heavy-hitter CSV.

Telemetry rides the obs layer (fuzzyheavyhitters_tpu/obs): structured log
events instead of prints (JSON-lines via ``FHH_LOG_FORMAT=json``), a
heartbeat thread naming the active phase/level, and — when
``FHH_RUN_REPORT`` is set — an end-of-run machine-readable report with
per-level phase seconds and data-plane accounting.
"""

from __future__ import annotations

import asyncio
import os
import time

import jax
import numpy as np

from .. import obs
from ..ops import ibdcf
from ..protocol.leader_rpc import RpcLeader
from ..protocol.rpc import CollectorClient
from ..utils import compile_cache
from ..utils import config as configmod
from ..workloads import OUTPUT_CSV, rides, sample_points, strings


def _split(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


async def _emit_window(res, window: int) -> None:
    """Per-window heavy-hitter lines for the streaming (FHH_WINDOWS)
    mode — each tumbling window reports its own hitter set."""
    for row, c in zip(res.decode_ints(), res.counts):
        obs.emit(
            "hitter", window=window, value=str(row.tolist()), count=int(c)
        )


def keygen_report(cfg, rng, engine: str) -> None:
    """Key-size / keys-per-second report (ref: leader.rs:90-104, 319-329).

    Runs on the fast engine for the backend (ibdcf.best_engine) with one
    untimed warmup call so the report measures throughput, not the one-off
    XLA compile."""
    n = min(cfg.num_sites, 1000)
    pts = np.stack(
        [strings.generate_random_bit_vectors(rng, cfg.data_len, cfg.n_dims) for _ in range(n)]
    )
    if engine != "np":  # numpy has no compile step to warm
        k0, _ = ibdcf.gen_l_inf_ball(pts, 1, rng, engine=engine)
        jax.block_until_ready(k0)
    t0 = time.perf_counter()
    k0, _ = ibdcf.gen_l_inf_ball(pts, 1, rng, engine=engine)
    jax.block_until_ready(k0)
    dt = time.perf_counter() - t0
    per_client = sum(np.asarray(x)[0].nbytes for x in k0)
    obs.emit(
        "keygen.report",
        engine=engine,
        key_bytes=per_client,
        n_keys=n,
        seconds=round(dt, 3),
        sec_per_key=round(dt / n, 6),
    )


async def amain() -> None:
    import contextlib

    cfg, _, nreqs = configmod.get_args("Leader", get_n_reqs=True)
    # persistent XLA compile cache (FHH_COMPILE_CACHE): repeat runs skip
    # the per-bucket program compiles entirely
    compile_cache.enable()
    rng = np.random.default_rng()

    # backend knob, like bin/server.py: "cpu" pins every uncommitted array
    # op (keygen here) onto the host backend
    ctx = (
        jax.default_device(jax.devices("cpu")[0])
        if cfg.backend == "cpu"
        else contextlib.nullcontext()
    )
    with ctx:
        await _run(cfg, nreqs, rng)


async def _run(cfg, nreqs: int, rng) -> None:
    # fast keygen engine for the backend (amain's default_device(cpu)
    # context is visible to best_engine via utils.effective_platform)
    engine = ibdcf.best_engine()
    reg = obs.default_registry()
    keygen_report(cfg, rng, engine)

    # each setup stage gets its own phase so the run report's keygen
    # seconds mean keygen, not keygen+sampling+sketch
    obs.emit("sampling", distribution=cfg.distribution, n=nreqs)
    with reg.span("sampling"):
        pts = sample_points(cfg, nreqs, rng)
    with reg.span("keygen"):
        k0, k1 = ibdcf.gen_l_inf_ball(pts, cfg.ball_size, rng, engine=engine)

    sk0 = sk1 = None
    if cfg.malicious:
        # malicious-security material: per-dimension MAC'd payload DPFs
        # over the client's point + Beaver triples (protocol/sketch.py;
        # ref north star names the resurrected sketch.rs path).  Works
        # for the flagship fuzzy multi-dim workloads: one DPF per dim
        # sharing the client's MAC key, verified per dim.
        from ..ops.fields import F255, FE62
        from ..protocol import sketch as sketchmod

        seeds = rng.integers(
            0, 2**32, size=(nreqs, cfg.n_dims, 2, 4), dtype=np.uint32
        )
        cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
        with reg.span("sketch_gen"):
            sk0, sk1 = sketchmod.gen(seeds, pts, FE62, F255, cseed)

    h0, p0 = _split(cfg.server0)
    h1, p1 = _split(cfg.server1)
    # multi-tenant collection sessions: FHH_COLLECTION names the
    # server-side session this leader's crawl runs in (protocol/
    # sessions.py) — N leaders with distinct collections share one
    # server pair concurrently; unset = the default session
    collection = os.environ.get("FHH_COLLECTION") or None
    c0 = await CollectorClient.connect(h0, p0, collection=collection)
    c1 = await CollectorClient.connect(h1, p1, collection=collection)

    lead = RpcLeader(cfg, c0, c1)
    # per-f_bucket compile warmup (FHH_WARMUP=0 opts out): bucket
    # recompiles run now — and land in the FHH_COMPILE_CACHE when set —
    # instead of billing into the crawl itself.  Needs the key shapes on
    # the servers, so it rides after the upload in both paths below.
    warm = os.environ.get("FHH_WARMUP", "1") != "0"

    async def _maybe_warm():
        if not warm:
            return
        t_w = time.perf_counter()
        info = await lead.warmup()
        obs.emit(
            "warmup.done",
            seconds=round(time.perf_counter() - t_w, 2),
            f_buckets=info["f_buckets"],
        )

    # streaming-ingest mode (FHH_WINDOWS=N, N > 1): instead of one bulk
    # upload, the fabricated clients submit their key shares continuously
    # through the admission-controlled front door (submit_keys) in N
    # tumbling windows; each window seals at its boundary and its frozen
    # snapshot is crawled while the next window keeps ingesting.  The
    # production shape on the ROADMAP, driven here from one process.
    windows = max(1, int(os.environ.get("FHH_WINDOWS", "1")))
    if windows > 1:
        # malicious mode streams too: the clients' sketch material rides
        # each submission and every sealed window commits its own
        # challenge root (protocol/rpc.py window_seal)
        import jax as _jax

        from ..protocol.leader_rpc import WindowedIngest

        sk0_leaves = None if sk0 is None else _jax.tree.leaves(sk0)
        sk1_leaves = None if sk1 is None else _jax.tree.leaves(sk1)
        t0 = time.perf_counter()
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        wi = WindowedIngest(lead)
        per_w = (nreqs + windows - 1) // windows
        bs = max(1, cfg.addkey_batch_size)
        crawl_task = None
        for w in range(windows):
            lo_w, hi_w = w * per_w, min((w + 1) * per_w, nreqs)
            for chunk_no, lo in enumerate(range(lo_w, hi_w, bs)):
                sl = slice(lo, min(lo + bs, hi_w))
                await wi.submit(
                    f"site{chunk_no % max(1, cfg.num_sites)}",
                    tuple(np.asarray(x)[sl] for x in k0),
                    tuple(np.asarray(x)[sl] for x in k1),
                    sk0_chunk=(
                        None if sk0_leaves is None
                        else [np.asarray(x)[sl] for x in sk0_leaves]
                    ),
                    sk1_chunk=(
                        None if sk1_leaves is None
                        else [np.asarray(x)[sl] for x in sk1_leaves]
                    ),
                )
            stats = await wi.seal_window()
            if crawl_task is not None:
                await _emit_window(await crawl_task, w - 1)
            if stats["keys"]:
                crawl_task = asyncio.create_task(wi.crawl_window(w))
            else:
                crawl_task = None
        if crawl_task is not None:
            await _emit_window(await crawl_task, windows - 1)
        obs.emit("crawl.done", seconds=round(time.perf_counter() - t0, 2))
        return

    # supervised crawl (FHH_SUPERVISE=0 opts out), malicious mode
    # included — the per-level challenge ratchet makes sketch crawls
    # restartable (see protocol/sketch.py): the leader checkpoints every
    # FHH_CKPT_EVERY levels and, on any transport loss or server restart,
    # restores both servers and re-runs only the lost levels
    supervise = os.environ.get("FHH_SUPERVISE", "1") != "0"
    t0 = time.perf_counter()
    if supervise:
        res = await lead.run_supervised(
            nreqs, k0, k1, sk0, sk1,
            checkpoint_every=int(os.environ.get("FHH_CKPT_EVERY", "16")),
            warmup=warm,
        )
    else:
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        await lead.upload_keys(k0, k1, sk0, sk1)
        obs.emit("addkeys.done", seconds=round(time.perf_counter() - t0, 2))
        await _maybe_warm()
        t0 = time.perf_counter()
        res = await lead.run(nreqs)
    obs.emit("crawl.done", seconds=round(time.perf_counter() - t0, 2))

    for row, c in zip(res.decode_ints(), res.counts):
        obs.emit("hitter", value=str(row.tolist()), count=int(c))
    if cfg.distribution == "rides" and res.paths.shape[0]:
        os.makedirs(os.path.dirname(OUTPUT_CSV), exist_ok=True)
        rides.save_heavy_hitters(res.paths, OUTPUT_CSV)
        obs.emit("csv.written", path=OUTPUT_CSV, hitters=int(res.paths.shape[0]))


def main() -> None:
    # /metrics exporter claims its port FIRST (before amain's arg
    # validation) so even a leader that dies on a config error was
    # scrapeable; bind failure degrades with a structured warn
    # (obs.exporter — zero-cost when FHH_METRICS_PORT is unset)
    obs.exporter.maybe_start("leader")
    # fresh-compile telemetry: compiles attribute to the active phase
    obs.devmem.install_compile_listener()
    # shared exit contract (obs.exit_report): SIGTERM -> SystemExit so the
    # run report is still written — a timed-out run leaves per-level
    # phase/byte accounting up to the level it died in (plus the
    # heartbeat trail naming it).  The leader keeps the bare
    # $FHH_RUN_REPORT path; the servers claim .s0/.s1 siblings.
    # Likewise for the distributed-trace ring (FHH_TRACE_DIR): the
    # leader's segment is named for it, and exit_report flushes it.
    obs.trace.claim_tag("leader")
    with obs.exit_report():
        asyncio.run(amain())


if __name__ == "__main__":
    main()
