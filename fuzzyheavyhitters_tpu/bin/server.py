"""Collector server binary (ref: src/bin/server.rs).

Run one per party::

    python -m fuzzyheavyhitters_tpu.bin.server --config configs/config.json --server_id 0
    python -m fuzzyheavyhitters_tpu.bin.server --config configs/config.json --server_id 1

Startup order mirrors the reference (server.rs:344-354): the data-plane
socket between the two servers is established BEFORE the leader-facing RPC
listener binds, server1 listening / server0 dialing with retries.

Fault tolerance: set ``FHH_CKPT_DIR`` to a writable directory to enable
the ``tree_checkpoint``/``tree_restore`` verbs — a supervised leader
(``FHH_SUPERVISE``, bin/leader.py) then rolls a faulted crawl back to the
last checkpoint instead of restarting it; without the dir the server
still reconnect-dedups replayed verbs, and recovery degrades to
restart-from-scratch.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from .. import obs
from ..protocol.rpc import CollectorServer
from ..utils import compile_cache
from ..utils import config as configmod


def _split(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def _fleet_register(server, server_id: int, host: str, port: int) -> None:
    """Drop this server half's registration row into the shared fleet
    directory (``FHH_FLEET`` names the dir; ``FHH_FLEET_PAIR`` names the
    host pair, default ``pair0``).  ``FleetDirectory.scan`` folds the two
    ``<pair>_s<id>.json`` halves into one :class:`HostPair` row; the boot
    id is what the supervisor's liveness probe compares against, so a
    restarted process re-registers as a NEW boot.  Atomic tmp+rename: a
    scan never reads a torn row."""
    fleet_dir = os.environ.get("FHH_FLEET")
    if not fleet_dir:
        return
    os.makedirs(fleet_dir, exist_ok=True)
    pair = os.environ.get("FHH_FLEET_PAIR") or "pair0"
    row = {
        "pair": pair,
        "server_id": server_id,
        "host": host,
        "port": port,
        "boot_id": server._boot_id,
        "capacity": int(os.environ.get("FHH_FLEET_CAPACITY", "4")),
        "ts": round(time.time(), 3),
    }
    path = os.path.join(fleet_dir, f"{pair}_s{server_id}.json")
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(row, f)
    os.replace(tmp, path)
    obs.emit("fleet.registered", pair=pair, server=server_id,
             boot_id=server._boot_id)


async def amain(cfg, server_id: int) -> None:
    import contextlib

    import jax

    host0, port0 = _split(cfg.server0)
    host1, port1 = _split(cfg.server1)
    my_host, my_port = (host0, port0) if server_id == 0 else (host1, port1)
    # data plane rides on server1's port + 1 (ref: server.rs:41, 208-233)
    peer_host = host1 if server_id == 0 else my_host
    peer_port = port1 + 1

    # cfg.backend selects the aggregation device: "cpu" pins every
    # uncommitted array op onto the host backend (useful where no
    # accelerator is attached); "tpu" (default) keeps JAX's default device
    # and, when an accelerator actually resolved (not an XLA:CPU fallback,
    # where unrolled rounds only inflate compiles), switches the PRG to its
    # unrolled round loop (faster chip execution — ops/prg.py).
    if cfg.backend != "cpu" and jax.default_backend() != "cpu":
        from ..ops import prg

        prg.CHACHA_UNROLL = True
    ctx = (
        jax.default_device(jax.devices("cpu")[0])
        if cfg.backend == "cpu"
        else contextlib.nullcontext()
    )
    with ctx:
        ckpt_dir = os.environ.get("FHH_CKPT_DIR") or None
        if ckpt_dir is not None:
            os.makedirs(ckpt_dir, exist_ok=True)
        # multi-chip client sharding: FHH_DATA_DEVICES overrides the
        # config knob (0 = auto: all local devices on an accelerator
        # host), and FHH_MESH_FAULTS arms the consumed-once device-loss
        # schedule (resilience.chaos mesh grammar: mesh:kill@level=N ...)
        # for recovery drills against a live server
        dd = os.environ.get("FHH_DATA_DEVICES")
        if dd is not None:
            cfg.server_data_devices = int(dd)
        mesh_chaos = None
        faults = os.environ.get("FHH_MESH_FAULTS")
        if faults:
            from ..resilience.chaos import MeshChaos, parse_mesh_faults

            mesh_chaos = MeshChaos(parse_mesh_faults(faults))
        server = CollectorServer(
            server_id, cfg, ckpt_dir=ckpt_dir, _mesh_chaos=mesh_chaos
        )
        srv = await server.start(my_host, my_port, peer_host, peer_port)
        # fleet directory registration (protocol/fleet.py): after start so
        # the row only ever advertises a pair that is actually listening
        _fleet_register(server, server_id, my_host, my_port)
        obs.emit("server.serving", server=server_id, host=my_host, port=my_port)
        async with srv:
            await srv.serve_forever()


def _peek_server_id(argv: list[str]) -> int | None:
    """Cheap pre-parse of ``--server_id`` so the /metrics port claim can
    happen FIRST (see main); full validation still belongs to get_args."""
    for i, a in enumerate(argv):
        if a == "--server_id" and i + 1 < len(argv):
            try:
                return int(argv[i + 1])
            except ValueError:
                return None
        if a.startswith("--server_id="):
            try:
                return int(a.split("=", 1)[1])
            except ValueError:
                return None
    return None


def main() -> None:
    import sys

    # /metrics exporter claims its port FIRST — before arg validation —
    # so a server that dies on a config error is still scrapeable for
    # the seconds it lives, and a port conflict surfaces immediately at
    # startup rather than after an expensive setup.  Bind failure is a
    # structured warn, never a crash (the PR 1 report-path discipline);
    # FHH_METRICS_PORT unset costs one getenv.
    sid = _peek_server_id(sys.argv[1:])
    obs.exporter.maybe_start(f"s{sid}" if sid in (0, 1) else "server")
    # arg validation runs BEFORE the exit-report contract: a server that
    # dies here has no identity yet, and writing the run report to the
    # bare shared $FHH_RUN_REPORT path would clobber the leader's
    cfg, server_id, _ = configmod.get_args("Server", get_server_id=True)
    if server_id not in (0, 1):
        raise SystemExit(f"server_id must be 0 or 1, got {server_id}")
    # persistent XLA compile cache (FHH_COMPILE_CACHE): a restarted
    # server re-reads its crawl programs instead of recompiling them —
    # recovery cost stays network + restore, not compile churn
    compile_cache.enable()
    # fresh-compile telemetry (obs.devmem): every backend compile counts
    # under the active phase; past the warmup ladder it is alert fodder
    obs.devmem.install_compile_listener()
    # both servers + the leader inherit ONE $FHH_RUN_REPORT from the shared
    # environment; the leader keeps the bare path, each server claims a
    # .s<id> sibling so the last exiter can't clobber the others' reports
    obs.claim_report_path(f"s{server_id}")
    # ... and names its distributed-trace ring segment the same way
    # (FHH_TRACE_DIR; `python -m fuzzyheavyhitters_tpu.obs.trace merge`
    # folds all three processes' rings into one Perfetto timeline)
    obs.trace.claim_tag(f"s{server_id}")
    # shared exit contract (obs.exit_report): SIGTERM -> SystemExit, so a
    # drained/killed server still leaves its run report (phase seconds,
    # data-plane bytes, fetch counts) + a heartbeat trail for the postmortem
    with obs.exit_report():
        asyncio.run(amain(cfg, server_id))


if __name__ == "__main__":
    main()
