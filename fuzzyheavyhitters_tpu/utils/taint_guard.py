"""Runtime shadow-taint sanitizer: the dynamic half of fhh-taint.

The static analyzer (:mod:`fuzzyheavyhitters_tpu.analysis.taint`)
proves the declared source table cannot reach an obs sink *through the
flows it can see* — but its call resolution is name-based and
per-module, and an unresolved cross-module hop drops taint by design
(the conservative-for-noise direction).  This module closes the gap
dynamically, sanitizer-style: under ``FHH_DEBUG_TAINT=1`` the source
constructors :func:`register` their secret buffers' bytes, and every
obs sink boundary (log emit, metrics render, trace record, alert fire,
report build) runs :func:`check` over the rendered payload, asserting
no registered buffer — byte-equal, byte-contained, or interpolated as
its repr/hex text — crosses.  The existing tier-1 e2e + chaos suites
then exercise the assertion on every scrape, trace, and recovery path
they already cover.

Off by default, zero overhead when off: with the env var unset,
:func:`register` returns before touching the payload and :func:`check`
is one module-global bool test — the obs hot paths pay a function call,
never a hash or a scan.

Sanctioned flows run inside :func:`declassified`, whose required
``reason`` is the runtime twin of the written
``# fhh-taint: declassified(reason)`` contract at the same static
site (grep the reason text to find its twin).

:data:`_DEFAULT_SOURCES` names the registration sites this repo arms —
one entry per source the pyproject ``[tool.fhh-lint.taint]`` table
declares for a RUNTIME-REGISTRABLE buffer (drift-tested in
tests/test_taint.py).  The window/sketch roots are deliberately absent:
they are one-way commitments CARRIED by seal stats and checkpoints by
design (sketch.window_root), and registering them would assert against
the protocol itself.
"""

from __future__ import annotations

import contextlib
import contextvars
import os

__all__ = [
    "TaintViolation",
    "check",
    "declassified",
    "enabled",
    "register",
    "reset",
]

_ENV = "FHH_DEBUG_TAINT"

# registration sites armed in THIS repo: source label (matching the
# pyproject [tool.fhh-lint.taint] key) -> the constructor that calls
# register().  Drift-tested against pyproject so a new declared source
# cannot ship without deciding whether the runtime twin registers it.
_DEFAULT_SOURCES = {
    "CollectionSession._sec_seed": "protocol/sessions.py + rpc._setup_secure",
    "CollectionSession._sketch_seed": "rpc plane_handshake coin flip",
    "CollectionSession._ratchet_digest": "sketch.transcript_init/_absorb",
    "OtExtSender._seeds": "ops/otext.py OtExtSender.__init__",
    "OtExtSender.s_bits": "ops/otext.py OtExtSender.__init__",
    "OtExtReceiver._seeds0": "ops/otext.py OtExtReceiver.__init__",
    "OtExtReceiver._seeds1": "ops/otext.py OtExtReceiver.__init__",
    "CollectionSession._imported_pool_shares":
        "rpc session_import pool reconstruction",
}

# True once ANY source registered in this process: the sink-boundary
# check() calls reduce to one global bool test until then
_armed = False

# registered markers: byte images of source buffers, and the string
# forms an f-string interpolation would render them as
_byte_markers: dict[bytes, str] = {}
_text_markers: dict[str, str] = {}

# depth of sanctioned declassification windows for the current task
# (contextvars: one task's window never blesses a neighbour's render)
_declass_depth: contextvars.ContextVar[int] = contextvars.ContextVar(
    "fhh_declass_depth", default=0
)

# ignore trivially-short text markers: a 2-char scalar repr would trip
# on unrelated digits in any rendered line
_MIN_TEXT_MARKER = 8


class TaintViolation(AssertionError):
    """A registered secret buffer (or its rendered text) crossed an obs
    sink boundary.  Subclasses AssertionError so test suites that treat
    assertion failures as hard failures catch it without new plumbing."""


def enabled() -> bool:
    """True when the sanitizer is switched on (``FHH_DEBUG_TAINT=1``).
    Read per registration — source construction is rare (session
    handshake, OT setup), so the getenv never sits on a hot path."""
    return os.environ.get(_ENV, "") == "1"


_NOOP = contextlib.nullcontext()


class _DeclassifiedWindow:
    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _declass_depth.set(_declass_depth.get() + 1)
        return None

    def __exit__(self, *exc):
        _declass_depth.reset(self._token)
        return False


def declassified(reason: str):
    """Suspend taint assertions for the current task while the body
    runs — the runtime form of a written ``declassified(reason)``
    contract.  ``reason`` is mandatory and non-empty."""
    if not reason or not reason.strip():
        raise ValueError("declassified() requires a written reason")
    if not _armed:
        return _NOOP
    return _DeclassifiedWindow()


def _buffer_bytes(value) -> bytes | None:
    if isinstance(value, (bytes, bytearray, memoryview)):
        return bytes(value)
    tobytes = getattr(value, "tobytes", None)
    if callable(tobytes):
        try:
            return tobytes()
        # fhh-lint: disable=broad-except (a sanitizer must never crash
        # the protocol it watches: an exotic tobytes() is just skipped)
        except Exception:
            return None
    return None


def register(label: str, value) -> None:
    """Register one secret buffer under the sanitizer.  ``value`` is a
    host ndarray or bytes-like (callers pass host-side state — the
    source constructors all build np arrays or digests).  No-op (and
    free) when the sanitizer is disabled; never raises on an exotic
    value — a sanitizer must not crash the protocol it watches."""
    global _armed
    if not enabled() or value is None:
        return
    raw = _buffer_bytes(value)
    if raw is None or len(raw) == 0:
        return
    _armed = True
    _byte_markers[raw] = label
    _text_markers.setdefault(raw.hex(), label)
    try:  # the f-string interpolation form: str(np.ndarray) / str(bytes)
        text = str(value).strip()
    # fhh-lint: disable=broad-except (sanitizer-never-crashes: a value
    # whose __str__ throws simply gets no text marker)
    except Exception:
        return
    if len(text) >= _MIN_TEXT_MARKER:
        _text_markers.setdefault(text, label)


def reset() -> None:
    """Drop all registered markers and disarm (test isolation)."""
    global _armed
    _armed = False
    _byte_markers.clear()
    _text_markers.clear()


def _scan_text(text: str, sink: str) -> None:
    for marker, label in _text_markers.items():
        if marker in text:
            # fhh-lint: disable=secret-to-sink (`label` here is the
            # SOURCE NAME from the registry — "CollectionSession
            # ._sec_seed" — never the secret bytes themselves)
            raise TaintViolation(
                f"rendered text at sink '{sink}' contains the "
                f"interpolated bytes of registered source '{label}' — "
                "key material crossed an obs boundary (fhh-taint "
                "contract violation; mask/open it first or wrap a "
                "sanctioned flow in taint_guard.declassified(reason))"
            )


def _scan_bytes(raw: bytes, sink: str) -> None:
    for marker, label in _byte_markers.items():
        if marker == raw or (len(raw) > len(marker) and marker in raw):
            # fhh-lint: disable=secret-to-sink (`label` is the source
            # NAME from the registry, never the secret bytes)
            raise TaintViolation(
                f"payload at sink '{sink}' carries the raw bytes of "
                f"registered source '{label}' — key material crossed "
                "an obs boundary (fhh-taint contract violation)"
            )


def _scan(obj, sink: str, depth: int) -> None:
    if depth > 6 or obj is None or isinstance(obj, (bool, int, float)):
        return
    if isinstance(obj, str):
        _scan_text(obj, sink)
        return
    if isinstance(obj, (bytes, bytearray, memoryview)):
        _scan_bytes(bytes(obj), sink)
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _scan(k, sink, depth + 1)
            _scan(v, sink, depth + 1)
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            _scan(v, sink, depth + 1)
        return
    raw = _buffer_bytes(obj)
    if raw is not None:
        _scan_bytes(raw, sink)
        return
    # anything else renders via str() at the sink eventually; compare
    # the rendered form (bounded: reprs of obs payloads are small)
    try:
        _scan_text(str(obj), sink)
    except TaintViolation:
        raise
    # fhh-lint: disable=broad-except (sanitizer-never-crashes: objects
    # whose __str__ throws are not scannable and not sink-renderable)
    except Exception:
        pass


def check(obj, *, sink: str) -> None:
    """Assert no registered source buffer is reachable from ``obj`` —
    byte-equal, byte-contained, or rendered as text.  Called at every
    obs sink boundary; one bool test when the sanitizer is off."""
    if not _armed or _declass_depth.get():
        return
    _scan(obj, sink, 0)
