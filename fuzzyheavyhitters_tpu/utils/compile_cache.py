"""Persistent XLA compilation cache wiring (``FHH_COMPILE_CACHE``).

Compile churn is a first-order cost of the crawl: every new frontier
bucket size (1 -> 2 -> 4 ... as sites' prefixes separate) recompiles the
expand / GC / OT programs, and through a remote-chip tunnel each compile
is tens of seconds of wall-clock billed into whatever happens to run
first — in bench.py's case, into the measured sections and ultimately
past the harness budget (BENCH_r05 rc=124).  JAX ships a persistent
on-disk compilation cache keyed by the HLO fingerprint; pointing it at a
stable directory makes every *repeat* compile (a second bench section, a
restarted server, the next bench round) a cache read instead.

``enable()`` is idempotent and safe everywhere: it reads
``FHH_COMPILE_CACHE`` (a directory path; created if missing), configures
``jax.config.jax_compilation_cache_dir`` plus the thresholds that would
otherwise skip small/fast programs, and returns the path — or ``None``
when the knob is unset or this JAX build lacks the config (the crawl
then simply recompiles as before).  The binaries (bin/leader, bin/server,
bin/mesh) and bench.py call it at startup; bench additionally defaults
the knob for its child processes so all sections share one cache.
"""

from __future__ import annotations

import os

_enabled: str | None = None


def enable(path: str | None = None) -> str | None:
    """Wire JAX's persistent compilation cache at ``path`` (default:
    ``$FHH_COMPILE_CACHE``).  Returns the directory in use, or ``None``
    when disabled/unsupported.  Idempotent — the first successful call
    wins; later calls return the established path."""
    global _enabled
    if _enabled is not None:
        return _enabled
    path = path or os.environ.get("FHH_COMPILE_CACHE")
    if not path:
        return None
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip sub-second / sub-MB programs — exactly
        # the per-bucket expand/GC kernels whose churn this exists to kill
        for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):
                pass  # older JAX: the cache still works, thresholds stay
    except (AttributeError, ValueError, OSError) as e:
        from .. import obs

        obs.emit(
            "compile_cache.unavailable",
            severity="warn",
            path=path,
            error=f"{type(e).__name__}: {e}",
        )
        return None
    _enabled = path
    return path
