"""Persistent XLA compilation cache wiring (``FHH_COMPILE_CACHE``).

Compile churn is a first-order cost of the crawl: every new frontier
bucket size (1 -> 2 -> 4 ... as sites' prefixes separate) recompiles the
expand / GC / OT programs, and through a remote-chip tunnel each compile
is tens of seconds of wall-clock billed into whatever happens to run
first — in bench.py's case, into the measured sections and ultimately
past the harness budget (BENCH_r05 rc=124).  JAX ships a persistent
on-disk compilation cache keyed by the HLO fingerprint; pointing it at a
stable directory makes every *repeat* compile (a second bench section, a
restarted server, the next bench round) a cache read instead.

``enable()`` is idempotent and safe everywhere: it reads
``FHH_COMPILE_CACHE`` (a directory path; created if missing), configures
``jax.config.jax_compilation_cache_dir`` plus the thresholds that would
otherwise skip small/fast programs, and returns the path — or ``None``
when the knob is unset or this JAX build lacks the config (the crawl
then simply recompiles as before).  The binaries (bin/leader, bin/server,
bin/mesh) and bench.py call it at startup; bench additionally defaults
the knob for its child processes so all sections share one cache.

:func:`backend_compiles` counts fresh XLA backend compiles process-wide
(via jax.monitoring) — the acceptance instrument for warmup coverage:
a crawl over warmed shapes must add ZERO to it.
"""

from __future__ import annotations

import os
import threading

_enabled: str | None = None

# fresh-compile accounting: every backend compile (an XLA compile that
# was NOT served from any cache — the thing warmup exists to take off
# the measured clock) bumps this counter via jax.monitoring.  Tests pin
# the warmed-crawl contract with it: a crawl on warmed shapes must
# report a delta of ZERO (a per-batch static arg, a fresh jit wrapper
# per call, or a warmup coverage hole all break that loudly).
_compile_lock = threading.Lock()
_compile_count = 0  # fhh-guard: _compile_count=_compile_lock
_listener_on = False  # fhh-guard: _listener_on=_compile_lock

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_event_duration(name: str, *_a, **_k) -> None:
    global _compile_count
    if name == _COMPILE_EVENT:
        with _compile_lock:
            _compile_count += 1


def backend_compiles() -> int:
    """Process-wide count of fresh XLA backend compiles so far.  The
    jax.monitoring listener registers on first use (and stays for the
    process lifetime — listeners cannot unregister portably); snapshot
    before and after the measured region and compare deltas."""
    global _listener_on
    with _compile_lock:
        if not _listener_on:
            import jax

            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            _listener_on = True
        return _compile_count


def enable(path: str | None = None) -> str | None:
    """Wire JAX's persistent compilation cache at ``path`` (default:
    ``$FHH_COMPILE_CACHE``).  Returns the directory in use, or ``None``
    when disabled/unsupported.  Idempotent — the first successful call
    wins; later calls return the established path."""
    global _enabled
    if _enabled is not None:
        return _enabled
    path = path or os.environ.get("FHH_COMPILE_CACHE")
    if not path:
        return None
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip sub-second / sub-MB programs — exactly
        # the per-bucket expand/GC kernels whose churn this exists to kill
        for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):
                pass  # older JAX: the cache still works, thresholds stay
    except (AttributeError, ValueError, OSError) as e:
        from .. import obs

        obs.emit(
            "compile_cache.unavailable",
            severity="warn",
            path=path,
            error=f"{type(e).__name__}: {e}",
        )
        return None
    _enabled = path
    return path
