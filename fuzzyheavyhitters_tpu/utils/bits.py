"""Bit-vector codecs and bitstring arithmetic.

Host-side helpers used by client key generation and the workload samplers.
Semantics follow the reference's utilities (ref: src/lib.rs:56-190):

- ``u32_to_bits``     LSB-first bit expansion           (lib.rs:56)
- ``msb_u32_to_bits`` MSB-first bit expansion           (lib.rs:67)
- ``bits_to_u32``     interprets ``bits[0]`` as the MSB (lib.rs:78)
- ``string_to_bits``  per-byte LSB-first                (lib.rs:90)
- ``all_bit_vectors`` all 2^d bit patterns, bit j of pattern i = (i >> j) & 1
                      (lib.rs:125)
- ``add_bitstrings`` / ``subtract_bitstrings``: MSB-first fixed-point
  arithmetic (lib.rs:131, 153).

Divergence from the reference, by design: the reference's ``add_bitstrings``
grows the result by one bit on carry-out (which would misalign key levels
against the tree depth) and its subtract wraps modulo 2^n.  Our ball-bound
helpers saturate at the domain edges instead — identical behavior on every
non-overflowing input, and well-defined on the rest (matching what the
clamped coords variant at ibDCF.rs:189-205 does).
"""

from __future__ import annotations

import numpy as np


def u32_to_bits(nbits: int, value: int) -> np.ndarray:
    """LSB-first bit expansion of ``value`` into ``nbits`` bools."""
    assert 0 <= nbits <= 32
    return np.array([(value >> i) & 1 == 1 for i in range(nbits)], dtype=bool)


def msb_u32_to_bits(nbits: int, value: int) -> np.ndarray:
    """MSB-first bit expansion of ``value`` into ``nbits`` bools."""
    assert 0 <= nbits <= 32
    return np.array([(value >> i) & 1 == 1 for i in reversed(range(nbits))], dtype=bool)


def bits_to_u32(bits) -> int:
    """Interpret ``bits[0]`` as the most-significant bit."""
    bits = np.asarray(bits, dtype=bool)
    assert bits.size <= 32
    out = 0
    for b in bits:
        out = (out << 1) | int(b)
    return out


def bits_to_int(bits) -> int:
    """MSB-first interpretation with no width limit (for >32-bit strings)."""
    out = 0
    for b in np.asarray(bits, dtype=bool):
        out = (out << 1) | int(b)
    return out


def int_to_bits(nbits: int, value: int) -> np.ndarray:
    """MSB-first expansion with no 32-bit width limit."""
    return np.array([(value >> i) & 1 == 1 for i in reversed(range(nbits))], dtype=bool)


def string_to_bits(s: str) -> np.ndarray:
    """Per-byte LSB-first expansion of the UTF-8 bytes of ``s``."""
    out = []
    for byte in s.encode("utf-8"):
        out.extend((byte >> i) & 1 == 1 for i in range(8))
    return np.array(out, dtype=bool)


def bits_to_string(bits) -> str:
    bits = np.asarray(bits, dtype=bool)
    assert bits.size % 8 == 0
    data = bytearray()
    for i in range(bits.size // 8):
        byte = 0
        for j in range(8):
            byte |= int(bits[8 * i + j]) << j
        data.append(byte)
    return data.decode("utf-8")


def all_bit_vectors(dim: int) -> np.ndarray:
    """All 2^dim bit patterns; pattern i has bit j = (i >> j) & 1.

    Row ordering matches the reference's frontier-expansion child order
    (ref: src/lib.rs:125-129, src/collect.rs:384), which fixes the layout of
    per-level count vectors handed back to the leader.
    """
    i = np.arange(1 << dim)[:, None]
    j = np.arange(dim)[None, :]
    return ((i >> j) & 1).astype(bool)


def add_bitstrings(alpha, beta) -> np.ndarray:
    """MSB-first addition, saturating at 2^n - 1 (n = max input width)."""
    alpha = np.asarray(alpha, dtype=bool)
    beta = np.asarray(beta, dtype=bool)
    n = max(alpha.size, beta.size)
    total = bits_to_int(alpha) + bits_to_int(beta)
    total = min(total, (1 << n) - 1)
    return int_to_bits(n, total)


def subtract_bitstrings(alpha, beta) -> np.ndarray:
    """MSB-first subtraction, saturating at 0."""
    alpha = np.asarray(alpha, dtype=bool)
    beta = np.asarray(beta, dtype=bool)
    n = max(alpha.size, beta.size)
    total = max(bits_to_int(alpha) - bits_to_int(beta), 0)
    return int_to_bits(n, total)


def i16_to_bitvec(value: int) -> np.ndarray:
    """i16 -> 16 bools, MSB-first, two's complement (ref: sample_driving_data.rs:25)."""
    return int_to_bits(16, int(value) & 0xFFFF)


def bitvec_to_i16(bits) -> int:
    """16 bools MSB-first -> i16 (ref: sample_driving_data.rs:31)."""
    v = bits_to_int(bits)
    return v - 0x10000 if v >= 0x8000 else v


def i16_to_ob_bits(value: int) -> np.ndarray:
    """i16 -> 16 bools, MSB-first **offset-binary** (sign bit flipped).

    Unsigned lexicographic order on these strings equals signed order on the
    values, which is what the ibDCF comparator needs; raw two's complement
    (the reference's encoding, sample_driving_data.rs:25) sorts negatives
    above positives and silently breaks zero-crossing intervals."""
    return int_to_bits(16, (int(value) & 0xFFFF) ^ 0x8000)


def ob_bits_to_i16(bits) -> int:
    """16 bools MSB-first offset-binary -> i16 (inverse of i16_to_ob_bits)."""
    v = bits_to_int(bits) ^ 0x8000
    return v - 0x10000 if v >= 0x8000 else v


def pack_bits_lsb(bits: np.ndarray, axis: int = -1) -> np.ndarray:
    """Pack a bool array along ``axis`` (length <= 32) into uint32, bit j = bits[j]."""
    bits = np.moveaxis(np.asarray(bits, dtype=bool), axis, -1)
    assert bits.shape[-1] <= 32
    weights = (np.uint32(1) << np.arange(bits.shape[-1], dtype=np.uint32)).astype(np.uint32)
    return (bits.astype(np.uint32) * weights).sum(axis=-1).astype(np.uint32)
