from . import bits  # noqa: F401
from .config import Config, load_config  # noqa: F401
