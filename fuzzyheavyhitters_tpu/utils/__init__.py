from . import bits  # noqa: F401
from .config import Config, load_config  # noqa: F401


def effective_platform() -> str:
    """Platform of the EFFECTIVE default device: honors an active
    ``jax.default_device(...)`` context (which the test suite uses to pin
    compile-bound tests to the host) before falling back to the process
    default backend.  The single source of truth for engine selection —
    ops/ibdcf.best_engine and protocol/collect._expand_engine both route
    through here, so a platform-string quirk is fixed in one place."""
    import jax

    dd = jax.config.jax_default_device
    if dd is not None:
        return getattr(dd, "platform", dd)
    return jax.default_backend()
