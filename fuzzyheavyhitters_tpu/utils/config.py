"""Config system — JSON schema compatible with the reference's config files.

The schema mirrors ``Config`` in the reference (ref: src/config.rs:5-16):
``data_len, n_dims, ball_size, addkey_batch_size, num_sites, threshold,
zipf_exponent, server0, server1, distribution``.  The reference's shipped
JSON files also carry ``sketch_batch_size`` / ``sketch_batch_size_last``
keys that its parser ignores (config.rs vs src/bin/config.json:9-10); here
they are live in the spec helper (protocol/sketch.py ``verify_level``
chunks the client axis by them; the *_last knob covers the 8-limb F255
level).  The production verify (protocol/rpc.py ``sketch_verify``) runs
the whole level as ONE fused device program — sharded by
``sketch_shards`` below — so the host chunking knobs no longer gate it.

Extra TPU-native knobs (all defaulted so reference configs load unchanged):

- ``backend``: "tpu" | "cpu" — aggregation device; "cpu" pins the server's
  array ops onto the host backend (bin/server.py).
- ``secure_exchange``: if True, the GC+OT 2PC data plane (protocol/secure.py);
  if False, the trusted-exchange mode that reveals per-(node,client)
  equality bits between the two servers (counts still travel as field
  shares toward the leader).
- ``malicious``: if True, clients attach MAC'd sketch keys + Beaver triples
  (protocol/sketch.py — the resurrected sketch.rs/mpc.rs path named in
  BASELINE.json) and the servers verify every level, excluding cheating
  clients via the liveness gate.  Covers the flagship fuzzy multi-dim
  workloads: one payload DPF per dimension sharing the client's MAC key,
  verified per dim with per-dim prefix dedup (the product frontier repeats
  per-dim prefixes across slots).  Caveat: verification follows the
  *frontier* the servers actually crawl — depth 1 is checked in full
  before the first threshold, and the server refuses depth-1 re-verifies
  afterward (Beaver-triple reuse under a fresh challenge would leak).
- ``f_max``: padded-frontier capacity (static device shapes).
- ``crawl_shard_nodes``: split each level's crawl into node-axis shards of
  this many frontier slots, one RPC verb per shard — a mid-level fault
  then re-runs only the lost shards (protocol/leader_rpc.py shard retry).
  0 (default) keeps one verb per level.
- ``crawl_pipeline_depth``: how many shard verbs the leader keeps in
  flight at once on a sharded level (protocol/leader_rpc.py pipelined
  crawl): span k's GC/OT network phase overlaps span k+1's device
  expand.  1 (default) is the sequential PR-4 path; values > 1 require
  ``crawl_shard_nodes`` > 0 to have any effect.  Results are
  bit-identical either way; on any in-flight fault the pipeline
  quiesces and falls back to the sequential per-span retry.
"""

from __future__ import annotations

import argparse
import dataclasses
import json


@dataclasses.dataclass
class Config:
    data_len: int
    n_dims: int
    ball_size: int
    addkey_batch_size: int
    num_sites: int
    threshold: float
    zipf_exponent: float
    server0: str
    server1: str
    distribution: str
    sketch_batch_size: int = 100_000
    sketch_batch_size_last: int = 25_000
    backend: str = "tpu"
    secure_exchange: bool = False
    malicious: bool = False
    f_max: int = 1024  # padded-frontier capacity (static shapes on device)
    # mid-level retry granularity: frontier-node span per crawl shard
    # (each shard is its own RPC verb — a mid-level fault re-runs only
    # the lost shards, protocol/leader_rpc.py).  0 disables sharding.
    crawl_shard_nodes: int = 0
    # sharded-crawl pipelining: shard verbs the leader keeps in flight
    # (1 = sequential; >1 overlaps span k's plane I/O with span k+1's
    # device expand — protocol/leader_rpc.py pipelined crawl)
    crawl_pipeline_depth: int = 1
    # radix-2^k level fusion (protocol/collect.py): crawl k prefix bits
    # per wire round trip — each fused level expands every frontier node
    # by all 2^(k·n_dims) child patterns, runs ONE equality stage at the
    # fused string width S' = k·2·n_dims, and prunes on the depth-(base+k)
    # counts (bit-identical to k sequential levels: a fused child
    # survives iff its count clears threshold, and monotone counts make
    # the intermediate-depth prunes subsumed).  1 = today's crawl,
    # bit-identical compiled programs; 2 and 3 cut round trips, per-level
    # telemetry, and checkpoint cadence by k.  Dim caps from the 32-bit
    # packed plane (collect.check_radix): k=2 ⇒ n_dims ≤ 2, k=3 ⇒ 1.
    # Both servers + leader must agree; checkpoints/exports stamp it.
    crawl_radix_bits: int = 1
    # equality-test engine (protocol/secure.ot_path): "auto" runs the
    # 1-of-2^S chosen-payload OT (no garbled circuit) whenever the
    # string width S = 2·n_dims fits secure.OT2S_MAX_S, the garbled
    # circuit beyond; "ot2s"/"gc" force one path (both servers derive
    # the path from this knob + S, so the wire format always agrees)
    ot_path: str = "auto"
    # secure crawls garble/evaluate each level as ONE whole-level device
    # program (ignoring crawl_shard_nodes for the GC/OT batch) — the
    # device-resident batching that closes the trusted/secure gap.  Set
    # False to restore node-sharded secure levels (mid-level retry at
    # span granularity, span pipelining) at the cost of fragmenting the
    # equality batch into host-sized chunks.
    secure_whole_level: bool = True
    # multi-chip collector servers (parallel/server_mesh.py): how many
    # LOCAL devices each CollectorServer shards the client axis over.
    # 0 = auto: every visible local device on an accelerator host, ONE on
    # a CPU host (the virtual host-platform devices exist for tests —
    # production CPU servers gain nothing from sharding a host backend;
    # tests/bench pass explicit counts under
    # XLA_FLAGS=--xla_force_host_platform_device_count=8).  1 pins the
    # single-device path; N > 1 requests exactly N (capped at the visible
    # device count, then at the largest divisor of the client batch).
    # Results are bit-identical at every setting: sharding is a physical
    # layout, the 2PC transcript never changes (asserted in tier-1).
    server_data_devices: int = 0
    # multi-chip SECURE KERNEL stage (parallel/kernel_shard.py): how many
    # of the server's data-mesh devices the whole-level 2PC kernels —
    # row-sharded IKNP extension, 1-of-2^S / GC equality, b2a — shard
    # over.  0 = auto: follow the mesh's data shards.  1 pins the
    # single-device kernel path (the packed share bits gather over ICI
    # before string extraction — the pre-PR-10 layout).  N > 1 caps the
    # kernel shards at N; the ACTIVE count per level is the largest
    # divisor of the level's planar block count (padded_tests(B)/8192)
    # that fits, so a small batch degrades to fewer shards — ultimately
    # to the gather path — instead of failing.  The wire is byte-
    # identical at every setting (asserted in tier-1).
    secure_kernel_shards: int = 0
    # malicious-secure SKETCH verify sharding (parallel/sketch_shard.py):
    # how many of the server's data-mesh devices the per-level check
    # batch (the three MAC/square checks per (client, dim)) shards over.
    # 0 = auto: follow the mesh's data shards.  1 pins the single fused
    # program; N > 1 caps at N.  The ACTIVE count is the largest divisor
    # of the client batch that fits, so a non-dividing batch degrades to
    # fewer shards instead of failing.  The challenge stream, both wire
    # messages, and the verdict vector are bit-identical at every
    # setting (asserted in tier-1 and gated in bench_sketch).
    sketch_shards: int = 0
    # per-level secure-kernel phase split (phase_otext/garble/eval/b2a
    # spans in the run report): True syncs the device at each phase
    # boundary so the spans carry real device time — the acceptance
    # instrument for kernel work.  False skips the syncs (spans then
    # measure dispatch only); the phases are sequential data-dependent
    # steps, so the syncs cost only the dispatch-ahead slack.
    secure_phase_sync: bool = True
    # -- streaming ingest front door (protocol/rpc.py submit_keys,
    #    resilience/admission.py) -------------------------------------
    # hard per-window pool bound in KEYS — the "no unbounded queue"
    # invariant made a number; over it the shed policy below decides
    ingest_window_keys: int = 1 << 20
    # keys/sec token-bucket rate limit at the gate server (0 = off) and
    # its burst allowance; an over-rate submission gets a retryable
    # Overloaded verdict carrying the refill horizon
    ingest_rate_keys_per_s: float = 0.0
    ingest_burst_keys: int = 4096
    # per-client keys-per-window quota (0 = off): a flooding client hits
    # its quota and backs off; other clients' admissions are unaffected
    ingest_client_quota: int = 0
    # over-capacity behavior: "reject" answers Overloaded (the client
    # backs off and retries); "reservoir" keeps the pool a seeded
    # uniform sample of everything offered (native.Reservoir — seed-
    # reproducible, checkpoint-carried)
    ingest_shed: str = "reject"
    # seed for the reservoir shed sampler (per-window streams derive
    # from it deterministically)
    ingest_seed: int = 0
    # how many ingest windows a server keeps live at once (the sealed
    # window being crawled + the window(s) still accruing); bounds
    # server memory against a runaway window id
    ingest_windows_retained: int = 4
    # multi-tenant collection sessions (protocol/sessions.py): how many
    # per-collection sessions one server keeps live at once.  Each
    # session owns a full crawl state (frontier, keys, ingest pools,
    # OT endpoints), so the bound is a memory bound; at the cap an IDLE
    # session (nothing uploaded, no pools, not mid-verb) is evicted
    # oldest-first and a new collection is otherwise refused loudly.
    collection_sessions_max: int = 8
    # arm the fhh-race runtime sanitizer (utils/guards.py) on this
    # process's servers/drivers regardless of FHH_DEBUG_GUARDS — every
    # guarded-attribute access then asserts its owning lock is held by
    # the current task.  Debug/chaos-suite instrumentation, never a
    # production knob: attribute access gains a descriptor hop
    debug_guards: bool = False


def load_config(path: str) -> Config:
    with open(path) as f:
        raw = json.load(f)
    fields = {f.name for f in dataclasses.fields(Config)}
    unknown = set(raw) - fields
    if unknown:
        raise ValueError(f"Unknown config keys: {sorted(unknown)}")
    return Config(**raw)


def get_args(name: str, get_server_id: bool = False, get_n_reqs: bool = False):
    """CLI mirroring the reference's flags (ref: src/config.rs:55-111)."""
    p = argparse.ArgumentParser(prog=name, description="TPU-native private fuzzy heavy hitters.")
    p.add_argument("-c", "--config", required=True, help="Location of JSON config file")
    if get_server_id:
        p.add_argument("-i", "--server_id", type=int, required=True, help="Zero-indexed ID of server")
    if get_n_reqs:
        p.add_argument("-n", "--num_requests", type=int, required=True, help="Number of client requests")
    args = p.parse_args()
    cfg = load_config(args.config)
    server_id = getattr(args, "server_id", -1)
    n_reqs = getattr(args, "num_requests", 0)
    return cfg, server_id, n_reqs
