"""Runtime lock-discipline sanitizer: the dynamic half of fhh-race.

The static analyzer (:mod:`fuzzyheavyhitters_tpu.analysis.concurrency`)
proves the declared guard map — each shared attribute bound to the
asyncio lock that owns it — but its ``# fhh-race: holds=<lock>``
contracts on dynamically-dispatched verbs are *declarations* the AST
cannot verify.  This module verifies them at runtime, sanitizer-style:
under ``FHH_DEBUG_GUARDS=1`` every access to a guarded attribute asserts
that the owning lock is held *by the current task* at that moment, so
the existing tier-1 e2e + chaos suites exercise the contracts on every
verb, replay, recovery, and fault-injection path they already cover.

Off by default, zero overhead when off: :func:`install` is a no-op
unless enabled, leaving the instance's class — and therefore every
attribute access — untouched.  When enabled it swaps the instance onto
a dynamically-built subclass whose :class:`GuardedState` descriptors
wrap each guarded attribute, and wraps the named locks' ``acquire`` /
``release`` (instance-level, which ``async with`` reaches via the
mixin's ``self.acquire()``) to track the owning task.

Deliberately-unlocked windows — the event-loop-atomic ingest fast path,
the frame-arrival pre-expand — run inside :func:`unguarded`, whose
required ``reason`` is the runtime twin of the written justification on
the static suppression at the same site.

Scope: asyncio locks only.  The threading-locked module globals in
``obs/`` / ``native/`` are covered statically (inline ``# fhh-guard:``
annotations); their C-level locks admit no ownership hook.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import os

__all__ = [
    "GuardViolation",
    "GuardedState",
    "enabled",
    "install",
    "unguarded",
]

_ENV = "FHH_DEBUG_GUARDS"

# True once ANY instance armed in this process: the unguarded() windows
# on the hot dispatch path reduce to one global bool check until then
_armed = False

# depth of deliberately-unlocked windows for the current task/context
# (contextvars so one task's window never blesses a neighbour's access)
_unguarded_depth: contextvars.ContextVar[int] = contextvars.ContextVar(
    "fhh_unguarded_depth", default=0
)


class GuardViolation(AssertionError):
    """A guarded attribute was touched without its owning lock held by
    the current task.  Subclasses AssertionError so test suites that
    treat assertion failures as hard failures catch it without new
    plumbing."""


def enabled() -> bool:
    """True when the sanitizer is switched on (``FHH_DEBUG_GUARDS=1``).
    Read per call — :func:`install` runs at server/driver construction,
    so a test flipping the env var before construction gets the mode it
    asked for."""
    return os.environ.get(_ENV, "") == "1"


# shared no-op window for the disarmed path: nullcontext is stateless,
# reusable, and reentrant, so every call returns THIS instance — the
# verb-dispatch hot path pays one function call + bool check, never a
# generator-context-manager allocation
_NOOP = contextlib.nullcontext()


class _UnguardedWindow:
    """Armed-path window: bumps the per-task unguarded depth for the
    body.  A fresh instance per entry (the token is per-with)."""

    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _unguarded_depth.set(_unguarded_depth.get() + 1)
        return None

    def __exit__(self, *exc):
        _unguarded_depth.reset(self._token)
        return False


def unguarded(reason: str):
    """Suspend guard assertions for the current task while the body
    runs.  ``reason`` is mandatory and non-empty: every runtime window
    mirrors a written justification on the static suppression at the
    same site (grep for the reason text to find its twin)."""
    if not reason or not reason.strip():
        raise ValueError("unguarded() requires a written reason")
    if not _armed:  # sanitizer never armed: stay off the contextvar
        return _NOOP
    return _UnguardedWindow()


def _current_task():
    try:
        return asyncio.current_task()
    except RuntimeError:  # no running loop (sync caller, worker thread)
        return None


def _track_ownership(lock) -> None:
    """Wrap ``lock.acquire``/``lock.release`` (idempotently) so the lock
    remembers which task holds it.  Instance-level wrapping suffices:
    asyncio's ``_ContextManagerMixin.__aenter__`` calls ``self.acquire()``,
    an instance lookup."""
    if getattr(lock, "_fhh_tracked", False):
        return
    orig_acquire, orig_release = lock.acquire, lock.release

    async def acquire(*a, **kw):
        ok = await orig_acquire(*a, **kw)
        lock._fhh_owner = _current_task()
        return ok

    def release(*a, **kw):
        lock._fhh_owner = None
        return orig_release(*a, **kw)

    lock.acquire = acquire
    lock.release = release
    lock._fhh_owner = None
    lock._fhh_tracked = True


class GuardedState:
    """Descriptor asserting lock ownership on every get/set/delete of
    one guarded attribute.  The value itself lives in the instance
    ``__dict__`` under a shadow key, so installing the descriptor
    preserves the already-constructed state."""

    __slots__ = ("name", "lock_name", "_shadow")

    def __init__(self, name: str, lock_name: str):
        self.name = name
        self.lock_name = lock_name
        self._shadow = f"_fhh_guarded__{name}"

    def _check(self, obj) -> None:
        if _unguarded_depth.get():
            return
        lock = obj.__dict__.get(self.lock_name)
        if lock is None:
            lock = getattr(obj, self.lock_name, None)
        if lock is None:
            return  # lock not constructed yet: construction-time access
        if not lock.locked():
            raise GuardViolation(
                f"guarded attribute '{type(obj).__name__}.{self.name}' "
                f"accessed with its owning lock '{self.lock_name}' not "
                "held (fhh-race contract violation — hold the lock or "
                "open a guards.unguarded(reason) window)"
            )
        owner = getattr(lock, "_fhh_owner", None)
        task = _current_task()
        if owner is not None and task is not None and owner is not task:
            raise GuardViolation(
                f"guarded attribute '{type(obj).__name__}.{self.name}' "
                f"accessed while '{self.lock_name}' is held by ANOTHER "
                "task (fhh-race contract violation)"
            )

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj)
        try:
            return obj.__dict__[self._shadow]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value):
        self._check(obj)
        obj.__dict__[self._shadow] = value

    def __delete__(self, obj):
        self._check(obj)
        try:
            del obj.__dict__[self._shadow]
        except KeyError:
            raise AttributeError(self.name) from None


def install(obj, guard_map: dict, force: bool = False) -> bool:
    """Arm the sanitizer on one instance: when :func:`enabled` (or
    ``force=True`` — the ``Config.debug_guards`` knob), swap ``obj``
    onto a per-call subclass carrying a :class:`GuardedState` per
    ``guard_map`` entry (``attr -> lock attribute``) and arm ownership
    tracking on each named lock.  Call at the END of construction —
    guarded attributes must already exist.  Returns whether the
    sanitizer was armed (False = disabled: ``obj`` is untouched,
    attribute access cost is unchanged)."""
    global _armed
    if (not enabled() and not force) or not guard_map:
        return False
    _armed = True
    cls = type(obj)
    if getattr(cls, "_fhh_guards_installed", False):
        return True  # already armed (re-entrant install)
    descriptors = {
        attr: GuardedState(attr, lock_name)
        for attr, lock_name in guard_map.items()
    }
    for attr, desc in descriptors.items():
        # move the live value under the shadow key the descriptor reads
        if attr in obj.__dict__:
            obj.__dict__[desc._shadow] = obj.__dict__.pop(attr)
    shadow_cls = type(
        f"Guarded{cls.__name__}",
        (cls,),
        {**descriptors, "_fhh_guards_installed": True},
    )
    obj.__class__ = shadow_cls
    for lock_name in set(guard_map.values()):
        lock = getattr(obj, lock_name, None)
        if lock is not None:
            _track_ownership(lock)
    return True
