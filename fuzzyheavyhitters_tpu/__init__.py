"""fuzzyheavyhitters_tpu — a TPU-native two-server private fuzzy heavy hitters framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the reference
``sks-codes/fuzzyheavyhitters`` system (two-server interval-bound DCF fuzzy
heavy hitters, IEEE S&P 2021 lineage).  Nothing here is a port: keys are
tensors, tree frontiers are tensors, the two collector servers are two devices
on a mesh axis, and the server<->server exchange is an XLA collective.

Layout (mirrors the reference's layer map, SURVEY.md §1):

- ``utils``     bit codecs, bitstring arithmetic, config    (ref: src/lib.rs, src/config.rs)
- ``ops``       PRG, prime fields, ibDCF + payload DPF,
                GC/base-OT/OT-extension primitives           (ref: src/prg.rs, src/fastfield.rs,
                                                             src/field.rs, src/ibDCF.rs,
                                                             src/equalitytest.rs)
- ``parallel``  device mesh + server/client-axis collectives (ref: src/bin/server.rs TCP mesh)
- ``protocol``  aggregation engine, secure data plane,
                sketch/MPC verification, leader/server RPC   (ref: src/collect.rs, src/rpc.rs,
                                                             src/sketch.rs, src/mpc.rs,
                                                             src/bin/*.rs)
- ``workloads`` zipf / rides / covid samplers + CSV output   (ref: src/sample_*.rs)

64-bit integer support is required for the fast 62-bit field (``ops.fields``);
we enable it here, before any JAX arrays are created.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
