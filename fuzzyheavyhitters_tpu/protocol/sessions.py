"""Per-collection session subsystem: the multi-tenant half of the server.

The reference protocol is embarrassingly parallel ACROSS collections —
independent trees, independent FSS keys, independent 2PC transcripts
(PAPER.md §0) — so one server pair can serve many collections at once
provided every piece of per-collection state is keyed instead of
global.  This module holds that keying:

- :class:`CollectionSession` — everything a single collection's crawl
  owns (frontier, keys, liveness, sketch ratchet/root, expand cache,
  ingest window pools + admission gate, checkpoint namespace, OT
  sessions, per-session verb lock).  The attributes that used to live
  directly on ``CollectorServer`` live here now; the fhh-race guard map
  binds them to the session's own ``_verb_lock``
  (``[tool.fhh-lint.guards]`` "CollectionSession.*" + the
  :data:`_SESSION_GUARDS` runtime twin).
- :class:`SessionTable` — the bounded keyed table of live sessions,
  selected on the wire by the ``collection`` field of the existing
  ``__hello__`` handshake (protocol/rpc.py).  A connection that never
  says hello (or says it without a collection) works on the DEFAULT
  session, so every single-tenant flow is unchanged.
- :class:`PlaneMux` — the server↔server data-plane socket demultiplexed
  into per-collection FIFO channels: every plane frame is
  ``(channel, payload)``, a single pump task routes frames into
  per-channel queues, and each session's exchanges ride its own channel
  — two collections' 2PC transcripts interleave on the wire without
  ever desynchronizing, because each receiver demuxes by key instead
  of assuming global FIFO order.

Checkpoint namespacing: the default session keeps the legacy
``fhh_server{id}_l{level}.npz`` names; any other collection writes
``fhh_server{id}_c{key}_l{level}.npz``, and every blob is stamped with
its collection key (``sess`` field) so a blob renamed across
namespaces refuses to restore (validate-before-mutate, PR-4 contract).
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as obsmod
from ..obs import devmem
from ..obs import metrics as obsmetrics
from ..ops import dpf, prg
from ..ops.fields import F255, FE62
from ..ops.ibdcf import IbDcfKeyBatch
from ..parallel import server_mesh as smesh
from ..resilience import admission as resadmission
from ..utils import guards, taint_guard
from ..utils.config import Config
from . import collect, mpc, sketch as sketchmod

DEFAULT_COLLECTION = "default"
# collection keys become checkpoint filename components and wire channel
# tags: keep them filesystem- and log-safe
_KEY_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

SHARED_MASK_SEED = b"XXX This is bog\x00"  # 16 B, ref: server.rs:331-332

# structure template for (de)serializing sketch key batches over the wire
_z = np.zeros(0)
_SKETCH_TREEDEF = sketchmod.SketchKeyBatch(
    key=dpf.DpfKeyBatch(_z, _z, _z, _z, _z, _z),
    mac_key=_z,
    mac_key2=_z,
    mac_key_last=_z,
    mac_key2_last=_z,
    triples=mpc.TripleBatch(_z, _z, _z),
    triples_last=mpc.TripleBatch(_z, _z, _z),
)


def _mask_words(level: int, n: int, blocks_for: int) -> np.ndarray:
    """Shared pseudorandom mask words for one level (both servers derive the
    same stream, so shares cancel on reconstruction).  Host NumPy on
    purpose: the mask is tiny (F·2^d elements) and the device version
    would cost a device->host round trip per level per server — a full
    tunnel RTT on remote-chip deployments."""
    seed = prg.seeds_from_bytes(SHARED_MASK_SEED)[0].copy()
    seed[3] ^= np.uint32(level)
    return prg.np_stream_words(seed, n * blocks_for).reshape(n, blocks_for)


def mask_fe62(level: int, n: int) -> np.ndarray:
    # host twin of FE62.sample (see protocol/rpc.py history): the device
    # version cost one tunnel RTT per level for microseconds of NumPy
    return FE62.np_sample(_mask_words(level, n, 4))


def mask_f255(level: int, n: int) -> np.ndarray:
    return F255.np_sample(_mask_words(level, n, 8))


class _WindowPool:
    """One ingest window's append-only key pool (the streaming front
    door's unit of work: protocol verbs ``submit_keys`` → ``window_seal``
    → ``window_load``).

    ``entries`` holds admitted submissions (tuples of key arrays, the
    same chunk shape ``add_keys`` receives) in arrival order; once the
    reservoir shed policy engages, the list freezes into a SLOT TABLE
    and replacements overwrite in place.  ``verdicts`` records every
    FINAL outcome by ``sub_id`` so at-least-once delivery (reconnect
    replays, recovery journal replays) answers the recorded verdict
    instead of double-admitting or re-advancing the sampler's RNG.
    Overloaded rejections are deliberately NOT recorded — a backed-off
    retry is a fresh attempt against refilled tokens."""

    __slots__ = (
        "window", "wa", "entries", "verdicts", "keys",
        "admitted_keys", "shed_keys", "rejected", "sealed", "sealed_at",
        "sk_root",
    )

    def __init__(self, window: int, wa: resadmission.WindowAdmission):
        self.window = int(window)
        self.wa = wa
        self.entries: list = []
        self.verdicts: dict = {}
        self.keys = 0
        self.admitted_keys = 0
        self.shed_keys = 0
        self.rejected = 0
        self.sealed = False
        # wall-clock seal instant: the start of this window's
        # seal-to-hitters SLO clock (observed at final_shares of the
        # crawl that loads the window — protocol/rpc.py)
        self.sealed_at: float | None = None
        # malicious mode: the window's committed sketch-challenge root
        # (uint32[4], sketch.window_root) — stamped at seal, carried by
        # the seal stats and the ingest checkpoint so a recovered
        # window's crawl replays the IDENTICAL challenge sequence
        self.sk_root: np.ndarray | None = None

    def apply(self, sub_id: str, chunk: tuple,
              v: resadmission.Verdict) -> dict:
        """Commit one gate verdict to the pool; returns the wire
        response (the mirror server replays it via :meth:`apply_mirror`)."""
        n_keys = int(chunk[0].shape[0])
        if not v.admitted and v.scope is not None:
            self.rejected += 1
            return {
                "admitted": False, "overloaded": True, "scope": v.scope,
                "retry_after_s": round(float(v.retry_after_s), 4),
                "window": self.window,
            }
        if not v.admitted:  # reservoir shed this submission
            resp = {"admitted": False, "shed": True, "window": self.window}
            self.verdicts[sub_id] = resp
            self.shed_keys += n_keys
            return resp
        if v.slot is None:
            self.entries.append(chunk)
            self.keys += n_keys
        else:
            old = self.entries[v.slot]
            old_n = int(old[0].shape[0])
            self.entries[v.slot] = chunk
            self.keys += n_keys - old_n
            self.shed_keys += old_n
            # keep the admission ledger's occupancy honest under
            # variable-size chunks
            self.wa.keys += n_keys - old_n
        self.admitted_keys += n_keys
        resp = {"admitted": True, "slot": v.slot, "window": self.window}
        self.verdicts[sub_id] = resp
        return resp

    def apply_mirror(self, sub_id: str, chunk: tuple, mirror: dict,
                     client_id: str | None = None) -> dict:
        """Replay the GATE server's verdict on the peer pool so both
        servers' windows stay positionally identical.  Validates loudly —
        a mirror that cannot apply means the two pools diverged, which
        must never be papered over."""
        n_keys = int(chunk[0].shape[0])
        slot = mirror.get("slot")
        if self.wa.shed == resadmission.SHED_RESERVOIR:
            if self.wa.sub_keys is None:
                self.wa.sub_keys = n_keys  # uniform-chunk contract holds
            if mirror.get("shed") or slot is not None:
                # a restored GATE being rebuilt by the recovery journal:
                # the replayed verdict consumed one sampler draw in its
                # first life — advance the restored stream past it (the
                # verdict itself is applied verbatim below), so
                # post-recovery live admissions continue the SAME
                # seed-reproducible sequence.  When the reservoir
                # engaged only AFTER the last checkpoint, there is no
                # sampler to advance yet: bank the draw so the eventual
                # engagement fast-forwards past it.  A mirror server
                # never re-engages a reservoir, so this is harmless
                # bookkeeping outside recovery.
                if self.wa.reservoir is not None:
                    self.wa.reservoir.offer(1)
                else:
                    self.wa.pending_draws += 1
        if mirror.get("shed"):
            resp = {"admitted": False, "shed": True, "window": self.window}
            self.verdicts[sub_id] = resp
            self.shed_keys += n_keys
            return resp
        if slot is None:
            if self.keys + n_keys > self.wa.max_keys:
                raise RuntimeError(
                    f"ingest mirror overflows window {self.window}: "
                    f"{self.keys} + {n_keys} > {self.wa.max_keys} "
                    "(gate/mirror pools diverged)"
                )
            self.entries.append(chunk)
            self.keys += n_keys
            # keep the admission ledger in lockstep: a recovery journal
            # replay rebuilds a restarted GATE through this path, and its
            # later live decisions must see the true occupancy
            self.wa.subs += 1
            self.wa.keys += n_keys
            self.wa._charge(client_id, n_keys)
        else:
            slot = int(slot)
            if not 0 <= slot < len(self.entries):
                raise RuntimeError(
                    f"ingest mirror names slot {slot} of a "
                    f"{len(self.entries)}-slot window {self.window} pool "
                    "(gate/mirror pools diverged)"
                )
            old_n = int(self.entries[slot][0].shape[0])
            self.entries[slot] = chunk
            self.keys += n_keys - old_n
            self.shed_keys += old_n
            self.wa.keys += n_keys - old_n
            self.wa._charge(client_id, n_keys)
        self.admitted_keys += n_keys
        resp = {"admitted": True, "slot": slot, "window": self.window}
        self.verdicts[sub_id] = resp
        return resp

    def stats(self) -> dict:
        out = {
            "window": self.window,
            "sealed": self.sealed,
            "keys": self.keys,
            "subs": len(self.entries),
            "admitted_keys": self.admitted_keys,
            "shed_keys": self.shed_keys,
            "rejected": self.rejected,
        }
        if self.sk_root is not None:
            # plain ints: the driver banks these stats and replays them
            # into a recovery re-seal (dict equality in tests must stay
            # unambiguous, so no ndarray values here)
            out["sk_root"] = [int(x) for x in self.sk_root]
        return out


# Runtime twin of the fhh-race guard map — the "CollectionSession.*"
# entries of pyproject [tool.fhh-lint.guards], attr -> owning asyncio
# lock (drift-tested against the pyproject table in
# tests/test_concurrency.py).  Under FHH_DEBUG_GUARDS=1 (or
# Config.debug_guards) utils/guards.py arms a GuardedState descriptor
# per entry ON EVERY SESSION INSTANCE, so every access asserts the
# session's OWN verb lock is held by the current task — the per-tenant
# twin of the old server-global discipline, declared BEFORE the
# multi-tenant refactor multiplied the interleaving space (PR-9 ground
# rule).
_SESSION_GUARDS = {
    "frontier": "_verb_lock",
    "keys": "_verb_lock",
    "keys_parts": "_verb_lock",
    "alive_keys": "_verb_lock",
    "_children": "_verb_lock",
    "_last_shares": "_verb_lock",
    "_shard_children": "_verb_lock",
    "_shard_last": "_verb_lock",
    "_expand_ready": "_verb_lock",
    "_ingest_pools": "_verb_lock",
    "_admission": "_verb_lock",
    "_sketch_parts": "_verb_lock",
    "_sketch_root": "_verb_lock",
    "_ratchet_digest": "_verb_lock",
    "_window_sketch_root": "_verb_lock",
    "_export_epoch": "_verb_lock",
    "_import_seen": "_verb_lock",
    "_radix": "_verb_lock",
}


class CollectionSession:
    """One collection's complete server-side state (see module doc).

    Everything here used to be a ``CollectorServer`` attribute; the
    crawl verbs (protocol/rpc.py) now receive the session resolved from
    the connection's ``__hello__`` and serialize on ``self._verb_lock``
    — per session, so two collections' verbs interleave on the event
    loop while each collection's own verbs stay strictly ordered."""

    def __init__(self, key: str, server_id: int, cfg: Config,
                 obs: obsmetrics.Registry, ckpt_dir: str | None):
        self.key = key
        self.server_id = server_id
        self.cfg = cfg
        self.obs = obs
        self.ckpt_dir = ckpt_dir
        self.last_used = time.monotonic()
        # heartbeat-gap instrument: when did this session last COMPLETE
        # a verb (last_used marks arrival — a wedged verb advances
        # last_used forever while last_progress stalls, which is exactly
        # the signal status.sessions.per_session.last_progress_s carries)
        self.last_progress = time.monotonic()
        # control connections currently bound to this session via
        # __hello__ (protocol/rpc.py increments at bind, decrements when
        # the connection closes): a session with live bindings is NEVER
        # idle-evicted, even when it holds no state yet — evicting it
        # would orphan the bound leader (its uploads would land in an
        # object the table no longer serves) and let a same-key
        # successor share its PlaneMux channel
        self.bound = 0
        # data-plane session state: which plane epoch this session's
        # channel handshake (coin flip + base-OT) ran against; 0 = never
        self.plane_epoch = 0
        # -- crawl state ---------------------------------------------------
        # radix-2^k level fusion (Config.crawl_radix_bits): bit levels
        # fused per crawl verb.  Fixed per session at construction —
        # leader and both servers derive it from the same config knob,
        # and checkpoint/export blobs stamp it so a restore under a
        # different radix refuses (validate-before-mutate)
        collect.check_radix(cfg.n_dims, cfg.crawl_radix_bits)
        self._radix: int = int(cfg.crawl_radix_bits)
        self.keys_parts: list = []
        self.keys: IbDcfKeyBatch | None = None
        self.alive_keys: np.ndarray | None = None
        self.frontier: collect.Frontier | None = None
        self._children: object | None = None
        self._last_shares: np.ndarray | None = None
        self._shard_children: dict = {}
        self._shard_last: dict = {}
        self._shard_level: int | None = None
        self._mask_cache: tuple | None = None
        self._expand_ready: dict = {}
        # -- secure plane (per-session IKNP/base-OT endpoints) -------------
        self._ot: object | None = None
        self._ot_snd: object | None = None
        self._ot_rcv: object | None = None
        self._sec_seed: np.ndarray | None = None
        self._crawl_ctr: int = 0
        # -- sketch (malicious-secure) state -------------------------------
        self._sketch_parts: list = []
        self._sketch: object | None = None
        self._sketch_states: object | None = None
        self._sketch_pids: np.ndarray | None = None
        self._sketch_depth: int = 0
        # stored value-pair shares awaiting the next sketch_verify: a
        # list of (pairs, depth, field) — one entry per bit level of the
        # latest fused prune (a single entry at crawl_radix_bits=1)
        self._sketch_pairs: list | None = None
        self._sketch_seed: np.ndarray | None = None
        self._sketch_root: np.ndarray | None = None
        self._ratchet_digest: bytes | None = None
        # streaming malicious mode: the LOADED window's committed
        # challenge root (sketch.window_root, installed by window_load
        # from the sealed pool) — tree_init commits it as the ratchet
        # root instead of the raw session coin flip, so a recovered
        # window replays the identical challenge; None = batch flow
        self._window_sketch_root: np.ndarray | None = None
        # -- streaming ingest: PER-SESSION gate + pools --------------------
        # each collection gets its own admission controller (token
        # bucket, quotas, reservoir seed), so a flooding tenant exhausts
        # its own bucket and cannot starve another collection's window
        self._ingest_pools: dict = {}
        # seal instant of the window the CURRENT crawl loaded (None =
        # batch upload): final_shares observes seal-to-hitters from it
        self._window_seal_ts: float | None = None
        self._admission = resadmission.AdmissionController(
            max_window_keys=cfg.ingest_window_keys,
            rate_keys_per_s=cfg.ingest_rate_keys_per_s,
            burst_keys=cfg.ingest_burst_keys,
            client_quota=cfg.ingest_client_quota,
            shed=cfg.ingest_shed,
            seed=cfg.ingest_seed,
        )
        # admission gate: submit_keys runs the token-bucket arithmetic
        # in an executor behind this per-session lock, so a flooding
        # tenant's admission math never stalls the shared event loop
        # while the bucket still mutates strictly serialized per session
        self._adm_gate = asyncio.Lock()
        # -- fleet migration bookkeeping (protocol/fleet.py) ---------------
        # session_export stamps each blob with (boot id, export epoch);
        # session_import refuses a replayed stamp — double-importing one
        # export would double-land its in-flight sub_ids
        self._export_epoch = 0
        self._import_seen: set = set()
        # -- multi-chip mesh: per-session binding over the shared devices --
        # (ServerMesh.bind pins shard count to the client batch, which is
        # per-collection state; the underlying Mesh + jitted reduction
        # kernels are lru-cached at module level, so sessions share every
        # compiled program)
        k = smesh.resolve_data_devices(cfg.server_data_devices)
        self._mesh = smesh.ServerMesh(k) if k > 1 else None
        self._verb_lock = asyncio.Lock()
        # LAST: the sanitizer (a no-op unless FHH_DEBUG_GUARDS=1 or
        # cfg.debug_guards) wraps the already-constructed guarded state
        guards.install(self, _SESSION_GUARDS, force=cfg.debug_guards)

    # -- lifecycle --------------------------------------------------------

    def reset_state(self, reset_obs: bool = True) -> None:  # fhh-race: holds=_verb_lock (reached only from the reset verb, which _dispatch runs under this session's verb lock; sanitizer-validated)
        """The ``reset`` verb's body: a new collection run on this
        session opens clean (crawl state, sketch state, ingest pools,
        checkpoint namespace, telemetry).  ``reset_obs`` False skips the
        registry wipe — the DEFAULT session shares the SERVER's registry
        (single-tenant reports depend on that), so when other tenants
        are live its reset must not zero their shared-plane accounting
        (scheduler fills, dedup hits, control bytes)."""
        self.keys_parts.clear()
        self.keys = None
        self.alive_keys = None
        self.frontier = None
        self._children = None
        self._last_shares = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        self._sketch_parts.clear()
        self._sketch = None
        self._sketch_states = None
        self._sketch_pids = None
        self._sketch_depth = 0
        self._sketch_pairs = None
        self._sketch_root = None
        self._ratchet_digest = None
        self._window_sketch_root = None
        self._ingest_pools.clear()  # a new collection's front door opens clean
        self._window_seal_ts = None
        self.ckpt_clear()  # a new collection must not resume an old one's
        if reset_obs:  # fresh per-collection phase/byte/fetch accounting
            self.obs.reset()
        if self._ot is not None:  # fresh GC/b2a randomness per collection
            import secrets as _secrets

            self._sec_seed = np.frombuffer(
                _secrets.token_bytes(16), dtype="<u4"
            ).copy()
            taint_guard.register(
                "CollectionSession._sec_seed", self._sec_seed
            )

    def clear_crawl_state(self) -> None:  # fhh-race: holds=_verb_lock (reached only from window_load/tree_restore, which run under this session's verb lock; sanitizer-validated)
        """Drop the crawl-plane state while leaving ingest pools and
        checkpoints alone (``window_load``'s reset-to-fresh-batch).
        The per-window SKETCH material clears with it: each window
        carries its own client sketch keys and its own committed
        challenge root (``window_load`` re-seeds both right after)."""
        self.keys = None
        self.alive_keys = None
        self.frontier = None
        self._children = None
        self._last_shares = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        self._sketch_parts.clear()
        self._sketch = None
        self._sketch_states = None
        self._sketch_pids = None
        self._sketch_depth = 0
        self._sketch_pairs = None
        self._sketch_root = None
        self._ratchet_digest = None
        self._window_sketch_root = None

    def idle(self) -> bool:  # fhh-race: atomic (read-only probe from the serve-loop session bind; one event-loop slice)
        """True when nothing durable lives here (eviction candidate)."""
        return (
            self.bound == 0
            and self.keys is None
            and not self.keys_parts
            and self.frontier is None
            and not self._ingest_pools
            and not self._verb_lock.locked()
        )

    # -- engine/layout ----------------------------------------------------

    def planar(self) -> bool:  # fhh-race: atomic (pure read of init-time state: _mesh/_radix are set at session construction)
        """This session's frontier LAYOUT: the process expand engine,
        except under the multi-chip mesh, which pins interleaved/XLA
        (the client axis must be a plain named axis — pallas_call takes
        no sharded operands), and under radix > 1 fusion, whose
        multi-step expand is implemented on the interleaved/XLA engine
        only (collect.expand_share_bits_radix)."""
        return (collect._expand_engine() and self._mesh is None
                and self._radix == 1)

    def crawl_radix(self, level) -> int:  # fhh-race: atomic (pure read of init-time state)
        """Fused bit count of the crawl round based at bit ``level``:
        the session radix, clipped at the tail (data_len not divisible
        by k leaves a final partial round of ``L - level`` bits)."""
        L = self.keys.cw_seed.shape[-2]
        return min(self._radix, L - int(level))

    def concat_keys(self) -> None:  # fhh-race: holds=_verb_lock (reached only from tree_init/tree_restore/warmup under this session's verb lock; sanitizer-validated)
        """Materialize ``self.keys`` from the uploaded chunks (shared by
        ``tree_init`` and ``tree_restore``).  Under the multi-chip mesh
        the batch binds the active shard count and the key planes land
        client-axis-sharded across the local devices."""
        self.keys = IbDcfKeyBatch(
            *[
                # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (wire input: the uploaded chunks are host numpy already — np.asarray is a no-copy view; runs once per collection/restore, never per level)
                np.concatenate([np.asarray(p[i]) for p in self.keys_parts])
                for i in range(len(self.keys_parts[0]))
            ]
        )
        if self._mesh is not None:
            self._mesh.bind(self.keys.cw_seed.shape[0])
            self.keys = self._mesh.shard_keys(self.keys)
        # key-plane residency (obs.devmem): the flagship's "1.51 chips
        # of key storage" risk as a live per-collection gauge — set at
        # the one place the materialized plane changes size
        self.obs.gauge(
            "key_plane_bytes", devmem.tree_nbytes(tuple(self.keys))
        )

    def concat_sketch(self) -> None:  # fhh-race: holds=_verb_lock (reached only from tree_init/tree_restore under this session's verb lock; sanitizer-validated)
        """Materialize ``self._sketch`` from the uploaded chunks."""
        leaves = [jax.tree.leaves(p) for p in self._sketch_parts]
        # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (wire input: uploaded sketch chunks are host numpy; once per collection/restore)
        cat = [np.concatenate([np.asarray(p[i]) for p in leaves])
               for i in range(len(leaves[0]))]
        self._sketch = jax.tree.unflatten(
            jax.tree.structure(_SKETCH_TREEDEF), cat
        )

    def challenge_seed(self, level: int) -> np.ndarray:  # fhh-race: holds=_verb_lock (reached only from sketch_verify under this session's verb lock; sanitizer-validated)
        """This level's sketch challenge via the ratchet (sketch.py):
        hash(committed root ‖ level ‖ transcript digest).  Falls back to
        the raw session seed only when the ratchet was never committed
        (sketch keys without tree_init — a protocol error soon anyway)."""
        if self._sketch_root is None:
            return self._sketch_seed
        return sketchmod.ratchet_seed(
            self._sketch_root, level, self._ratchet_digest
        )

    # fhh-race: holds=_verb_lock (reached only from tree_prune/tree_prune_last under this session's verb lock; sanitizer-validated)
    def advance_sketch(self, level: int, parent: np.ndarray,
                       pat_bits: np.ndarray, n_alive: int) -> None:
        """Advance the frontier-following sketch DPF states with the same
        survivor table as the count frontier (one 1-D sketch tree per
        dimension; dim j's direction is pattern bit j), storing the new
        depth's value-pair shares gated by node liveness AND per-dim
        prefix DEDUPLICATION: in d > 1 the count frontier is a product —
        two frontier nodes routinely share the same dim-j prefix, and
        counting an honest one-hot entry twice makes ``<r,x>² != <r²,x>``
        (with r_i + r_j in place of a single r).  Each dim keeps only the
        FIRST slot of every distinct prefix; the dedup table derives from
        the public survivor table, so both servers gate identically.

        Radix fusion: ``pat_bits`` may carry a step axis ([F, r, d] —
        a fused prune at base bit level ``level``).  The sketch states
        advance r eval_bit steps and EVERY step's value-pair shares are
        stored (one ``_sketch_pairs`` entry per depth, each gated by its
        own depth's dedup table), so the next batched ``sketch_verify``
        opens every intermediate depth's Beaver slab exactly once and
        keeps k=1's detection guarantee — a payload forged at a depth
        the fused crawl never takes counts at is still caught, one
        fused level later than a sequential crawl would catch it."""
        L = self.keys.cw_seed.shape[-2]
        k = self._sketch.key  # batch [N, d]
        d = k.root_seed.shape[1]
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(parent)
        pat_bits = np.asarray(pat_bits)
        if pat_bits.ndim == 2:  # radix-1 callers: [F, d] -> [F, 1, d]
            pat_bits = pat_bits[:, None, :]
        r = pat_bits.shape[1]
        st = jax.tree.map(lambda a: a[parent], self._sketch_states)
        F2 = parent.shape[0]
        parent_pid = self._sketch_pids[parent[:n_alive]]  # [n_alive, d]
        pids = self._sketch_pids
        entries = []
        for t in range(r):
            bl = level + t  # absolute bit level of this step
            last = bl == L - 1
            fld = F255 if last else FE62
            direction = jnp.asarray(pat_bits[:, t, :], bool)[:, None, :]
            cw = tuple(a[None] for a in dpf.level_cw(k, bl))  # [1, N, d, ...]
            cwv = (k.cw_val[..., bl, :] if not last else k.cw_val_last)[None]
            st, pair = dpf.eval_bit(
                cw, st, direction, cwv, k.key_idx[None], fld, sketchmod.LANES
            )  # pair [F, N, d, LANES(, limbs)]
            # this depth's per-dim prefix dedup: key = parent pid chain +
            # the step bits THROUGH step t (two fused survivors sharing a
            # dim-j prefix at this depth are one node of dim j's 1-D
            # sketch tree and must be counted once, even if they diverge
            # at a later step)
            pids = np.zeros((F2, d), np.int32)
            keep = np.zeros((F2, d), bool)
            for j in range(d):
                key_j = np.stack(
                    [parent_pid[:, j]]
                    + [pat_bits[:n_alive, u, j].astype(np.int32)
                       for u in range(t + 1)],
                    1,
                )
                _, inv = np.unique(key_j, axis=0, return_inverse=True)
                pids[:n_alive, j] = inv
                _, first = np.unique(inv, return_index=True)
                keep[first, j] = True
            gate = jnp.asarray(
                keep.reshape((F2, 1, d) + (1,) * (pair.ndim - 3))
            )
            entries.append((jnp.where(gate, pair, 0), bl + 1, fld))
        self._sketch_states = st
        self._sketch_pids = pids  # final depth's table seeds the next prune
        self._sketch_depth = level + r
        self._sketch_pairs = entries

    # -- crawl span bookkeeping -------------------------------------------

    def shard_frontier_view(self, shard):  # fhh-race: atomic (pure slice of the frontier, never suspends; reached from the frame-arrival pre-expand)
        """The frontier view one crawl verb works on: the whole frontier
        (``shard`` None) or the node span ``[lo, hi)`` of it."""
        if shard is None:
            return self.frontier
        return collect.frontier_slice(
            self.frontier, shard[0], shard[1], planar=self.planar()
        )

    def stash_children(self, level, shard, children) -> None:  # fhh-race: holds=_verb_lock (reached only from the crawl verbs under this session's verb lock; sanitizer-validated)
        """Bank one crawl's child-state cache for the coming prune: whole
        level under ``_children``, shards keyed by span ``lo`` (a shard
        RE-RUN overwrites its slot — exactly the retry semantics)."""
        if shard is None:
            self._children = children
            return
        if self._shard_level != int(level):
            # first shard of a new level: drop any stale spans
            self._shard_children.clear()
            self._shard_last.clear()
            self._shard_level = int(level)
        self._children = None  # sharded levels assemble at prune time
        if children is not None:
            self._shard_children[int(shard[0])] = children

    def assemble_shard_children(self):  # fhh-race: holds=_verb_lock (reached only from tree_prune under this session's verb lock; sanitizer-validated)
        """Stitch the per-shard child caches back into one full-level
        cache; refuses a torn level (a missing span would silently
        advance garbage for its nodes)."""
        children = collect.children_cat(sorted(self._shard_children.items()))
        got = (
            children.seed.shape[4]
            if isinstance(children, collect.PlanarChildren)
            else children.seed.shape[0]
        )
        if got != self.frontier.f_bucket:
            raise RuntimeError(
                f"sharded crawl incomplete: child caches cover {got} of "
                f"{self.frontier.f_bucket} frontier slots"
            )
        self._shard_children.clear()
        return children

    def mask_rows(self, level: int, shard, C: int, f255: bool) -> np.ndarray:  # fhh-race: holds=_verb_lock (reached only from tree_crawl/_last under this session's verb lock; sanitizer-validated)
        """Wire-format mask rows for one (level, shard): the FULL-level
        stream sliced to the shard's node rows — the leader's uniform
        v0 - v1 reconstruction must be shard-oblivious, so a node's mask
        cannot depend on how the level was sharded.  One-entry cache."""
        F = self.frontier.f_bucket
        key = (level, F, f255)
        if self._mask_cache is None or self._mask_cache[0] != key:
            full = (
                mask_f255(level, F * C).reshape(F, C, 8)
                if f255
                else mask_fe62(level, F * C).reshape(F, C)
            )
            self._mask_cache = (key, full)
        full = self._mask_cache[1]
        return full if shard is None else full[shard[0] : shard[1]]

    def keys_fp(self) -> np.ndarray:  # fhh-race: holds=_verb_lock (reached only from tree_checkpoint/tree_restore under this session's verb lock; sanitizer-validated)
        """Cheap key identity for checkpoint/restore pairing: key_idx +
        root seeds.  An OPERATIONAL check (did the leader re-upload the
        same batch it crawled with), not a cryptographic one."""
        h = hashlib.sha256()
        # fhh-lint: disable=host-sync-in-hot-loop (checkpoint/restore identity check: once per checkpoint, not per level)
        h.update(np.ascontiguousarray(np.asarray(self.keys.key_idx)))
        # fhh-lint: disable=host-sync-in-hot-loop (as above)
        h.update(np.ascontiguousarray(np.asarray(self.keys.root_seed)))
        return np.frombuffer(h.digest(), np.uint8)

    # -- checkpoint namespace ---------------------------------------------

    def ckpt_prefix(self) -> str:
        """Per-collection checkpoint filename prefix.  The default
        session keeps the legacy name so single-tenant deployments (and
        their existing on-disk checkpoints) are untouched; every other
        collection gets its own namespace."""
        if self.key == DEFAULT_COLLECTION:
            return f"fhh_server{self.server_id}_l"
        return f"fhh_server{self.server_id}_c{self.key}_l"

    def ckpt_levels(self) -> list:
        """Level stamps of this session's on-disk checkpoints, ascending
        NUMERICALLY (the same ordering :meth:`ckpt_prune` keeps by)."""
        if self.ckpt_dir is None or not os.path.isdir(self.ckpt_dir):
            return []
        prefix = self.ckpt_prefix()
        levels = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith(prefix) and name.endswith(".npz"):
                try:
                    levels.append(int(name[len(prefix):-4]))
                except ValueError:
                    continue
        return sorted(levels)

    def ckpt_path(self, level: int) -> str:
        # level-stamped: a torn checkpoint round (one server wrote level k,
        # the other died first) must leave BOTH servers able to restore the
        # same earlier level
        return os.path.join(
            self.ckpt_dir, f"{self.ckpt_prefix()}{level}.npz"
        )

    def ckpt_prune(self, keep: int = 2) -> None:
        """Drop all but the newest ``keep`` checkpoint levels of THIS
        collection's namespace (other sessions' files are untouched)."""
        prefix = self.ckpt_prefix()
        found = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith(prefix) and name.endswith(".npz"):
                try:
                    found.append((int(name[len(prefix):-4]), name))
                except ValueError:
                    continue
        found.sort()
        # NB: found[:-keep] would be the EMPTY slice at keep=0 ([-0] == [0])
        doomed = found[: len(found) - keep] if keep else found
        for _, name in doomed:
            os.remove(os.path.join(self.ckpt_dir, name))

    def ckpt_clear(self) -> None:
        if self.ckpt_dir is not None and os.path.isdir(self.ckpt_dir):
            self.ckpt_prune(keep=0)

    # -- ingest pools (per-session gate) ----------------------------------

    def ingest_pool(self, window: int) -> _WindowPool:  # fhh-race: atomic (create-or-get + bounded eviction in one event-loop slice; called from the unlocked ingest fast path and from locked verbs)
        """Create-or-get the pool for ``window``; live-window count is
        BOUNDED (``cfg.ingest_windows_retained``) so a runaway window id
        can never grow server memory — the refusal is loud, never a
        silent drop."""
        pool = self._ingest_pools.get(window)
        if pool is None:
            if len(self._ingest_pools) >= max(
                1, self.cfg.ingest_windows_retained
            ):
                # sealed EMPTY windows are fully consumed (window_load
                # skips them, so only loads drop pools): evict the
                # oldest such before refusing — a quiet stretch of idle
                # windows must not wedge the front door
                idle = [
                    w for w in sorted(self._ingest_pools)
                    if self._ingest_pools[w].sealed
                    and not self._ingest_pools[w].entries
                ]
                if idle:
                    del self._ingest_pools[idle[0]]
            if len(self._ingest_pools) >= max(
                1, self.cfg.ingest_windows_retained
            ):
                raise RuntimeError(
                    f"ingest window {window} would exceed the "
                    f"{self.cfg.ingest_windows_retained} live-window bound "
                    f"(live: {sorted(self._ingest_pools)})"
                )
            pool = self._ingest_pools[window] = _WindowPool(
                window, self._admission.window(window)
            )
        return pool

    def ingest_status(self) -> dict:  # fhh-race: holds=_verb_lock (reached only from the status verb under this session's verb lock; sanitizer-validated)
        """Front-door health for ``status``: per-window occupancy, the
        unsealed-queue depth, and the admit/shed/reject counters."""
        pools = [self._ingest_pools[w] for w in sorted(self._ingest_pools)]
        unsealed = [p for p in pools if not p.sealed]
        return {
            "current_window": (
                unsealed[-1].window if unsealed
                else (pools[-1].window if pools else None)
            ),
            "queue_depth": sum(p.keys for p in unsealed),
            "admitted": sum(p.admitted_keys for p in pools),
            "shed": sum(p.shed_keys for p in pools),
            "rejected": sum(p.rejected for p in pools),
            "windows": {
                str(p.window): {
                    "keys": p.keys,
                    "subs": len(p.entries),
                    "sealed": p.sealed,
                }
                for p in pools
            },
        }

    # verdict codes in the checkpoint blob: slot >= 0, -1 = appended in
    # arrival order (no slot), -2 = reservoir-shed
    _ING_APPEND, _ING_SHED = -1, -2

    def ingest_ckpt_fields(self, blob: dict) -> None:  # fhh-race: holds=_verb_lock (reached only from tree_checkpoint under this session's verb lock; sanitizer-validated)
        """Flatten every live ingest pool into ``ing_*`` npz fields."""
        ws = sorted(self._ingest_pools)
        if not ws:
            return
        blob["ing_windows"] = np.asarray(ws, np.int64)
        for i, w in enumerate(ws):
            p = self._ingest_pools[w]
            blob[f"ing{i}_meta"] = np.array(
                [w, int(p.sealed), p.keys, p.admitted_keys, p.shed_keys,
                 p.rejected, len(p.entries), p.wa.subs, p.wa.keys,
                 -1 if p.wa.sub_keys is None else p.wa.sub_keys,
                 p.wa.pending_draws],
                np.int64,
            )
            sub_ids, codes = [], []
            for sid, resp in p.verdicts.items():
                sub_ids.append(sid)
                if resp.get("shed"):
                    codes.append(self._ING_SHED)
                elif resp.get("slot") is None:
                    codes.append(self._ING_APPEND)
                else:
                    codes.append(int(resp["slot"]))
            blob[f"ing{i}_sub_ids"] = np.array(sub_ids, dtype=str)
            blob[f"ing{i}_sub_codes"] = np.array(codes, np.int64)
            blob[f"ing{i}_lens"] = np.array(
                [int(e[0].shape[0]) for e in p.entries], np.int64
            )
            n_leaf = len(p.entries[0]) if p.entries else 0
            blob[f"ing{i}_nleaf"] = np.int64(n_leaf)
            for j in range(n_leaf):
                # entries are host arrays already (submit_keys converts)
                blob[f"ing{i}_leaf{j}"] = np.concatenate(
                    [e[j] for e in p.entries]
                )
            blob[f"ing{i}_clients"] = np.array(
                list(p.wa.client_keys.keys()), dtype=str
            )
            blob[f"ing{i}_client_keys"] = np.array(
                list(p.wa.client_keys.values()), np.int64
            )
            if p.wa.reservoir is not None:
                blob[f"ing{i}_res"] = p.wa.reservoir.state()
            if p.sealed_at is not None:
                # the seal instant rides the checkpoint so a recovered
                # window's seal-to-hitters SLO observation survives the
                # restart (the replayed seal verb is a no-op on an
                # already-sealed pool and must not restamp the clock)
                blob[f"ing{i}_sealed_at"] = np.float64(p.sealed_at)
            if p.sk_root is not None:
                # the window's committed sketch-challenge root: a
                # recovered malicious window MUST replay the identical
                # challenge (a restarted server's fresh plane coin flip
                # would otherwise re-root the ratchet and turn the
                # re-run's slab openings into a <r - r', x> leak)
                blob[f"ing{i}_skroot"] = np.array(p.sk_root, np.uint32)

    @staticmethod
    def ingest_validate(z: dict, path: str) -> list | None:
        """Validate-before-mutate for the ``ing_*`` fields: parse every
        window's record fully (shapes cross-checked) BEFORE any pool is
        touched; a torn tail refuses loudly with live state intact.
        Returns the parsed per-window records, or None when the blob
        carries no ingest fields (a pre-streaming checkpoint)."""
        if "ing_windows" not in z:
            return None
        parsed = []
        # fhh-lint: disable=host-sync-in-hot-loop (checkpoint blob: host npz entries)
        ws = np.asarray(z["ing_windows"], np.int64)  # checkpoint blob: host
        for i, w in enumerate(ws):
            req_keys = {f"ing{i}_meta", f"ing{i}_sub_ids", f"ing{i}_sub_codes",
                        f"ing{i}_lens", f"ing{i}_nleaf"}
            missing = req_keys - set(z)
            if missing:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is missing ingest "
                    f"fields {sorted(missing)} (truncated write?)"
                )
            meta = np.array(z[f"ing{i}_meta"], np.int64)
            if meta.shape != (11,) or int(meta[0]) != int(w):
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} has a malformed "
                    f"ingest meta row for window {int(w)}"
                )
            lens = np.array(z[f"ing{i}_lens"], np.int64)
            n_leaf = int(z[f"ing{i}_nleaf"])
            if lens.shape[0] != int(meta[6]):
                raise RuntimeError(
                    f"tree_restore: ingest window {int(w)} entry table is "
                    f"torn ({lens.shape[0]} lengths vs {int(meta[6])} slots)"
                )
            leaves = []
            for j in range(n_leaf):
                key = f"ing{i}_leaf{j}"
                if key not in z:
                    raise RuntimeError(
                        f"tree_restore: ingest window {int(w)} is missing "
                        f"leaf {j} (truncated write?)"
                    )
                leaf = z[key]  # npz entries are host ndarrays
                if leaf.shape[0] != int(lens.sum()):
                    raise RuntimeError(
                        f"tree_restore: ingest window {int(w)} leaf {j} "
                        f"covers {leaf.shape[0]} keys, lengths sum to "
                        f"{int(lens.sum())}"
                    )
                leaves.append(leaf)
            sub_ids = z[f"ing{i}_sub_ids"]
            codes = np.array(z[f"ing{i}_sub_codes"], np.int64)
            if sub_ids.shape[0] != codes.shape[0]:
                raise RuntimeError(
                    f"tree_restore: ingest window {int(w)} verdict table "
                    "is torn"
                )
            parsed.append({
                "meta": meta,
                "lens": lens,
                "leaves": leaves,
                "sub_ids": sub_ids,
                "codes": codes,
                "clients": np.array(z.get(f"ing{i}_clients", [])),
                "client_keys": np.array(
                    z.get(f"ing{i}_client_keys", []), np.int64
                ),
                "res": (
                    np.array(z[f"ing{i}_res"], np.uint64)
                    if f"ing{i}_res" in z
                    else None
                ),
                # optional (blobs from before the SLO clock omit it)
                "sealed_at": (
                    float(z[f"ing{i}_sealed_at"])
                    if f"ing{i}_sealed_at" in z
                    else None
                ),
                # optional (semi-honest windows / older blobs omit it)
                "sk_root": (
                    np.array(z[f"ing{i}_skroot"], np.uint32)
                    if f"ing{i}_skroot" in z
                    else None
                ),
            })
        return parsed

    def ingest_restore_apply(self, parsed: list) -> None:  # fhh-race: holds=_verb_lock (reached only from tree_restore under this session's verb lock; sanitizer-validated)
        """Rebuild the ingest pools from validated records (the mutation
        half of the restore contract)."""
        from ..native import Reservoir

        self._ingest_pools.clear()
        for rec in parsed:
            meta = rec["meta"]
            w = int(meta[0])
            wa = self._admission.window(w)
            pool = _WindowPool(w, wa)
            pool.sealed = bool(meta[1])
            pool.sealed_at = rec.get("sealed_at")
            pool.sk_root = rec.get("sk_root")
            pool.keys = int(meta[2])
            pool.admitted_keys = int(meta[3])
            pool.shed_keys = int(meta[4])
            pool.rejected = int(meta[5])
            wa.subs = int(meta[7])
            wa.keys = int(meta[8])
            wa.sub_keys = None if int(meta[9]) < 0 else int(meta[9])
            wa.pending_draws = int(meta[10])
            bounds = np.concatenate([[0], np.cumsum(rec["lens"])])
            pool.entries = [
                tuple(
                    leaf[bounds[e]:bounds[e + 1]] for leaf in rec["leaves"]
                )
                for e in range(len(rec["lens"]))
            ]
            for sid, code in zip(rec["sub_ids"], rec["codes"]):
                code = int(code)
                if code == self._ING_SHED:
                    resp = {"admitted": False, "shed": True, "window": w}
                elif code == self._ING_APPEND:
                    resp = {"admitted": True, "slot": None, "window": w}
                else:
                    resp = {"admitted": True, "slot": code, "window": w}
                pool.verdicts[str(sid)] = resp
            wa.client_keys = {
                str(c): int(n)
                for c, n in zip(rec["clients"], rec["client_keys"])
            }
            if rec["res"] is not None:
                wa.reservoir = Reservoir.from_state(rec["res"])
            self._ingest_pools[w] = pool


class SessionTable:
    """Bounded keyed table of :class:`CollectionSession`.

    ``get`` creates on first use; the table is bounded by
    ``cfg.collection_sessions_max`` — at the cap an IDLE session (no
    keys, no frontier, no ingest pools, not mid-verb) is evicted
    oldest-first, otherwise the new collection is refused loudly (a
    server must never silently drop a live tenant's state)."""

    def __init__(self, server_id: int, cfg: Config,
                 server_obs: obsmetrics.Registry, ckpt_dir: str | None):
        self.server_id = server_id
        self.cfg = cfg
        self.server_obs = server_obs
        self.ckpt_dir = ckpt_dir
        self._by_key: dict[str, CollectionSession] = {}

    def __len__(self) -> int:
        return len(self._by_key)

    def keys(self):
        return list(self._by_key)

    def items(self):
        return list(self._by_key.items())

    def peek(self, key: str) -> CollectionSession | None:
        return self._by_key.get(key)

    def default(self) -> CollectionSession:
        return self.get(DEFAULT_COLLECTION)

    def get(self, key: str | None = None) -> CollectionSession:  # fhh-race: atomic (serve-loop session table: create-or-get + eviction never suspends; all connections share one event loop)
        key = key or DEFAULT_COLLECTION
        if not _KEY_RE.match(key):
            raise ValueError(
                f"collection key {key!r} is invalid (want "
                "[A-Za-z0-9._-]{1,64}: it names checkpoint files and "
                "wire channels)"
            )
        cs = self._by_key.get(key)
        if cs is None:
            cap = max(1, self.cfg.collection_sessions_max)
            if len(self._by_key) >= cap:
                idle = sorted(
                    (k for k, s in self._by_key.items() if s.idle()),
                    key=lambda k: self._by_key[k].last_used,
                )
                if idle:
                    del self._by_key[idle[0]]
            if len(self._by_key) >= cap:
                raise RuntimeError(
                    f"collection {key!r} would exceed the "
                    f"{cap}-session bound and no live session is idle "
                    f"(live: {sorted(self._by_key)})"
                )
            reg = (
                self.server_obs
                if key == DEFAULT_COLLECTION
                else obsmetrics.Registry(f"server{self.server_id}:{key}")
            )
            cs = self._by_key[key] = CollectionSession(
                key, self.server_id, self.cfg, reg, self.ckpt_dir
            )
            if key != DEFAULT_COLLECTION:
                obsmod.emit(
                    "session.created",
                    server=self.server_id,
                    collection=key,
                )
        cs.last_used = time.monotonic()
        return cs


class _PlaneFailure:
    """Queue sentinel delivering a plane death to a blocked recv."""

    __slots__ = ("err",)

    def __init__(self, err: BaseException):
        self.err = err


class PlaneMux:
    """Per-collection demux of the single server↔server socket.

    Every data-plane frame is ``(channel, payload)``; one pump task per
    live transport routes payloads into per-channel FIFO queues.  Sends
    interleave freely (each frame is one atomic ``writer.write``), and
    each receiver reads only its own channel — so two collections' 2PC
    exchanges share the socket without any cross-tenant ordering
    assumptions.  ``epoch`` counts transports: a session whose channel
    handshake ran against an older epoch must re-key before trusting
    the plane again (protocol/rpc.py ``_ensure_session_plane``)."""

    # per-channel depth bound: the positional protocol keeps at most a
    # handful of frames in flight per collection (one exchange at a
    # time under the session's verb lock); hitting this bound means the
    # two servers' channel streams diverged — fail the plane loudly.
    MAX_DEPTH = 1024

    def __init__(self, route_count=None, tag: str = "plane"):
        self.epoch = 0
        self._queues: dict[str, asyncio.Queue] = {}
        self._err: BaseException | None = None
        self._pump_task: asyncio.Task | None = None
        # (chan, nbytes) byte-accounting hook, resolved by the server to
        # the owning session's registry
        self._route_count = route_count
        # trace component name for frame-arrival instants (fhh-trace):
        # the server passes "server{id}" so the merged timeline can draw
        # the peer's span -> this server's wire arrival
        self.tag = tag

    def attach(self, reader, read_frame) -> int:
        """Bind the mux to a fresh transport: fail every waiter of the
        old one (their frames can never arrive), reset channels, and
        start the new pump.  Returns the new epoch."""
        self.epoch += 1
        old, self._queues = self._queues, {}
        err = ConnectionError("data plane replaced by a new connection")
        for q in old.values():
            self._deliver_failure(q, err)
        self._err = None
        if self._pump_task is not None and not self._pump_task.done():
            self._pump_task.cancel()
        self._pump_task = asyncio.ensure_future(
            self._pump(reader, read_frame, self.epoch)
        )
        return self.epoch

    def close(self) -> None:
        if self._pump_task is not None and not self._pump_task.done():
            self._pump_task.cancel()
        self.fail(ConnectionError("data plane closed"))

    def fail(self, err: BaseException) -> None:
        """Fail every current and future recv with ``err`` (until the
        next :meth:`attach`)."""
        self._err = err
        for q in self._queues.values():
            self._deliver_failure(q, err)

    @staticmethod
    def _deliver_failure(q: asyncio.Queue, err: BaseException) -> None:
        try:
            q.put_nowait(_PlaneFailure(err))
        except asyncio.QueueFull:
            # drop one data frame to make room: the plane is dead, the
            # waiter must learn it either way
            try:
                q.get_nowait()
            except asyncio.QueueEmpty:
                pass
            q.put_nowait(_PlaneFailure(err))

    def _queue(self, chan: str) -> asyncio.Queue:
        q = self._queues.get(chan)
        if q is None:
            # fhh-lint: disable=unbounded-queue (bounded: MAX_DEPTH is a positive maxsize; overflow fails the plane loudly in _pump)
            q = self._queues[chan] = asyncio.Queue(maxsize=self.MAX_DEPTH)
        return q

    async def recv(self, chan: str):
        """Next payload on ``chan`` (FIFO per channel).  Raises the
        plane's death as ConnectionError — the same failure shape a
        direct socket read gave, so every existing recovery path
        (plane_reset, shard retry, supervisor rollback) works
        unchanged."""
        if self._err is not None:
            raise ConnectionError(
                f"data plane down: {self._err!r}"
            ) from self._err
        q = self._queue(chan)
        # fhh-lint: disable=unbounded-await (deliberately unbounded like the serve-loop reads: response waits are bounded at the caller — per-verb deadlines on the control plane, TCP keepalive on the data plane)
        item = await q.get()
        if isinstance(item, _PlaneFailure):
            # leave the failure visible to any later recv on this chan
            self._deliver_failure(q, item.err)
            raise ConnectionError(
                f"data plane down: {item.err!r}"
            ) from item.err
        payload, hdr = item
        if hdr is not None and obsmod.trace.enabled():
            # the peer stamped its (trace, span) onto the frame's
            # session header: an arrival instant parented under the
            # SENDER's span ties the two servers' timelines together
            obsmod.trace.instant(
                "plane_recv", comp=self.tag,
                trace_id=hdr[0], parent=hdr[1], chan=chan,
            )
        return payload

    async def _pump(self, reader, read_frame, epoch: int) -> None:
        """Route frames until the transport dies.  A pump outliving its
        epoch (superseded by attach) exits quietly — its queues were
        already failed and replaced."""
        try:
            while True:
                # fhh-lint: disable=unbounded-await (serve-loop read: waits indefinitely for the next frame by design; liveness comes from TCP keepalive on the peer socket)
                nbytes, frame = await read_frame(reader)
                if epoch != self.epoch:
                    return
                # frames are (collection, payload) — or, under fhh-trace,
                # (collection, payload, (trace_id, span_id)): the session
                # header grows the sender's trace context
                chan, payload = frame[0], frame[1]
                hdr = frame[2] if len(frame) > 2 else None
                if self._route_count is not None:
                    self._route_count(chan, nbytes)
                self._queue(chan).put_nowait((payload, hdr))
        except asyncio.CancelledError:
            raise
        # fhh-lint: disable=broad-except (transport boundary: EVERY pump failure — EOF, reset, a QueueFull divergence, a corrupt frame — must surface to the blocked receivers as a plane death)
        except Exception as e:
            if epoch == self.epoch:
                self.fail(e)
