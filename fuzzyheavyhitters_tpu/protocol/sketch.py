"""Malicious-secure sketch: MAC'd payload DPFs + sketch inner products.

Resurrection of the reference's commented-out sketch layer (ref:
src/sketch.rs:8-245) as a live TPU component, per the north star.  A
client encodes its contribution as a payload DPF whose per-level value is
the pair ``(x, k·x)`` for a per-client MAC key ``k`` (sketch.rs:8-24,
79-130); each server holds additive shares of the one-hot value vector
over the tree level.  The servers then compute three sketch inner
products against a SHARED random vector r (their common seed plays the
role of the reference's shared rand stream, sketch.rs:157-199):

    r_x  = <r, x>,   r2_x = <r², x>,   r_kx = <r, k·x>

— pure batched dot products over (clients × nodes), the MXU-friendly hot
path — and verify with Beaver triples (protocol/mpc.py) that

    1.  <r,x>² − <r²,x>        = 0     (one-hot / 0-1 vector check)
    2.  k·k − k²               = 0     (MAC-key share consistency)
    3.  k·<r,x> − <r,kx>       = 0     (MAC check: no additive attack)

(the MulState recipe of mpc.rs:103-141).  Failures flip the per-client
``alive_keys`` liveness flag that already gates every count
(collect.rs:32, 495 — the hook upstream left for exactly this).

Scope note, stated honestly: in the reference's *ancestor* the payload
DPF was also the counting path, so the sketch protected the counts
directly; the reference replaced that path with the GC+OT equality
protocol and left the sketch dead.  Here the sketch runs as the
malicious-security scaffold alongside the ibDCF path — same protocol,
same checks, same liveness gate — over the 1-D string workloads the
upstream sketch covered (a one-hot vector check does not extend to fuzzy
L∞ balls, which contain many nodes per level).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dpf, prg
from ..ops.dpf import DpfEvalState, DpfKeyBatch
from . import mpc

LANES = 2  # payload lanes: (x, k·x)


class SketchKeyBatch(NamedTuple):
    """One party's sketch keys for N clients (ref: sketch.rs:14-24)."""

    key: DpfKeyBatch
    mac_key: jax.Array  # field_t share [N]
    mac_key2: jax.Array  # field_t share of k² [N]
    mac_key_last: jax.Array  # field_u share [N(, limbs)]
    mac_key2_last: jax.Array
    triples: mpc.TripleBatch  # field_t [N, L-1, CHECKS]
    triples_last: mpc.TripleBatch  # field_u [N, CHECKS(, limbs)]


class SketchOutput(NamedTuple):
    """Per-client sketch inner products + shared linear-combination
    coefficients (ref: sketch.rs:26-43)."""

    r_x: jax.Array
    r2_x: jax.Array
    r_kx: jax.Array
    rand1: jax.Array
    rand2: jax.Array
    rand3: jax.Array


def gen(init_seeds, alpha_bits, field_t, field_u, seed) -> tuple[SketchKeyBatch, SketchKeyBatch]:
    """Client-side keygen (ref: sketch.rs:79-149 ``gen``/``gen_from_str``):
    unit payloads (x = 1 at the client's prefix) MAC'd with fresh per-client
    keys; triples for every level's checks ride along.

    init_seeds: uint32[N, 2, 4]; alpha_bits: bool[N, L]; seed: uint32[4]
    client-side randomness (MAC keys, shares, triples).
    """
    alpha_bits = np.asarray(alpha_bits, bool)
    N, L = alpha_bits.shape
    wt = 4
    wu = 8 if field_u.limb_shape else 4
    seed = jnp.asarray(seed, jnp.uint32)

    def sub_seed(tag):
        return seed ^ jnp.asarray([0, 0, 0, tag], jnp.uint32)

    # MAC keys + shares
    k = field_t.sample(prg.stream_words(sub_seed(1), N * wt).reshape(N, wt))
    k2 = field_t.mul(k, k)
    k_last = field_u.sample(prg.stream_words(sub_seed(2), N * wu).reshape(N, wu))
    k2_last = field_u.mul(k_last, k_last)

    def share(field, v, tag):
        w = 8 if field.limb_shape else 4
        n = int(np.prod(v.shape[: v.ndim - len(field.limb_shape)]))
        s0 = field.sample(prg.stream_words(sub_seed(tag), n * w).reshape(v.shape[: v.ndim - len(field.limb_shape)] + (w,)))
        return s0, field.sub(v, s0)

    k_s = share(field_t, k, 3)
    k2_s = share(field_t, k2, 4)
    kl_s = share(field_u, k_last, 5)
    k2l_s = share(field_u, k2_last, 6)

    # payload values: inner levels (1, k) in T; last level (1, k_last) in U
    one_t = jnp.broadcast_to(field_t.from_int(1), (N,))
    vals = jnp.stack([one_t, k], axis=-1)[:, None, :]  # [N, 1, 2]
    vals = jnp.broadcast_to(vals, (N, L - 1, LANES))
    one_u = jnp.broadcast_to(
        field_u.from_int(1), (N,) + field_u.limb_shape
    )
    vals_last = jnp.stack([one_u, k_last], axis=1)  # [N, LANES(, limbs)]

    dk0, dk1 = dpf.gen_pair(
        init_seeds, alpha_bits, vals, vals_last, field_t, field_u, LANES
    )

    t0, t1 = mpc.gen_triples(field_t, (N, L - 1, mpc.CHECKS), sub_seed(7))
    tl0, tl1 = mpc.gen_triples(field_u, (N, mpc.CHECKS), sub_seed(8))

    def mk(p, dk, trip, trip_last):
        return SketchKeyBatch(
            key=dk,
            mac_key=k_s[p],
            mac_key2=k2_s[p],
            mac_key_last=kl_s[p],
            mac_key2_last=k2l_s[p],
            triples=trip,
            triples_last=trip_last,
        )

    return mk(0, dk0, t0, tl0), mk(1, dk1, t1, tl1)


def shared_r_stream(field, shared_seed, level: int, m: int, n_clients: int):
    """The servers' common sketch randomness for one level: per-node r_j
    (and r_j²) plus per-client rand1..3 — both servers derive identical
    values from the shared seed (the reference's shared rand_stream,
    sketch.rs:164-168, seeded like server.rs:331-332)."""
    w = 8 if field.limb_shape else 4
    s = jnp.asarray(shared_seed, jnp.uint32) ^ jnp.asarray(
        [0, 0, 0x5E71C, level], jnp.uint32
    )
    words = prg.stream_words(s, (m + 3 * n_clients) * w)
    r = field.sample(words[: m * w].reshape((m, w)))
    rands = field.sample(
        words[m * w :].reshape((n_clients, 3, w))
    )
    return r, rands


@partial(jax.jit, static_argnames=("field",))
def sketch_output(field, pair_shares, r, rands) -> SketchOutput:
    """Batched sketch inner products (ref: sketch.rs:157-199 sketch_at).

    pair_shares: field[N, M, LANES(, limbs)] — this server's value-pair
    shares over the M tree nodes of the level; r: field[M(, limbs)] shared
    random vector; rands: field[N, 3(, limbs)].
    """
    x = pair_shares[..., 0] if not field.limb_shape else pair_shares[..., 0, :]
    kx = pair_shares[..., 1] if not field.limb_shape else pair_shares[..., 1, :]
    r2 = field.mul(r, r)
    rb = r[None] if not field.limb_shape else r[None]
    r_x = field.sum(field.mul(x, rb), axis=1)
    r2_x = field.sum(field.mul(x, r2[None]), axis=1)
    r_kx = field.sum(field.mul(kx, rb), axis=1)
    g = lambda i: (rands[:, i] if not field.limb_shape else rands[:, i, :])
    return SketchOutput(
        r_x=r_x, r2_x=r2_x, r_kx=r_kx, rand1=g(0), rand2=g(1), rand3=g(2)
    )


@partial(jax.jit, static_argnames=("field",))
def mul_state(field, out: SketchOutput, mac_key, mac_key2, triples) -> mpc.MulStateBatch:
    """Assemble the three checks per client (ref: mpc.rs:83-141):
    (1) r_x*r_x - r2_x; (2) k*k - k²; (3) r_x*k - r_kx."""
    stack = lambda *vs: jnp.stack(vs, axis=1)
    xs = stack(out.r_x, mac_key, out.r_x)
    ys = stack(out.r_x, mac_key, mac_key)
    zs = stack(field.neg(out.r2_x), field.neg(mac_key2), field.neg(out.r_kx))
    rs = stack(out.rand1, out.rand2, out.rand3)
    return mpc.MulStateBatch(xs=xs, ys=ys, zs=zs, rs=rs, triples=triples)


def verify_batch(field, state0: mpc.MulStateBatch, state1: mpc.MulStateBatch):
    """In-process two-server verification: cor exchange + out exchange
    (the socketpair shape of the dead main.rs:14-72 ``verify_sketches``).
    Returns bool[N] — True where the client's sketch passes."""
    c0 = mpc.cor_share(field, state0)
    c1 = mpc.cor_share(field, state1)
    opened = mpc.cor(field, c0, c1)
    o0 = mpc.out_share(field, False, state0, opened)
    o1 = mpc.out_share(field, True, state1, opened)
    return np.asarray(mpc.verify(field, o0, o1))


# ---------------------------------------------------------------------------
# Server-side level evaluation over all prefixes of a level (the sketch's
# own frontier; chunked by sketch_batch_size over the client axis)
# ---------------------------------------------------------------------------


def eval_level_full(key: SketchKeyBatch, level: int, field_t, field_u, data_len: int):
    """Value-pair shares for ALL 2^(level+1) prefixes at ``level``.

    Walks a fixed ``2^data_len``-slot padded tree: slot i's direction at
    step j is bit ``data_len-1-j`` of i, so slots sharing a prefix hold
    identical (redundantly computed) states and EVERY level advances with
    the same ``[N, 2^data_len]`` program — one XLA compile per field for
    the whole walk instead of one per level width (the test suite is
    compile-bound; the redundancy is trivial at spec-helper scale).
    Exponential in ``data_len`` by construction: this enumerates all
    prefixes (a spec/test helper — the server path is the
    frontier-following sketch state, protocol/rpc.py).  Returns
    field[N, 2^(level+1), LANES(, limbs)]."""
    k = key.key
    N = k.root_seed.shape[0]
    L = data_len
    M = 1 << L
    st = jax.tree.map(
        lambda a: jnp.broadcast_to(a[:, None], (N, M) + a.shape[1:]),
        dpf.eval_init(k),
    )  # [N, M]
    slots = jnp.arange(M)
    shares = None
    for j in range(level + 1):
        cw = tuple(
            jax.tree.map(lambda a: a[:, None] if a.ndim > 1 else a, c)
            for c in dpf.level_cw(k, j)
        )
        field = field_t if j < data_len - 1 else field_u
        cwv = (k.cw_val[:, j] if j < data_len - 1 else k.cw_val_last)[:, None]
        dirs = jnp.broadcast_to(
            ((slots >> (L - 1 - j)) & 1).astype(bool)[None], (N, M)
        )
        st, shares = dpf.eval_bit(
            cw, st, dirs, cwv, k.key_idx[:, None], field, LANES
        )
    # representative slot of prefix p (level+1 bits): p << (L-1-level)
    idx = jnp.arange(1 << (level + 1)) << (L - 1 - level)
    return shares[:, idx]


def verify_level(
    sk0: SketchKeyBatch,
    sk1: SketchKeyBatch,
    level: int,
    field_t,
    field_u,
    data_len: int,
    shared_seed,
    sketch_batch_size: int = 100_000,
) -> np.ndarray:
    """Full two-server sketch verification at one level -> bool[N].

    Chunked over the client axis by ``sketch_batch_size`` (the config knob
    the reference ships but never parses, src/bin/config.json:9-10)."""
    last = level == data_len - 1
    field = field_u if last else field_t
    N = np.asarray(sk0.key.root_seed).shape[0]
    m = 1 << (level + 1)
    out = np.empty(N, bool)
    for lo in range(0, N, sketch_batch_size):
        sl = slice(lo, min(lo + sketch_batch_size, N))
        ks0 = jax.tree.map(lambda a: a[sl], sk0)
        ks1 = jax.tree.map(lambda a: a[sl], sk1)
        n_sl = np.asarray(ks0.key.root_seed).shape[0]
        # draw the r vector at the full-tree width and slice: the stream
        # program then has one shape for every level (and both servers
        # still derive identical values — same function, same args)
        r_full, rands = shared_r_stream(
            field, shared_seed, level, 1 << data_len, n_sl
        )
        r = r_full[:m]
        states = []
        for ks in (ks0, ks1):
            pairs = eval_level_full(ks, level, field_t, field_u, data_len)
            o = sketch_output(field, pairs, r, rands)
            if last:
                trip = jax.tree.map(lambda a: a, ks.triples_last)
                mk, mk2 = ks.mac_key_last, ks.mac_key2_last
            else:
                trip = jax.tree.map(lambda a: a[:, level], ks.triples)
                mk, mk2 = ks.mac_key, ks.mac_key2
            states.append(mul_state(field, o, mk, mk2, trip))
        out[sl] = verify_batch(field, states[0], states[1])
    return out
