"""Malicious-secure sketch: MAC'd payload DPFs + sketch inner products.

Resurrection of the reference's commented-out sketch layer (ref:
src/sketch.rs:8-245) as a live TPU component, per the north star.  A
client encodes its contribution as a payload DPF whose per-level value is
the pair ``(x, k·x)`` for a per-client MAC key ``k`` (sketch.rs:8-24,
79-130); each server holds additive shares of the one-hot value vector
over the tree level.  The servers then compute three sketch inner
products against a SHARED random vector r (their common seed plays the
role of the reference's shared rand stream, sketch.rs:157-199):

    r_x  = <r, x>,   r2_x = <r², x>,   r_kx = <r, k·x>

— pure batched dot products over (clients × nodes), the MXU-friendly hot
path — and verify with Beaver triples (protocol/mpc.py) that

    1.  <r,x>² − <r²,x>        = 0     (one-hot / 0-1 vector check)
    2.  k·k − k²               = 0     (MAC-key share consistency)
    3.  k·<r,x> − <r,kx>       = 0     (MAC check: no additive attack)

(the MulState recipe of mpc.rs:103-141).  Failures flip the per-client
``alive_keys`` liveness flag that already gates every count
(collect.rs:32, 495 — the hook upstream left for exactly this).

**Multi-dimensional clients** (round 4, the flagship fuzzy workloads):
a client with ``d`` dimensions submits ``d`` independent 1-D payload
DPFs — one per dimension's point — SHARING one MAC key ``k`` (and one
``k_last``), with per-(dim, level) Beaver triples.  Verification runs
the three checks per (client, dim); a client is excluded if ANY dim
fails.  A one-hot check cannot range over a d-dim product tree (an L∞
ball contains many nodes per level), but per-dim one-hot-ness + the
shared MAC pins each dimension's contribution to a single path, which
is exactly the shape of an honest fuzzy submission.

Scope note, stated honestly:

- In the reference's *ancestor* the payload DPF was also the counting
  path, so the sketch protected the counts directly; the reference
  replaced that path with the GC+OT equality protocol and left the
  sketch dead (sketch.rs commented out).  Here the sketch runs as the
  malicious-security scaffold alongside the ibDCF path — same protocol,
  same checks, same liveness gate (collect.rs:32, 495) — so it bounds a
  client's contribution SHAPE (one path per dim, MAC'd), not the ibDCF
  key bits themselves.
- **Frontier restriction** (server path, rpc.sketch_verify): from depth
  2 onward the servers verify the shares of frontier-surviving nodes
  only.  This is sound for COUNT integrity: a share living only on
  pruned nodes is, by the liveness gate, never summed into any count a
  threshold or the final output reads — verifying it would not change
  any protocol output.  What frontier restriction gives up is detection
  (a cheater whose malformation never meets the frontier keeps its
  liveness flag), not correctness of counts.
- **Depth 1 is verified in full** before the first threshold: the
  leader's level-0 ``sketch_verify`` evaluates BOTH children of the
  root per dim and checks the whole 2-node level, so level-0 pruning
  never acts on unverified counts (closing the round-3 gap); the
  depth-1 frontier re-verify is then skipped — re-opening the same
  Beaver triples for a second challenge would leak ``<r - r', x>``.
"""

from __future__ import annotations

import hashlib
import struct
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dpf, prg
from ..ops.dpf import DpfKeyBatch
from ..utils import taint_guard
from . import mpc

LANES = 2  # payload lanes: (x, k·x)

# ---------------------------------------------------------------------------
# Challenge ratchet (restartable sketch crawls)
#
# The per-session coin-flipped seed made malicious crawls one-shot: a
# data-plane reset mid-crawl re-flipped the coin, so a recovered level
# would open its Beaver triples under a DIFFERENT challenge r' and leak
# <r - r', x> of honest payloads.  The ratchet fixes the challenge per
# (collection, level) instead: each level's seed is a hash of
#
#   - a ROOT seed committed once per collection (the coin flip at the
#     first data-plane handshake, captured at tree_init — still
#     unpredictable to clients, who committed their keys beforehand);
#   - the LEVEL index;
#   - a boot-independent TRANSCRIPT DIGEST absorbing every survivor
#     table the leader has applied (both servers receive identical prune
#     frames, so both derive identical digests — and a checkpoint
#     restore rewinds the digest with the frontier).
#
# A re-run of a level after recovery therefore replays the IDENTICAL
# challenge: re-opening the same triple slab reveals exactly the wire
# messages the first run already revealed — a replay, not a second
# opening.  A challenge can only change if the transcript changed, in
# which case the old slab was never opened under it.
# ---------------------------------------------------------------------------

_RATCHET_TAG = b"fhh-sketch-ratchet/1"


def transcript_init() -> bytes:
    """Root of the boot-independent crawl transcript digest (absorb
    survivor tables with :func:`transcript_absorb`)."""
    return hashlib.sha256(_RATCHET_TAG).digest()


def transcript_absorb(
    digest: bytes, level: int, parent: np.ndarray, pat_bits: np.ndarray,
    n_alive: int,
) -> bytes:
    """Fold one prune's survivor table into the transcript digest.  Only
    the REAL entries are absorbed (the bucket padding varies with
    min_bucket and must not perturb the challenge)."""
    h = hashlib.sha256(digest)
    h.update(struct.pack("<qq", int(level), int(n_alive)))
    h.update(np.ascontiguousarray(
        np.asarray(parent[:n_alive], np.int64)
    ).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(pat_bits[:n_alive], bool)
    ).tobytes())
    out = h.digest()
    # the advanced digest commits to the survivor tables — private data;
    # the runtime taint sanitizer watches its bytes at every obs sink
    # (transcript_init's root is a public tag hash and is NOT registered)
    taint_guard.register("CollectionSession._ratchet_digest", out)
    return out


def ratchet_seed(root_seed, level: int, digest: bytes) -> np.ndarray:
    """The challenge seed for one level: uint32[4] =
    SHA-256(tag ‖ root ‖ level ‖ transcript)[:16].  Deterministic in its
    inputs — the restartability contract — and unpredictable to clients
    as long as the coin-flipped root is."""
    h = hashlib.sha256(_RATCHET_TAG)
    h.update(np.ascontiguousarray(
        np.asarray(root_seed, np.uint32)
    ).tobytes())
    h.update(struct.pack("<q", int(level)))
    h.update(digest)
    return np.frombuffer(h.digest()[:16], dtype="<u4").copy()


_WINDOW_TAG = b"fhh-sketch-window-root/1"


def window_root(session_seed, window: int) -> np.ndarray:
    """A streaming window's ratchet root: uint32[4] =
    SHA-256(tag ‖ session coin flip ‖ window)[:16].  Committed at
    ``window_seal`` and CARRIED by the seal stats + the ingest
    checkpoint, so a recovered window replays the IDENTICAL challenge
    sequence even though a restarted server's plane handshake flipped a
    fresh session coin — re-opening the window's Beaver slabs is then a
    replay, never a second opening (the batch-path ratchet argument,
    per window)."""
    h = hashlib.sha256(_WINDOW_TAG)
    h.update(np.ascontiguousarray(
        np.asarray(session_seed, np.uint32)
    ).tobytes())
    h.update(struct.pack("<q", int(window)))
    return np.frombuffer(h.digest()[:16], dtype="<u4").copy()


class SketchKeyBatch(NamedTuple):
    """One party's sketch keys for N clients (ref: sketch.rs:14-24).

    The DPF batch carries one key per (client, dim) — batch dims
    ``[N, d]`` — while the MAC keys are per CLIENT (shared across the
    client's dims); triples are per (client, dim, level, check)."""

    key: DpfKeyBatch  # batch dims [N, d]
    mac_key: jax.Array  # field_t share [N]
    mac_key2: jax.Array  # field_t share of k² [N]
    mac_key_last: jax.Array  # field_u share [N(, limbs)]
    mac_key2_last: jax.Array
    triples: mpc.TripleBatch  # field_t [N, d, L-1, CHECKS]
    triples_last: mpc.TripleBatch  # field_u [N, d, CHECKS(, limbs)]


class SketchOutput(NamedTuple):
    """Per-client sketch inner products + shared linear-combination
    coefficients (ref: sketch.rs:26-43)."""

    r_x: jax.Array
    r2_x: jax.Array
    r_kx: jax.Array
    rand1: jax.Array
    rand2: jax.Array
    rand3: jax.Array


def gen(init_seeds, alpha_bits, field_t, field_u, seed) -> tuple[SketchKeyBatch, SketchKeyBatch]:
    """Client-side keygen (ref: sketch.rs:79-149 ``gen``/``gen_from_str``):
    unit payloads (x = 1 at the client's prefix) MAC'd with fresh per-client
    keys; triples for every (dim, level)'s checks ride along.

    init_seeds: uint32[N(, d), 2, 4]; alpha_bits: bool[N(, d), L] — one
    payload DPF per dimension sharing the client's MAC key; seed:
    uint32[4] client-side randomness (MAC keys, shares, triples).  1-D
    callers may omit the dim axis (normalized to d = 1).
    """
    alpha_bits = np.asarray(alpha_bits, bool)
    init_seeds = np.asarray(init_seeds, np.uint32)
    if alpha_bits.ndim == 2:  # [N, L] -> [N, 1, L]
        alpha_bits = alpha_bits[:, None, :]
        init_seeds = init_seeds[:, None]
    N, d, L = alpha_bits.shape
    wt = 4
    wu = 8 if field_u.limb_shape else 4
    seed = jnp.asarray(seed, jnp.uint32)

    def sub_seed(tag):
        return seed ^ jnp.asarray([0, 0, 0, tag], jnp.uint32)

    # MAC keys + shares (per client, shared across its dims)
    k = field_t.sample(prg.stream_words(sub_seed(1), N * wt).reshape(N, wt))
    k2 = field_t.mul(k, k)
    k_last = field_u.sample(prg.stream_words(sub_seed(2), N * wu).reshape(N, wu))
    k2_last = field_u.mul(k_last, k_last)

    def share(field, v, tag):
        w = 8 if field.limb_shape else 4
        n = int(np.prod(v.shape[: v.ndim - len(field.limb_shape)]))
        s0 = field.sample(prg.stream_words(sub_seed(tag), n * w).reshape(v.shape[: v.ndim - len(field.limb_shape)] + (w,)))
        return s0, field.sub(v, s0)

    k_s = share(field_t, k, 3)
    k2_s = share(field_t, k2, 4)
    kl_s = share(field_u, k_last, 5)
    k2l_s = share(field_u, k2_last, 6)

    # payload values: inner levels (1, k) in T; last level (1, k_last) in U
    one_t = jnp.broadcast_to(field_t.from_int(1), (N,))
    vals = jnp.stack([one_t, k], axis=-1)[:, None, None, :]  # [N, 1, 1, 2]
    vals = jnp.broadcast_to(vals, (N, d, L - 1, LANES))
    limb = field_u.limb_shape
    one_u = jnp.broadcast_to(field_u.from_int(1), (N, d) + limb)
    k_lb = jnp.broadcast_to(k_last[:, None], (N, d) + limb)
    vals_last = jnp.stack([one_u, k_lb], axis=2)  # [N, d, LANES(, limbs)]

    dk0, dk1 = dpf.gen_pair(
        init_seeds, alpha_bits, vals, vals_last, field_t, field_u, LANES
    )

    t0, t1 = mpc.gen_triples(field_t, (N, d, L - 1, mpc.CHECKS), sub_seed(7))
    tl0, tl1 = mpc.gen_triples(field_u, (N, d, mpc.CHECKS), sub_seed(8))

    def mk(p, dk, trip, trip_last):
        return SketchKeyBatch(
            key=dk,
            mac_key=k_s[p],
            mac_key2=k2_s[p],
            mac_key_last=kl_s[p],
            mac_key2_last=k2l_s[p],
            triples=trip,
            triples_last=trip_last,
        )

    return mk(0, dk0, t0, tl0), mk(1, dk1, t1, tl1)


def shared_r_stream(field, shared_seed, level: int, m: int, n_rand: int):
    """The servers' common sketch randomness for one level: per-node r_j
    (and r_j²) plus ``n_rand`` rows of rand1..3 (one row per client×dim) —
    both servers derive identical values from the shared seed (the
    reference's shared rand_stream, sketch.rs:164-168, seeded like
    server.rs:331-332)."""
    w = 8 if field.limb_shape else 4
    s = jnp.asarray(shared_seed, jnp.uint32) ^ jnp.asarray(
        [0, 0, 0x5E71C, level], jnp.uint32
    )
    words = prg.stream_words(s, (m + 3 * n_rand) * w)
    r = field.sample(words[: m * w].reshape((m, w)))
    rands = field.sample(
        words[m * w :].reshape((n_rand, 3, w))
    )
    return r, rands


# ---------------------------------------------------------------------------
# Seek-by-offset challenge stream (the row-sharded verify's discipline)
#
# ``shared_r_stream`` draws the level's whole challenge stream — m per-node
# words of r, then 3 rand rows per (client, dim) — from one CTR stream.
# The device-resident sharded verify (parallel/sketch_shard.py) must hand
# shard i EXACTLY its client slice of that stream without materializing
# the rest, the same seek-by-offset discipline as
# ``otext.sender_extend_rows``: the stream is CTR-mode, so any word range
# is reachable by block offset + an intra-block slice.  Both helpers are
# jit-safe with TRACED ``level``/``row0`` (one compiled program per
# (m, batch) shape serves every level and every shard).
# ---------------------------------------------------------------------------

_STREAM_TAG = 0x5E71C  # shared_r_stream's level-domain word


def _stream_seed(seed, level):
    """The level's CTR seed — ``shared_r_stream``'s XOR, traced-level
    safe."""
    z = jnp.uint32(0)
    return jnp.asarray(seed, jnp.uint32) ^ jnp.stack(
        [z, z, jnp.uint32(_STREAM_TAG), jnp.asarray(level, jnp.uint32)]
    )


def challenge_r(field, seed, level, m: int):
    """The level's shared per-node challenge vector r — words [0, m·w)
    of the stream, identical on every shard (each derives it from the
    replicated seed)."""
    w = 8 if field.limb_shape else 4
    words = prg.stream_words(_stream_seed(seed, level), m * w)
    return field.sample(words.reshape(m, w))


def challenge_rands(field, seed, level, m: int, row0, n_rows: int):
    """Rows [row0, row0 + n_rows) of the level's rand1..3 table — the
    seek-by-offset twin of ``shared_r_stream``'s tail draw (words
    ``m·w + row0·3·w`` onward).  ``row0`` may be traced (the shard's
    ``axis_index``-derived offset); the generated block window covers
    any intra-block misalignment, and the slice is bit-identical to the
    corresponding rows of the single-device stream by CTR construction.
    (int32 word arithmetic: exact below ~2^31 stream words per level —
    ~34 GB of challenge material, far past any real batch.)"""
    w = 8 if field.limb_shape else 4
    span = n_rows * 3 * w
    word_lo = m * w + jnp.asarray(row0) * (3 * w)
    blk0 = word_lo // 16
    off = word_lo - blk0 * 16  # 0..15
    nblk = (span + 15) // 16 + 1  # static bound: any off fits
    blocks = prg.stream_blocks(
        _stream_seed(seed, level), nblk, jnp.asarray(blk0, jnp.uint32)
    )
    words = jax.lax.dynamic_slice(blocks.reshape(nblk * 16), (off,), (span,))
    return field.sample(words.reshape(n_rows, 3, w))


def level_check_state(field, pairs, triples: mpc.TripleBatch, mac_key,
                      mac_key2, seed, level, row0) -> mpc.MulStateBatch:
    """One fused level's check state for a client slice: ``pairs``
    field[m, n, d, LANES(, limbs)] value-pair shares over the level's m
    nodes, per-client MAC shares [n(, limbs)], and the per-(client, dim,
    check) triple slab.  ``row0`` is the slice's (client·dim)-row offset
    into the level's challenge stream — 0 for the whole batch, the
    shard's offset under ``shard_map`` — so a sharded call computes
    EXACTLY its rows of the single-device state.  jit-safe (traced
    ``level``/``row0``); the caller stacks :func:`mpc.cor_share` of the
    result as the wire message."""
    m, n, d = pairs.shape[0], pairs.shape[1], pairs.shape[2]
    r = challenge_r(field, seed, level, m)
    rands = challenge_rands(field, seed, level, m, row0, n * d)
    rands = rands.reshape((n, d, 3) + field.limb_shape)
    p = jnp.moveaxis(jnp.asarray(pairs), 0, 2)  # [n, d, m, LANES(, limbs)]
    out = sketch_output(field, p, r, rands)
    mk = jnp.expand_dims(jnp.asarray(mac_key), 1)  # broadcast over dims
    mk2 = jnp.expand_dims(jnp.asarray(mac_key2), 1)
    return mul_state(field, out, mk, mk2, triples)


@partial(jax.jit, static_argnames=("field",))
def sketch_output(field, pair_shares, r, rands) -> SketchOutput:
    """Batched sketch inner products (ref: sketch.rs:157-199 sketch_at).

    pair_shares: field[..., M, LANES(, limbs)] — this server's value-pair
    shares over the M tree nodes of the level, any leading batch dims
    (clients, or clients × dims); r: field[M(, limbs)] shared random
    vector; rands: field[..., 3(, limbs)] matching the batch dims.
    """
    limb = len(field.limb_shape)
    if limb:
        x, kx = pair_shares[..., 0, :], pair_shares[..., 1, :]
    else:
        x, kx = pair_shares[..., 0], pair_shares[..., 1]
    m_axis = x.ndim - 1 - limb
    r2 = field.mul(r, r)
    r_x = field.sum(field.mul(x, r), axis=m_axis)
    r2_x = field.sum(field.mul(x, r2), axis=m_axis)
    r_kx = field.sum(field.mul(kx, r), axis=m_axis)
    g = lambda i: (rands[..., i, :] if limb else rands[..., i])
    return SketchOutput(
        r_x=r_x, r2_x=r2_x, r_kx=r_kx, rand1=g(0), rand2=g(1), rand3=g(2)
    )


@partial(jax.jit, static_argnames=("field",))
def mul_state(field, out: SketchOutput, mac_key, mac_key2, triples) -> mpc.MulStateBatch:
    """Assemble the three checks per batch row (ref: mpc.rs:83-141):
    (1) r_x*r_x - r2_x; (2) k*k - k²; (3) r_x*k - r_kx.  ``mac_key`` /
    ``mac_key2`` must be pre-broadcast to ``out.r_x``'s shape."""
    axis = out.r_x.ndim - len(field.limb_shape)
    stack = lambda *vs: jnp.stack(
        [jnp.broadcast_to(v, out.r_x.shape) for v in vs], axis=axis
    )
    xs = stack(out.r_x, mac_key, out.r_x)
    ys = stack(out.r_x, mac_key, mac_key)
    zs = stack(field.neg(out.r2_x), field.neg(mac_key2), field.neg(out.r_kx))
    rs = stack(out.rand1, out.rand2, out.rand3)
    return mpc.MulStateBatch(xs=xs, ys=ys, zs=zs, rs=rs, triples=triples)


def verify_batch(field, state0: mpc.MulStateBatch, state1: mpc.MulStateBatch):
    """In-process two-server verification: cor exchange + out exchange
    (the socketpair shape of the dead main.rs:14-72 ``verify_sketches``).
    Returns bool[N] — True where the client's sketch passes."""
    c0 = mpc.cor_share(field, state0)
    c1 = mpc.cor_share(field, state1)
    opened = mpc.cor(field, c0, c1)
    o0 = mpc.out_share(field, False, state0, opened)
    o1 = mpc.out_share(field, True, state1, opened)
    return np.asarray(mpc.verify(field, o0, o1))


# ---------------------------------------------------------------------------
# Server-side level evaluation over all prefixes of a level (the sketch's
# own frontier; chunked by sketch_batch_size over the client axis)
# ---------------------------------------------------------------------------


def eval_level_full(key: SketchKeyBatch, level: int, field_t, field_u, data_len: int):
    """Value-pair shares for ALL 2^(level+1) prefixes at ``level``.

    Walks a fixed ``2^data_len``-slot padded tree: slot i's direction at
    step j is bit ``data_len-1-j`` of i, so slots sharing a prefix hold
    identical (redundantly computed) states and EVERY level advances with
    the same ``[..., 2^data_len]`` program — one XLA compile per field for
    the whole walk instead of one per level width (the test suite is
    compile-bound; the redundancy is trivial at spec-helper scale).
    Exponential in ``data_len`` by construction: this enumerates all
    prefixes (a SPEC/TEST helper — the server path is the
    frontier-following sketch state, protocol/rpc.py) and is therefore
    guarded to small domains.  Key batch dims are arbitrary (clients, or
    clients × dims); returns field[..., 2^(level+1), LANES(, limbs)]."""
    if data_len > 16:
        raise ValueError(
            f"eval_level_full enumerates 2^data_len slots; data_len="
            f"{data_len} > 16 would allocate per-client tensors of "
            f"{1 << data_len} nodes — use the frontier-following server "
            "path (rpc.sketch_verify) for production domains"
        )
    k = key.key
    batch = k.root_seed.shape[:-1]
    nb = len(batch)
    L = data_len
    M = 1 << L
    st = jax.tree.map(
        lambda a: jnp.broadcast_to(
            a[(Ellipsis, None) + (slice(None),) * (a.ndim - nb)],
            batch + (M,) + a.shape[nb:],
        ),
        dpf.eval_init(k),
    )  # [..., M(, 4)]
    slots = jnp.arange(M)
    shares = None
    for j in range(level + 1):
        cw = tuple(c[..., None, :] for c in dpf.level_cw(k, j))
        field = field_t if j < data_len - 1 else field_u
        if j < data_len - 1:
            cwv = k.cw_val[..., j, :]  # [..., LANES]
        else:
            cwv = k.cw_val_last  # [..., LANES(, limbs)]
        cwv = jnp.expand_dims(cwv, axis=nb)  # insert the M axis
        dirs = jnp.broadcast_to(
            ((slots >> (L - 1 - j)) & 1).astype(bool), batch + (M,)
        )
        st, shares = dpf.eval_bit(
            cw, st, dirs, cwv, k.key_idx[..., None], field, LANES
        )
    # representative slot of prefix p (level+1 bits): p << (L-1-level)
    idx = jnp.arange(1 << (level + 1)) << (L - 1 - level)
    return jnp.take(shares, idx, axis=nb)


def verify_level(
    sk0: SketchKeyBatch,
    sk1: SketchKeyBatch,
    level: int,
    field_t,
    field_u,
    data_len: int,
    shared_seed,
    sketch_batch_size: int = 100_000,
) -> np.ndarray:
    """Full two-server sketch verification at one level -> bool[N].

    Multi-dim key batches ([N, d]) verify per (client, dim); a client
    passes iff every dim passes.  Chunked over the client axis by
    ``sketch_batch_size`` (the config knob the reference ships but never
    parses, src/bin/config.json:9-10).  Spec/test helper like
    :func:`eval_level_full` (and guarded by its data_len limit)."""
    last = level == data_len - 1
    field = field_u if last else field_t
    limb = field.limb_shape
    batch = np.asarray(sk0.key.root_seed).shape[:-1]  # (N,) or (N, d)
    N = batch[0]
    extra = batch[1:]
    m = 1 << (level + 1)
    out = np.empty(N, bool)
    for lo in range(0, N, sketch_batch_size):
        sl = slice(lo, min(lo + sketch_batch_size, N))
        ks0 = jax.tree.map(lambda a: a[sl], sk0)
        ks1 = jax.tree.map(lambda a: a[sl], sk1)
        # .shape needs no materialization — np.asarray here copied the
        # whole seed batch to host once per verification batch
        n_sl = ks0.key.root_seed.shape[0]
        # draw the r vector at the full-tree width and slice: the stream
        # program then has one shape for every level (and both servers
        # still derive identical values — same function, same args)
        r_full, rands = shared_r_stream(
            field, shared_seed, level, 1 << data_len,
            n_sl * int(np.prod(extra, initial=1)),
        )
        r = r_full[:m]
        rands = rands.reshape((n_sl,) + tuple(extra) + (3,) + limb)
        states = []
        for ks in (ks0, ks1):
            pairs = eval_level_full(ks, level, field_t, field_u, data_len)
            o = sketch_output(field, pairs, r, rands)
            if last:
                trip = ks.triples_last
                mk, mk2 = ks.mac_key_last, ks.mac_key2_last
            else:
                trip = mpc.level_slab(ks.triples, level)
                mk, mk2 = ks.mac_key, ks.mac_key2
            if extra:  # broadcast per-client MACs over the dim axis
                mk = jnp.expand_dims(jnp.asarray(mk), 1)
                mk2 = jnp.expand_dims(jnp.asarray(mk2), 1)
            states.append(mul_state(field, o, mk, mk2, trip))
        ok = verify_batch(field, states[0], states[1])
        out[sl] = ok if not extra else ok.all(axis=tuple(range(1, ok.ndim)))
    return out
