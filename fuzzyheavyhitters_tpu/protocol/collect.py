"""Aggregation engine: the prefix-tree frontier crawl as device kernels.

TPU-first redesign of the reference's ``KeyCollection`` state machine
(ref: src/collect.rs:28-507).  The reference walks Rust object trees —
``TreeNode{path, key_states}`` per node, rayon loops over clients, and it
re-evaluates every client's FSS state once per child pattern
(``make_tree_node`` per search string, collect.rs:378-391), costing
``2^d × d×2`` PRG calls per (node, client).  Here:

- the frontier is a **padded tensor** ``[F, N, d, 2]`` of eval states with an
  alive-node mask (SURVEY.md §7 hard part 4) — no objects, no ragged shapes;
- one batched PRG expansion per (node, client, dim, side) yields BOTH
  children at once, serving all ``2^d`` child patterns — a ``2^d``-fold
  saving over the reference's per-pattern re-evaluation;
- each (node, client)'s both-direction share bits pack into ONE uint32
  (bit position ``j*4 + side*2 + dir`` for dim j ≤ 8), so per-pattern ball
  membership is a single ``(p0 ^ p1) & pattern_mask == 0`` — and that uint32
  is also the only thing the two servers ever need to exchange per level;
- paths live with the leader (host), not on device: expansion and prune
  orders are deterministic (child c of node f sits at ``f * 2^d + c``,
  pattern bit j = ``(c >> j) & 1``, matching the reference's
  ``all_bit_vectors`` child order, lib.rs:125-129), so the leader
  reconstructs paths from its own keep masks.

Memory plan: the counts pass emits only packed share bits (4 B per
node·client) — the expand → correction → pack pipeline is one fused XLA
program, so child seeds never materialize in HBM; after the leader prunes,
the surviving children's states come from one more expansion of their
parents (``advance``).  Per level this is ``(F + F') × N × d × 2`` PRG
expansions — still ``≈ 2^d / 2`` times fewer than the reference — and the
peak HBM footprint is the parent frontier plus the packed-bit tensor,
independent of ``2^d``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import ibdcf, prg
from ..ops.ibdcf import EvalState, IbDcfKeyBatch

MAX_DIMS = 8  # packed-u32 layout holds d*4 bits

# Advance-step engine, read at TRACE time: True routes the per-level eval
# expansion through the fused Pallas kernel (ops/eval_pallas.py).  Opt-in
# and TPU-only (the mesh/shard_map path always uses XLA): measured
# net-neutral at bench sizes through the remote-chip tunnel, kept for
# locally-attached chips where dispatch overhead is not the floor.
EVAL_PALLAS = False


class Frontier(NamedTuple):
    """Per-server frontier state for ``F`` (padded) tree nodes.

    states: EvalState over ``[F, N, d, 2]`` (node, client, dim, left/right);
    alive:  bool[F] node-liveness mask (dead slots are padding).
    """

    states: EvalState
    alive: jax.Array

    @property
    def f_max(self) -> int:
        return self.states.bit.shape[0]


def tree_init(keys: IbDcfKeyBatch, f_max: int) -> Frontier:
    """Root frontier: one alive node whose states are eval_init of every
    (client, dim, side) key (ref: collect.rs:67-92)."""
    root = ibdcf.eval_init(keys)  # [N, d, 2]
    pad = lambda a: jnp.broadcast_to(a[None], (f_max,) + a.shape)
    alive = jnp.zeros((f_max,), bool).at[0].set(True)
    return Frontier(states=EvalState(*[pad(x) for x in root]), alive=alive)


def _bit_positions(d: int):
    """bit position of (dim j, side s, direction r) in the packed uint32."""
    j = np.arange(d)[:, None, None]
    s = np.arange(2)[None, :, None]
    r = np.arange(2)[None, None, :]
    return (j * 4 + s * 2 + r).astype(np.uint32)  # [d, 2, 2]


def pattern_masks(d: int) -> np.ndarray:
    """uint32[2^d] — for child pattern c, the packed-bit positions that a
    membership test must compare: both sides of every dim, at direction
    ``(c >> j) & 1`` (child order: ref lib.rs:125-129)."""
    assert d <= MAX_DIMS
    pos = _bit_positions(d)
    masks = []
    for c in range(1 << d):
        m = np.uint32(0)
        for j in range(d):
            r = (c >> j) & 1
            m |= (np.uint32(1) << pos[j, 0, r]) | (np.uint32(1) << pos[j, 1, r])
        masks.append(m)
    return np.array(masks, dtype=np.uint32)


def expand_share_bits(keys: IbDcfKeyBatch, frontier: Frontier, level) -> jax.Array:
    """One PRG expansion of the whole frontier -> packed share bits.

    Returns uint32[F, N]: for every (node, client), the share bits
    ``y_bit ^ bit`` of BOTH child directions of every (dim, side) key,
    packed at ``_bit_positions`` (the tensor twin of collect.rs:393-410's
    per-(node,client) left||right bit strings — ours carries both
    directions so all 2^d patterns read from it).

    ``level`` may be traced; the same value must hold for the whole frontier
    (the crawl is level-synchronous, ref: leader.rs:417-440).
    """
    return _expand_share_bits_jit(keys, frontier, level, prg.DERIVED_BITS)


@partial(jax.jit, static_argnames=("derived_bits",))
def _expand_share_bits_jit(keys, frontier, level, derived_bits):
    cw_seed, cw_bits, cw_y = ibdcf.level_cw(keys, level)  # [N,d,2,(4|2)]
    st = frontier.states  # leaves [F, N, d, 2(,4)]
    # one fully-batched expansion over (node, client, dim, side); XLA fuses
    # expand -> correction -> pack, so child seeds never hit HBM
    _, _, tau_b, tau_y = prg.expand(st.seed, derived_bits)  # [F,N,d,2,2]
    t = st.bit[..., None]
    nb = jnp.where(t, tau_b ^ cw_bits, tau_b)  # cw broadcasts over F
    ny = jnp.where(t, tau_y ^ cw_y, tau_y)
    ny = ny ^ st.y_bit[..., None]
    share = nb ^ ny  # share bit = y ^ t per direction
    pos = jnp.asarray(_bit_positions(share.shape[-3]))  # [d, 2, 2]
    return jnp.sum(
        share.astype(jnp.uint32) << pos, axis=(-3, -2, -1), dtype=jnp.uint32
    )  # [F, N] uint32


@jax.jit
def counts_by_pattern(
    packed_self: jax.Array,
    packed_peer: jax.Array,
    masks: jax.Array,
    alive_keys: jax.Array,
    alive_nodes: jax.Array,
) -> jax.Array:
    """uint32[F, 2^d] per-child candidate counts.

    Membership of client i's ball in child (f, c) ⇔ the two servers' share
    bits agree on every compared position: ``(p0 ^ p1) & masks[c] == 0``
    (the plaintext of the GC equality test, ref: equalitytest.rs:130-146,
    reconstructed as the leader would, collect.rs:945-964).  Dead clients
    and dead nodes contribute zero (liveness gate, ref: collect.rs:495).
    """
    diff = packed_self ^ packed_peer  # [F, N]
    eq = (diff[:, :, None] & masks[None, None, :]) == 0  # [F, N, 2^d]
    eq = eq & alive_keys[None, :, None] & alive_nodes[:, None, None]
    return jnp.sum(eq, axis=1, dtype=jnp.uint32)  # [F, 2^d]


def advance(
    keys: IbDcfKeyBatch,
    frontier: Frontier,
    level,
    parent_idx: jax.Array,
    pattern_bits: jax.Array,
    n_alive: jax.Array,
) -> Frontier:
    """Materialize the surviving children as the next frontier.

    parent_idx:   int32[F'] parent slot per surviving child (padded);
    pattern_bits: bool[F', d] child pattern per survivor;
    n_alive:      number of real entries (rest is padding).

    Gathers the parents' states and advances one level with the pattern's
    per-dim direction (both keys of a dim take the same bit — the interval
    pair walks together, ref: collect.rs:100, ibDCF.rs:120-131).
    """
    return _advance_jit(
        keys, frontier, level, parent_idx, pattern_bits, n_alive,
        prg.DERIVED_BITS, EVAL_PALLAS,
    )


@partial(jax.jit, static_argnames=("derived_bits", "use_pallas"))
def _advance_jit(keys, frontier, level, parent_idx, pattern_bits, n_alive,
                 derived_bits, use_pallas=False):
    cw = ibdcf.level_cw(keys, level)
    st = frontier.states
    parents = jax.tree.map(lambda a: a[parent_idx], st)  # [F', N, d, 2]
    direction = jnp.broadcast_to(
        pattern_bits[:, None, :, None], parents.bit.shape
    )  # child pattern bit of each dim, same for both keys of the dim
    if use_pallas:
        from ..ops import eval_pallas

        cw_seed, cw_bits, cw_y = cw  # [N, d, 2, 4], [N, d, 2, 2]
        shp = parents.bit.shape  # [F', N, d, 2]
        # direction-select the cw bits and broadcast over the node axis in
        # XLA (bandwidth-trivial); the kernel is a pure flat map
        cwb_d = jnp.where(direction, cw_bits[None, ..., 1], cw_bits[None, ..., 0])
        cwy_d = jnp.where(direction, cw_y[None, ..., 1], cw_y[None, ..., 0])
        cws_b = jnp.broadcast_to(cw_seed[None], shp + (4,))
        seed2, bit2, y2 = eval_pallas.eval_bit_flat(
            parents.seed.reshape(-1, 4),
            parents.bit.reshape(-1),
            parents.y_bit.reshape(-1),
            direction.reshape(-1),
            cws_b.reshape(-1, 4),
            cwb_d.reshape(-1),
            cwy_d.reshape(-1),
            derived_bits,
        )
        states = EvalState(
            seed=seed2.reshape(shp + (4,)),
            bit=bit2.reshape(shp),
            y_bit=y2.reshape(shp),
        )
    else:
        states = ibdcf._eval_bit_jit(cw, parents, direction, derived_bits)
    f_max = parent_idx.shape[0]
    alive = jnp.arange(f_max) < n_alive
    return Frontier(states=states, alive=alive)


# ---------------------------------------------------------------------------
# Host-side compaction helper (leader-side prune bookkeeping)
# ---------------------------------------------------------------------------


def compact_survivors(keep: np.ndarray, f_max: int):
    """keep: bool[F, 2^d] -> (parent_idx int32[f_max], pattern int32[f_max],
    n_alive) padded with zeros.  Raises if survivors exceed f_max — the
    padded-frontier equivalent of the reference's unbounded Vec growth."""
    f, c = np.nonzero(keep)
    if len(f) > f_max:
        raise ValueError(
            f"{len(f)} surviving nodes exceed f_max={f_max}; "
            "raise f_max (recompiles) or the threshold"
        )
    parent = np.zeros(f_max, np.int32)
    pattern = np.zeros(f_max, np.int32)
    parent[: len(f)] = f
    pattern[: len(f)] = c
    return parent, pattern, len(f)


def pattern_to_bits(pattern: np.ndarray, d: int) -> np.ndarray:
    """int32[F'] child pattern ids -> bool[F', d] per-dim direction bits
    (bit j = (c >> j) & 1, ref: lib.rs:125-129)."""
    return ((pattern[:, None] >> np.arange(d)[None]) & 1).astype(bool)
