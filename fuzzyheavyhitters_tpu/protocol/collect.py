"""Aggregation engine: the prefix-tree frontier crawl as device kernels.

TPU-first redesign of the reference's ``KeyCollection`` state machine
(ref: src/collect.rs:28-507).  The reference walks Rust object trees —
``TreeNode{path, key_states}`` per node, rayon loops over clients, and it
re-evaluates every client's FSS state once per child pattern
(``make_tree_node`` per search string, collect.rs:378-391), costing
``2^d × d×2`` PRG calls per (node, client).  Here:

- the frontier is a **padded tensor** ``[F, N, d, 2]`` of eval states with an
  alive-node mask (SURVEY.md §7 hard part 4) — no objects, no ragged shapes;
- one batched PRG expansion per (node, client, dim, side) yields BOTH
  children at once, serving all ``2^d`` child patterns — a ``2^d``-fold
  saving over the reference's per-pattern re-evaluation;
- each (node, client)'s both-direction share bits pack into ONE uint32
  (bit position ``j*4 + side*2 + dir`` for dim j ≤ 8), so per-pattern ball
  membership is a single ``(p0 ^ p1) & pattern_mask == 0`` — and that uint32
  is also the only thing the two servers ever need to exchange per level;
- paths live with the leader (host), not on device: expansion and prune
  orders are deterministic (child c of node f sits at ``f * 2^d + c``,
  pattern bit j = ``(c >> j) & 1``, matching the reference's
  ``all_bit_vectors`` child order, lib.rs:125-129), so the leader
  reconstructs paths from its own keep masks.

Work plan (round 4 — closing the padded-frontier waste the reference
never has because it walks only live nodes, collect.rs:378-391):

- **Bucketed frontier**: the padded node axis ``F`` is the smallest power
  of two ≥ the survivor count (``bucket_for``), not a fixed ``f_max``.
  Static shapes are preserved — each bucket size is its own compiled
  program, at most ``log2(f_max)+1`` of them per crawl — while dead-slot
  waste is bounded at 2× instead of ``f_max / n_alive`` (the round-3
  regime ran 64 slots for ~4-8 live nodes: ~8-16× wasted PRG work).
- **Child-state cache**: ``expand_share_bits`` already runs the PRG on
  every (node, client, dim, side); it now also returns BOTH directions'
  child :class:`EvalState`, so the post-prune ``advance_from_children``
  is a pure gather+select — the second PRG pass of the old ``advance``
  (another ``F' × N × d × 2`` expansions per level) is gone entirely.
  Cost: the cache materializes ``F × N × d × 2 × 2`` child states
  (~72 B per (node, client, dim, side)) in HBM between crawl and prune —
  at bucketed ``F`` this is MBs, not GBs, and it replaces compute with
  bandwidth the TPU has to spare.  ``advance`` (re-expand) remains for
  callers without a cache.

Per level the PRG cost is now ``F_bucket × N × d × 2`` expansions — one
pass, sized to survivors — and the peak HBM footprint is the parent
frontier plus the child cache plus the packed-bit tensor, independent of
``2^d``.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import ibdcf, prg
from ..ops.ibdcf import EvalState, IbDcfKeyBatch

MAX_DIMS = 8  # packed-u32 layout holds d*4 bits (radix 1; see check_radix)


def radix_subtree_nodes(radix: int) -> int:
    """Nodes in the depth-``radix`` binary subtree below one (dim, side)
    frontier state: 2 + 4 + … + 2^radix = 2^(radix+1) - 2.  The packed
    share-bit word stores ALL of them per (dim, side), so a fused level
    can compare any intermediate depth along a child pattern's path."""
    return (1 << (radix + 1)) - 2


def max_dims_for_radix(radix: int) -> int:
    """Dim cap keeping 2·d·radix_subtree_nodes(radix) packed bits in one
    uint32: 8 dims at radix 1 (== MAX_DIMS), 2 at radix 2, 1 at radix 3."""
    return 32 // (2 * radix_subtree_nodes(radix))


def check_radix(d: int, radix: int) -> None:
    """Validate a crawl radix against the packed-u32 layout — loud, at
    config-use time, instead of a silent bit collision mid-crawl."""
    if radix not in (1, 2, 3):
        raise ValueError(
            f"crawl_radix_bits={radix}: supported radices are 1, 2, 3"
        )
    cap = max_dims_for_radix(radix)
    if d > cap:
        raise ValueError(
            f"crawl_radix_bits={radix} supports at most {cap} dim(s): the "
            f"packed share-bit word needs 2·d·(2^(radix+1)-2) bits per "
            f"(node, client) and must fit one uint32; got n_dims={d}"
        )

# NOTE (round 5): the per-level eval kernel `ops/eval_pallas.py` that once
# served the RE-EXPANDING fallback `advance` was retired: every crawl path
# advances via the gather-based `advance_from_children` (strictly better
# than any kernel — zero PRG work), leaving the kernel production-dead.
# The fallback runs the plain XLA eval step; the kernel lives in git
# history (rounds 3-4) if a re-expanding consumer ever returns.

# Engine for the level expansion itself (the crawl's dominant op): True
# (the default on real chips) routes it through the fused pack-in-kernel
# Pallas engine (ops/expand_pallas.py) with PLANE-MAJOR frontier state
# (seeds u32[4, d, 2, F, N], bits bool[d, 2, F, N]); False keeps the XLA
# ChaCha with interleaved [F, N, d, 2, 4] seeds (the only engine on CPU,
# and what the mesh bodies pin).  History, recorded honestly: the round-4
# word-planar kernel beat XLA on the body (~5 ms vs ~14 ms at B = 1M
# states) but lost it all to unfused pack/cache glue at the pallas_call
# boundary (~19 ms end to end); THIS engine moves the share-bit pack and
# the flag handling INTO the kernel (packed u32 emitted in-kernel, cw
# broadcast over nodes via a modular BlockSpec index map) — the round-4
# prototype the round-4 VERDICT asked to land.  NB the shared chip's
# throughput swings ~4x by hour; only back-to-back A/Bs are meaningful —
# bench.py's crawl section measures both engines back to back.  The
# engine — and with it the frontier state LAYOUT — is read at tree_init /
# expand / advance time and must not flip mid-crawl.
EXPAND_PALLAS: bool = True


def _expand_engine() -> bool:
    """Pallas engine iff enabled AND the effective default device is an
    accelerator — a ``jax.default_device(cpu)`` context (the test suite's
    way of pinning compile-bound tests to the host) must fall back to the
    XLA engine: Pallas has no CPU compile path."""
    from ..utils import effective_platform

    return EXPAND_PALLAS and effective_platform() != "cpu"


class Frontier(NamedTuple):
    """Per-server frontier state for ``F`` (bucket-padded) tree nodes.

    states: EvalState over ``[F, N, d, 2]`` (node, client, dim, left/right);
    alive:  bool[F] node-liveness mask (dead slots are padding).

    ``F`` is the current *bucket* — the smallest power of two holding the
    live nodes (see :func:`bucket_for`), not a global maximum.

    State LAYOUT depends on the expansion engine: the XLA engine keeps
    ``seed`` interleaved ``[F, N, d, 2, 4]`` with bits ``[F, N, d, 2]``;
    the Pallas engine keeps everything PLANE-MAJOR — seed
    ``[4, d, 2, F, N]``, bits ``[d, 2, F, N]`` — so one kernel block sees
    all ``d*2`` planes of a (node, client) row and packs the share bits
    in-kernel (ops/expand_pallas.py).
    """

    states: EvalState
    alive: jax.Array

    @property
    def f_bucket(self) -> int:
        return self.alive.shape[0]  # layout-independent (alive is always [F])


class PlanarChildren(NamedTuple):
    """Plane-major child-state cache from the Pallas engine.

    seed:  u32[2, 4, d, 2, F, N] — direction-major, t-corrected child seeds;
    flags: u32[d, 2, F, N] — packed child bits per direction
           (b_l | b_r<<1 | y_l<<2 | y_r<<3, y accumulated along the path).
    """

    seed: jax.Array
    flags: jax.Array


def bucket_for(n_alive: int, f_max: int, min_bucket: int = 1) -> int:
    """Smallest power of two ≥ ``n_alive`` (≥ ``min_bucket``), capped by
    ``f_max``.

    Sizing frontier tensors to the bucket keeps shapes static per bucket
    (a handful of compiles) while bounding dead-slot waste at 2× — the
    tensor analogue of the reference expanding only live nodes
    (collect.rs:378-391).  ``min_bucket`` lets compile-bound callers (the
    1-core-CPU test host, where every bucket is an XLA compile) pin a
    single shape; performance callers leave it at 1."""
    if n_alive > f_max:
        raise ValueError(
            f"{n_alive} surviving nodes exceed f_max={f_max}; "
            "raise f_max or the threshold"
        )
    b = 1 << max(0, int(np.ceil(np.log2(max(1, n_alive)))))
    return min(f_max, max(b, min_bucket))


def tree_init(
    keys: IbDcfKeyBatch, f_bucket: int = 1, planar: bool | None = None
) -> Frontier:
    """Root frontier: one alive node whose states are eval_init of every
    (client, dim, side) key (ref: collect.rs:67-92).  The root bucket is 1
    slot; it grows with the survivor count (``bucket_for``).

    ``planar`` selects the state layout (see :class:`Frontier`); None
    follows the process engine — callers that pin an engine (the mesh
    bodies pin XLA) must pin the matching layout here."""
    if planar is None:
        planar = _expand_engine()
    root = ibdcf.eval_init(keys)  # [N, d, 2]
    alive = jnp.zeros((f_bucket,), bool).at[0].set(True)
    if planar:
        # plane-major: [4, d, 2, F, N] seeds, [d, 2, F, N] bits (one
        # once-per-crawl transpose of [N, d, 2]-sized roots — tiny)
        seed = jnp.transpose(root.seed, (3, 1, 2, 0))  # [4, d, 2, N]
        seed = jnp.broadcast_to(
            seed[:, :, :, None], seed.shape[:3] + (f_bucket,) + seed.shape[3:]
        )
        pb = lambda a: jnp.broadcast_to(
            jnp.transpose(a, (1, 2, 0))[:, :, None],
            a.shape[1:] + (f_bucket, a.shape[0]),
        )
        states = EvalState(seed=seed, bit=pb(root.bit), y_bit=pb(root.y_bit))
    else:
        pad = lambda a: jnp.broadcast_to(a[None], (f_bucket,) + a.shape)
        states = EvalState(*[pad(x) for x in root])
    return Frontier(states=states, alive=alive)


def _bit_positions(d: int):
    """bit position of (dim j, side s, direction r) in the packed uint32."""
    j = np.arange(d)[:, None, None]
    s = np.arange(2)[None, :, None]
    r = np.arange(2)[None, None, :]
    return (j * 4 + s * 2 + r).astype(np.uint32)  # [d, 2, 2]


@lru_cache(maxsize=None)
def pattern_masks(d: int) -> np.ndarray:
    """uint32[2^d] — for child pattern c, the packed-bit positions that a
    membership test must compare: both sides of every dim, at direction
    ``(c >> j) & 1`` (child order: ref lib.rs:125-129).

    Cached (and returned read-only): every crawl level on every server
    asked for the same table, rebuilding a 2^d Python loop per level."""
    assert d <= MAX_DIMS
    pos = _bit_positions(d)
    masks = []
    for c in range(1 << d):
        m = np.uint32(0)
        for j in range(d):
            r = (c >> j) & 1
            m |= (np.uint32(1) << pos[j, 0, r]) | (np.uint32(1) << pos[j, 1, r])
        masks.append(m)
    out = np.array(masks, dtype=np.uint32)
    out.setflags(write=False)
    return out


def expand_share_bits(
    keys: IbDcfKeyBatch, frontier: Frontier, level, want_children: bool = True,
    use_pallas: bool | None = None,
):
    """One PRG expansion of the whole frontier -> packed share bits + the
    both-direction child-state cache.

    Returns ``(packed, children)``:

    - packed uint32[F, N]: for every (node, client), the share bits
      ``y_bit ^ bit`` of BOTH child directions of every (dim, side) key,
      packed at ``_bit_positions`` (the tensor twin of collect.rs:393-410's
      per-(node,client) left||right bit strings — ours carries both
      directions so all 2^d patterns read from it);
    - children: the fully-corrected child states of every slot, so the
      post-prune :func:`advance_from_children` is a gather, not a second
      PRG pass.  Its TYPE follows the engine: an :class:`EvalState` over
      ``[F, N, d, 2, 2]`` (trailing axis = direction) from the XLA engine,
      a :class:`PlanarChildren` from the Pallas engine (the default on
      real chips) — treat it as opaque and hand it back to
      :func:`advance_from_children`, which dispatches on the type.

    ``level`` may be traced; the same value must hold for the whole frontier
    (the crawl is level-synchronous, ref: leader.rs:417-440).

    ``want_children=False`` (the LAST level, which nothing advances past)
    skips materializing the cache — jit outputs are never dead-code
    eliminated, so the flag must be static, not a discarded return.

    ``use_pallas`` overrides the process engine (None follows it):
    callers that pin a frontier LAYOUT — the multi-chip server mesh pins
    interleaved/XLA, parallel/server_mesh.py — must pin the engine to
    match, exactly like ``tree_init``'s ``planar`` knob.
    """
    if use_pallas is None:
        use_pallas = _expand_engine()
    return _expand_share_bits_jit(
        keys, frontier, level, prg.DERIVED_BITS, want_children, use_pallas,
    )


@partial(jax.jit, static_argnames=("derived_bits", "want_children", "use_pallas"))
def _expand_share_bits_jit(keys, frontier, level, derived_bits,
                           want_children=True, use_pallas=False):
    cw = ibdcf.level_cw(keys, level)  # [N,d,2,(4|2)] each
    return _expand_body(cw, frontier, derived_bits, want_children, use_pallas)


def expand_share_bits_from_cw(cw, frontier: Frontier, want_children: bool = True):
    """:func:`expand_share_bits` for callers that hold the level's
    correction words directly instead of a device key batch — the
    HBM-overflow streaming mode (protocol/driver.py): keys live in host
    RAM and only the current level's cw slice rides to the device.

    ``cw`` = (cw_seed [N,d,2,4], cw_bits [N,d,2,2], cw_y [N,d,2,2]).
    """
    return _expand_cw_jit(
        cw, frontier, prg.DERIVED_BITS, want_children, _expand_engine()
    )


@partial(jax.jit, static_argnames=("derived_bits", "want_children", "use_pallas"))
def _expand_cw_jit(cw, frontier, derived_bits, want_children, use_pallas):
    return _expand_body(cw, frontier, derived_bits, want_children, use_pallas)


def _expand_body(cw, frontier, derived_bits, want_children, use_pallas):
    cw_seed, cw_bits, cw_y = cw
    st = frontier.states
    if use_pallas:
        # plane-major fused kernel: pack, flags, and cw broadcast all live
        # INSIDE the pallas_call (ops/expand_pallas.py); the only XLA prep
        # is one tiny per-level cw transpose+pack over [N, d, 2] arrays
        from ..ops import expand_pallas

        d, _, F, N = st.bit.shape  # [d, 2, F, N]
        d2, B = d * 2, F * N
        cws_n = jnp.transpose(
            jnp.asarray(cw_seed, jnp.uint32), (3, 1, 2, 0)
        ).reshape(4, d2, N)
        u32 = lambda a: jnp.transpose(a, (1, 2, 0)).astype(jnp.uint32)
        cwf_n = (
            u32(cw_bits[..., 0]) | (u32(cw_bits[..., 1]) << 1)
            | (u32(cw_y[..., 0]) << 2) | (u32(cw_y[..., 1]) << 3)
        ).reshape(d2, N)
        packed, oseeds, oflags = expand_pallas.expand_packed(
            st.seed.reshape(4, d2, B), st.bit.reshape(d2, B),
            st.y_bit.reshape(d2, B), cws_n, cwf_n, derived_bits,
            want_children,
        )
        packed = packed.reshape(F, N)
        if not want_children:
            return packed, None
        children = PlanarChildren(
            seed=oseeds.reshape(2, 4, d, 2, F, N),
            flags=oflags.reshape(d, 2, F, N),
        )
        return packed, children
    # one fully-batched XLA expansion over (node, client, dim, side)
    shp = st.bit.shape  # [F, N, d, 2]
    s_l, s_r, tau_b, tau_y = prg.expand(st.seed, derived_bits)
    t = st.bit[..., None]
    nb = jnp.where(t, tau_b ^ cw_bits, tau_b)  # cw broadcasts over F
    ny = jnp.where(t, tau_y ^ cw_y, tau_y)
    ny = ny ^ st.y_bit[..., None]
    share = nb ^ ny  # share bit = y ^ t per direction
    pos = jnp.asarray(_bit_positions(share.shape[-3]))  # [d, 2, 2]
    packed = jnp.sum(
        share.astype(jnp.uint32) << pos, axis=(-3, -2, -1), dtype=jnp.uint32
    )  # [F, N] uint32
    if not want_children:
        return packed, None
    # child-state cache: direction axis second-to-last (matching
    # nb/ny's trailing direction axis), seed correction per
    # ibDCF.rs:213-218 (the kernel applies it internally)
    seeds = jnp.stack([s_l, s_r], axis=-2)  # [F, N, d, 2, 2, 4]
    tc = st.bit[..., None, None]  # [F, N, d, 2, 1, 1]
    seeds = jnp.where(tc, seeds ^ cw_seed[..., None, :], seeds)
    children = EvalState(seed=seeds, bit=nb, y_bit=ny)
    return packed, children


def advance_from_children(
    children,
    parent_idx: jax.Array,
    pattern_bits: jax.Array,
    n_alive,
) -> Frontier:
    """Materialize the surviving children from the expand-time cache.

    ``children`` is whatever this level's :func:`expand_share_bits`
    returned — an :class:`EvalState` cache (XLA engine) or a
    :class:`PlanarChildren` (Pallas engine); the cache type selects the
    layout path, so a frontier never mixes layouts mid-crawl.

    parent_idx:   int32[F'] parent slot per surviving child (bucket-padded);
    pattern_bits: bool[F', d] child pattern per survivor;
    n_alive:      number of real entries (rest is padding).

    A gather over the node axis + a per-dim direction select — zero PRG
    work (the expansion already happened in :func:`expand_share_bits`).
    Both keys of a dim take the same direction bit: the interval pair
    walks together (ref: collect.rs:100, ibDCF.rs:120-131).
    """
    return _advance_children_jit(
        children, parent_idx, pattern_bits, n_alive,
        isinstance(children, PlanarChildren),
    )


@partial(jax.jit, static_argnames=("planar",))
def _advance_children_jit(children, parent_idx, pattern_bits, n_alive,
                          planar=False):
    if planar:
        # children: PlanarChildren(seed [2, 4, d, 2, F, N], flags [d, 2, F, N])
        seed_g = jnp.take(children.seed, parent_idx, axis=4)
        fl = jnp.take(children.flags, parent_idx, axis=2)  # [d, 2, F', N]
        one = jnp.uint32(1)
        bl, br = fl & one, (fl >> 1) & one
        yl, yr = (fl >> 2) & one, (fl >> 3) & one
        # per-plane direction: pattern bit of the dim, same for both sides
        dirp = jnp.transpose(pattern_bits)[:, None, :, None]  # [d, 1, F', 1]
        states = EvalState(
            seed=jnp.where(dirp[None], seed_g[1], seed_g[0]),
            bit=jnp.where(dirp, br, bl) != 0,
            y_bit=jnp.where(dirp, yr, yl) != 0,
        )
    else:
        dirb = pattern_bits[:, None, :, None]  # [F',1,d,1] -> bcast [F',N,d,2]
        ch = jax.tree.map(lambda a: a[parent_idx], children)  # [F', N, d, 2, 2, ..]
        states = EvalState(
            seed=jnp.where(dirb[..., None], ch.seed[..., 1, :], ch.seed[..., 0, :]),
            bit=jnp.where(dirb, ch.bit[..., 1], ch.bit[..., 0]),
            y_bit=jnp.where(dirb, ch.y_bit[..., 1], ch.y_bit[..., 0]),
        )
    alive = jnp.arange(parent_idx.shape[0]) < n_alive
    return Frontier(states=states, alive=alive)


@jax.jit
def counts_by_pattern(
    packed_self: jax.Array,
    packed_peer: jax.Array,
    masks: jax.Array,
    alive_keys: jax.Array,
    alive_nodes: jax.Array,
) -> jax.Array:
    """uint32[F, 2^d] per-child candidate counts.

    Membership of client i's ball in child (f, c) ⇔ the two servers' share
    bits agree on every compared position: ``(p0 ^ p1) & masks[c] == 0``
    (the plaintext of the GC equality test, ref: equalitytest.rs:130-146,
    reconstructed as the leader would, collect.rs:945-964).  Dead clients
    and dead nodes contribute zero (liveness gate, ref: collect.rs:495).
    """
    diff = packed_self ^ packed_peer  # [F, N]
    eq = (diff[:, :, None] & masks[None, None, :]) == 0  # [F, N, 2^d]
    eq = eq & alive_keys[None, :, None] & alive_nodes[:, None, None]
    return jnp.sum(eq, axis=1, dtype=jnp.uint32)  # [F, 2^d]


def to_interleaved(states: EvalState) -> EvalState:
    """Plane-major frontier state (seed [4, d, 2, F, N], bits [d, 2, F, N])
    -> interleaved ([F, N, d, 2, 4] / [F, N, d, 2]).  The single source of
    truth for the engine-edge transposes (used by :func:`advance` and the
    checkpoint restore's cross-engine conversion)."""
    return EvalState(
        seed=jnp.transpose(states.seed, (3, 4, 1, 2, 0)),
        bit=jnp.transpose(states.bit, (2, 3, 0, 1)),
        y_bit=jnp.transpose(states.y_bit, (2, 3, 0, 1)),
    )


def to_planar(states: EvalState) -> EvalState:
    """Inverse of :func:`to_interleaved` (the bit transpose is involutive;
    the seed one is its inverse permutation)."""
    return EvalState(
        seed=jnp.transpose(states.seed, (4, 2, 3, 0, 1)),
        bit=jnp.transpose(states.bit, (2, 3, 0, 1)),
        y_bit=jnp.transpose(states.y_bit, (2, 3, 0, 1)),
    )


def advance(
    keys: IbDcfKeyBatch,
    frontier: Frontier,
    level,
    parent_idx: jax.Array,
    pattern_bits: jax.Array,
    n_alive: jax.Array,
    use_pallas: bool | None = None,
) -> Frontier:
    """Re-expanding advance: the fallback for callers WITHOUT a child-state
    cache from :func:`expand_share_bits` (the crawl paths all have one and
    use :func:`advance_from_children` instead — zero PRG work).

    parent_idx:   int32[F'] parent slot per surviving child (padded);
    pattern_bits: bool[F', d] child pattern per survivor;
    n_alive:      number of real entries (rest is padding).

    Gathers the parents' states and advances one level with the pattern's
    per-dim direction (both keys of a dim take the same bit — the interval
    pair walks together, ref: collect.rs:100, ibDCF.rs:120-131).

    Layout note: the eval recurrence wants interleaved seeds; under the
    planar engine this rare path converts at the edges (tiny next to the
    PRG work it is about to redo).  ``use_pallas`` overrides the process
    engine (None follows it) for callers that pin a layout — see
    :func:`expand_share_bits`.
    """
    planar = _expand_engine() if use_pallas is None else use_pallas
    if planar:  # plane-major [4,d,2,F,N]/[d,2,F,N] -> interleaved
        frontier = frontier._replace(states=to_interleaved(frontier.states))
    out = _advance_jit(
        keys, frontier, level, parent_idx, pattern_bits, n_alive,
        prg.DERIVED_BITS,
    )
    if planar:
        out = out._replace(states=to_planar(out.states))
    return out


@partial(jax.jit, static_argnames=("derived_bits",))
def _advance_jit(keys, frontier, level, parent_idx, pattern_bits, n_alive,
                 derived_bits):
    cw = ibdcf.level_cw(keys, level)
    return _advance_body(cw, frontier, parent_idx, pattern_bits, n_alive,
                         derived_bits)


def _advance_body(cw, frontier, parent_idx, pattern_bits, n_alive, derived_bits):
    st = frontier.states
    parents = jax.tree.map(lambda a: a[parent_idx], st)  # [F', N, d, 2]
    direction = jnp.broadcast_to(
        pattern_bits[:, None, :, None], parents.bit.shape
    )  # child pattern bit of each dim, same for both keys of the dim
    states = ibdcf._eval_bit_jit(cw, parents, direction, derived_bits)
    f_max = parent_idx.shape[0]
    alive = jnp.arange(f_max) < n_alive
    return Frontier(states=states, alive=alive)


def advance_from_cw(cw, frontier: Frontier, parent_idx, pattern_bits, n_alive,
                    node_chunk: int | None = None) -> Frontier:
    """Re-expanding advance from an explicit cw slice — the streaming-mode
    twin of :func:`advance` (see :func:`expand_share_bits_from_cw`): the
    caller already uploaded this level's correction words, and the crawl
    runs WITHOUT a child cache (at wide frontiers the cache's
    ``F x N x d x 2`` x 36 B footprint is what breaks the HBM budget, so
    streaming crawls re-expand the survivors instead).

    Under the planar engine the whole step stays plane-major (gather
    surviving parents -> expand kernel -> direction select) — no layout
    transposes ever touch the multi-GB frontier — and ``node_chunk``
    bounds the transient: the child bucket is computed ``node_chunk``
    parent slots at a time inside one jit (fori_loop + in-place dynamic
    updates), so peak HBM is old frontier + new frontier + ONE chunk's
    expansion instead of + a full-bucket child cache.  The parent
    frontier is donated where XLA can use it.
    """
    F2 = parent_idx.shape[0]
    if _expand_engine():
        c = F2 if node_chunk is None else min(F2, node_chunk)
        if F2 % c:
            c = F2  # chunk must tile the bucket (both are powers of two)
        return _advance_cw_planar_jit(
            cw, frontier, parent_idx, pattern_bits, n_alive,
            prg.DERIVED_BITS, c,
        )
    return _advance_cw_jit(
        cw, frontier, parent_idx, pattern_bits, n_alive, prg.DERIVED_BITS
    )


@partial(jax.jit, static_argnames=("derived_bits",), donate_argnums=(1,))
def _advance_cw_jit(cw, frontier, parent_idx, pattern_bits, n_alive,
                    derived_bits):
    return _advance_body(cw, frontier, parent_idx, pattern_bits, n_alive,
                         derived_bits)


@partial(jax.jit, static_argnames=("derived_bits", "chunk"), donate_argnums=(1,))
def _advance_cw_planar_jit(cw, frontier, parent_idx, pattern_bits, n_alive,
                           derived_bits, chunk):
    st = frontier.states  # plane-major [4,d,2,F,N] / [d,2,F,N]
    F2 = parent_idx.shape[0]
    d = st.bit.shape[0]
    N = st.bit.shape[-1]

    def advance_slots(pidx, pbits):
        c = pidx.shape[0]
        par = EvalState(
            seed=jnp.take(st.seed, pidx, axis=3),
            bit=jnp.take(st.bit, pidx, axis=2),
            y_bit=jnp.take(st.y_bit, pidx, axis=2),
        )
        gf = Frontier(states=par, alive=jnp.ones(c, bool))
        _, children = _expand_body(cw, gf, derived_bits, True, True)
        return _advance_children_jit(
            children, jnp.arange(c), pbits, c, planar=True
        ).states

    if chunk == F2:
        states = advance_slots(parent_idx, pattern_bits)
    else:
        def body(i, acc):
            pidx = jax.lax.dynamic_slice_in_dim(parent_idx, i * chunk, chunk)
            pbits = jax.lax.dynamic_slice_in_dim(
                pattern_bits, i * chunk, chunk
            )
            ns = advance_slots(pidx, pbits)
            upd = lambda a, u, ax: jax.lax.dynamic_update_slice_in_dim(
                a, u, i * chunk, axis=ax
            )
            return EvalState(
                seed=upd(acc.seed, ns.seed, 3),
                bit=upd(acc.bit, ns.bit, 2),
                y_bit=upd(acc.y_bit, ns.y_bit, 2),
            )

        init = EvalState(
            seed=jnp.zeros((4, d, 2, F2, N), jnp.uint32),
            bit=jnp.zeros((d, 2, F2, N), bool),
            y_bit=jnp.zeros((d, 2, F2, N), bool),
        )
        states = jax.lax.fori_loop(0, F2 // chunk, body, init)
    alive = jnp.arange(F2) < n_alive
    return Frontier(states=states, alive=alive)


# ---------------------------------------------------------------------------
# Mid-level sharding (data-plane fault tolerance)
#
# A level's crawl can be split into deterministic spans over the frontier
# NODE axis: each span is its own RPC verb with its own request id, so a
# mid-level fault re-runs only the lost spans (protocol/leader_rpc.py's
# shard retry) instead of the whole level.  Spans must be identical on
# the leader and both servers — they are pure functions of (f_bucket,
# shard_nodes), both public.
# ---------------------------------------------------------------------------


def shard_spans(f_bucket: int, shard_nodes: int) -> list:
    """Deterministic node-axis spans ``[(lo, hi), ...]`` covering
    ``[0, f_bucket)``.  ``shard_nodes <= 0`` (the default) disables
    sharding: one span, the whole bucket."""
    if shard_nodes <= 0 or f_bucket <= shard_nodes:
        return [(0, f_bucket)]
    return [
        (lo, min(lo + shard_nodes, f_bucket))
        for lo in range(0, f_bucket, shard_nodes)
    ]


def frontier_slice(
    frontier: Frontier, lo: int, hi: int, planar: bool | None = None
) -> Frontier:
    """One shard's view of the frontier: node slots ``[lo, hi)`` of the
    states and the alive mask, layout-aware (the node axis sits at
    position 3/2 in the plane-major layout, 0 in the interleaved one)."""
    if planar is None:
        planar = _expand_engine()
    st = frontier.states
    if planar:
        states = EvalState(
            seed=st.seed[:, :, :, lo:hi],
            bit=st.bit[:, :, lo:hi],
            y_bit=st.y_bit[:, :, lo:hi],
        )
    else:
        states = jax.tree.map(lambda a: a[lo:hi], st)
    return Frontier(states=states, alive=frontier.alive[lo:hi])


def children_cat(parts: list):
    """Reassemble a full-level child-state cache from per-shard caches.

    ``parts``: list of ``(lo, children)`` in any order; children are
    whatever the engine's :func:`expand_share_bits` returned for each
    shard (all the same type).  Concatenates along the node axis in
    ``lo`` order — the exact inverse of :func:`frontier_slice`."""
    parts = [c for _, c in sorted(parts, key=lambda t: t[0])]
    if isinstance(parts[0], PlanarChildren):
        return PlanarChildren(
            seed=jnp.concatenate([p.seed for p in parts], axis=4),
            flags=jnp.concatenate([p.flags for p in parts], axis=2),
        )
    return jax.tree.map(lambda *a: jnp.concatenate(a, axis=0), *parts)


# ---------------------------------------------------------------------------
# Host-side compaction helper (leader-side prune bookkeeping)
# ---------------------------------------------------------------------------


def compact_survivors(keep: np.ndarray, f_max: int, min_bucket: int = 1):
    """keep: bool[F, 2^d] -> (parent_idx int32[Fb], pattern int32[Fb],
    n_alive) zero-padded to the survivor bucket ``Fb = bucket_for(...)``.
    Raises if survivors exceed the ``f_max`` cap — the bucketed-frontier
    equivalent of the reference's unbounded Vec growth."""
    f, c = np.nonzero(keep)
    fb = bucket_for(len(f), f_max, min_bucket)
    parent = np.zeros(fb, np.int32)
    pattern = np.zeros(fb, np.int32)
    parent[: len(f)] = f
    pattern[: len(f)] = c
    return parent, pattern, len(f)


def pattern_to_bits(pattern: np.ndarray, d: int) -> np.ndarray:
    """int32[F'] child pattern ids -> bool[F', d] per-dim direction bits
    (bit j = (c >> j) & 1, ref: lib.rs:125-129)."""
    return ((pattern[:, None] >> np.arange(d)[None]) & 1).astype(bool)


# ---------------------------------------------------------------------------
# Radix-2^k level fusion: crawl ``radix`` bits per round trip.
#
# A fused level expands every frontier node by all 2^(radix·d) child
# patterns at once.  Pattern ids are STEP-MAJOR: c = Σ_t step_t << (t·d)
# with step_t the familiar per-dim pattern of bit-level (base + t), so
# dim j's direction at step t is ``(c >> (t·d + j)) & 1`` — at radix 1
# this is exactly the existing child order (lib.rs:125-129), and the
# fused child of node f sits at ``f·2^(radix·d) + c`` like before.
#
# The packed share-bit word generalizes the ``j*4 + s*2 + r`` layout: per
# (dim j, side s) it stores the share bit of EVERY node in the depth-
# ``radix`` subtree — depth i's 2^i nodes at offset ``2^i - 2``, node
# indices little-endian in step order (the child of node m via direction
# r at depth i+1 is ``m | r << i``) — at position
#
#     j·2T + s·T + (2^i - 2) + node_idx,      T = radix_subtree_nodes(radix)
#
# which reduces to the radix-1 layout verbatim (T = 2).  Membership of a
# fused child is the conjunction of its per-depth memberships (the
# interval predicate is monotone along a path), so equality over the
# concatenated per-depth strings — what pattern_masks_radix compares —
# counts exactly what radix-1 would count at the deepest level, and the
# word still fits u32 at the supported (d, radix) pairs (check_radix).
# Radix > 1 pins the interleaved/XLA engine: the child cache is a plain
# EvalState with a 2^radix-wide node axis (the radix-1 cache's direction
# axis, generalized), so sharding/concat/advance reuse the same paths.
# ---------------------------------------------------------------------------


def _radix_positions(d: int, radix: int, step: int) -> np.ndarray:
    """uint32[d, 2, 2^(step+1)] — packed-bit positions of every
    depth-(step+1) subtree node per (dim, side).  Reduces to
    :func:`_bit_positions` at (radix, step) = (1, 0)."""
    T = radix_subtree_nodes(radix)
    j = np.arange(d)[:, None, None]
    s = np.arange(2)[None, :, None]
    m = np.arange(2 << step)[None, None, :]
    return (j * (2 * T) + s * T + ((2 << step) - 2) + m).astype(np.uint32)


@lru_cache(maxsize=None)
def pattern_masks_radix(d: int, radix: int) -> np.ndarray:
    """uint32[2^(radix·d)] — for fused child pattern c, the packed-bit
    positions a membership test compares: both sides of every dim at
    EVERY depth 1..radix along c's path.  ``pattern_masks`` at radix 1."""
    if radix == 1:
        return pattern_masks(d)
    check_radix(d, radix)
    T = radix_subtree_nodes(radix)
    masks = []
    for c in range(1 << (radix * d)):
        m = np.uint32(0)
        node = [0] * d  # per-dim subtree node index along c's path
        for t in range(radix):
            base = (2 << t) - 2
            for j in range(d):
                r = (c >> (t * d + j)) & 1
                node[j] |= r << t
                p = np.uint32(j * 2 * T + base + node[j])
                m |= (np.uint32(1) << p) | (np.uint32(1) << (p + np.uint32(T)))
        masks.append(m)
    out = np.array(masks, dtype=np.uint32)
    out.setflags(write=False)
    return out


def expand_share_bits_radix(
    keys: IbDcfKeyBatch, frontier: Frontier, level, radix: int,
    want_children: bool = True, use_pallas: bool | None = None,
):
    """:func:`expand_share_bits` crawling ``radix`` bit-levels at once:
    packed uint32[F, N] carries the share bits of the whole depth-
    ``radix`` subtree per (node, client), and ``children`` is an
    :class:`EvalState` cache over ``[F, N, d, 2, 2^radix, …]`` (trailing
    node axis = the subtree leaves) for :func:`advance_from_children_radix`.

    ``level`` is the BASE bit-level of the fused step (the key batch's
    correction words at ``level .. level+radix-1`` are consumed); it may
    be traced, so one compiled program serves every fused level of a
    crawl.  ``radix`` is the ACTUAL width of this step — the tail level
    of a crawl whose data_len is not a radix multiple passes its shorter
    remainder.  Radix 1 delegates to :func:`expand_share_bits` verbatim
    (same compiled programs, bit-identical crawl)."""
    if radix == 1:
        return expand_share_bits(
            keys, frontier, level,
            want_children=want_children, use_pallas=use_pallas,
        )
    if use_pallas:
        raise ValueError(
            "radix > 1 pins the interleaved/XLA expand engine — the "
            "plane-major Pallas layout has no fused multi-level kernel"
        )
    return _expand_radix_jit(
        keys, frontier, level, prg.DERIVED_BITS, radix, want_children
    )


@partial(jax.jit, static_argnames=("derived_bits", "radix", "want_children"))
def _expand_radix_jit(keys, frontier, level, derived_bits, radix,
                      want_children):
    st = frontier.states  # interleaved [F, N, d, 2, …]
    d = st.bit.shape[-2]
    # walk the subtree breadth-first: ``cur`` holds ALL of depth t's
    # nodes on a trailing node axis M = 2^t, each step expanding every
    # node into both children (new index = direction·M + m — the
    # little-endian step order the mask/advance tables assume)
    cur = EvalState(
        seed=st.seed[..., None, :], bit=st.bit[..., None],
        y_bit=st.y_bit[..., None],
    )
    packed = jnp.zeros(st.bit.shape[:2], jnp.uint32)  # [F, N]
    for t in range(radix):
        cw_seed, cw_bits, cw_y = ibdcf.level_cw(keys, level + t)  # [N,d,2,…]
        s_l, s_r, tau_b, tau_y = prg.expand(cur.seed, derived_bits)
        tb = cur.bit[..., None]  # [F, N, d, 2, M, 1]
        nb = jnp.where(tb, tau_b ^ cw_bits[None, :, :, :, None, :], tau_b)
        ny = jnp.where(tb, tau_y ^ cw_y[None, :, :, :, None, :], tau_y)
        ny = ny ^ cur.y_bit[..., None]
        share = nb ^ ny  # [F, N, d, 2, M, 2] (trailing direction axis)
        M = share.shape[-2]
        swap = lambda a: jnp.swapaxes(a, -1, -2).reshape(
            a.shape[:-2] + (2 * M,)
        )  # node index = direction·M + m
        pos = jnp.asarray(_radix_positions(d, radix, t))  # [d, 2, 2M]
        packed = packed | jnp.sum(
            swap(share).astype(jnp.uint32) << pos,
            axis=(-3, -2, -1), dtype=jnp.uint32,
        )
        seeds = jnp.stack([s_l, s_r], axis=-3)  # [F, N, d, 2, 2, M, 4]
        tc = cur.bit[..., None, :, None]  # [F, N, d, 2, 1, M, 1]
        seeds = jnp.where(
            tc, seeds ^ cw_seed[None, :, :, :, None, None, :], seeds
        )
        cur = EvalState(
            seed=seeds.reshape(seeds.shape[:-3] + (2 * M, 4)),
            bit=swap(nb), y_bit=swap(ny),
        )
    return packed, (cur if want_children else None)


def advance_from_children_radix(
    children, parent_idx: jax.Array, pattern_bits: jax.Array, n_alive,
    radix: int,
) -> Frontier:
    """:func:`advance_from_children` for a fused level: gather the
    surviving fused children from the radix cache's subtree-leaf axis.

    pattern_bits: bool[F', radix, d] step-major fused patterns
    (:func:`pattern_to_bits_radix`).  Radix 1 delegates to the existing
    advance (same compiled programs)."""
    if radix == 1:
        return advance_from_children(
            children, parent_idx, pattern_bits[:, 0, :], n_alive
        )
    return _advance_children_radix_jit(
        children, parent_idx, pattern_bits, n_alive
    )


@jax.jit
def _advance_children_radix_jit(children, parent_idx, pattern_bits, n_alive):
    r = pattern_bits.shape[1]
    # per-dim subtree-leaf index, little-endian in step order
    w = (1 << jnp.arange(r, dtype=jnp.int32))[None, :, None]
    idx = jnp.sum(pattern_bits.astype(jnp.int32) * w, axis=1)  # [F', d]
    ch = jax.tree.map(lambda a: a[parent_idx], children)  # [F', N, d, 2, R, …]
    i5 = idx[:, None, :, None, None]
    states = EvalState(
        seed=jnp.take_along_axis(
            ch.seed, i5[..., None], axis=-2
        )[..., 0, :],
        bit=jnp.take_along_axis(ch.bit, i5, axis=-1)[..., 0],
        y_bit=jnp.take_along_axis(ch.y_bit, i5, axis=-1)[..., 0],
    )
    alive = jnp.arange(parent_idx.shape[0]) < n_alive
    return Frontier(states=states, alive=alive)


def pattern_to_bits_radix(pattern: np.ndarray, d: int, radix: int) -> np.ndarray:
    """int[F'] fused child ids -> bool[F', radix, d] per-step direction
    bits (dim j at step t = ``(c >> (t·d + j)) & 1`` — step-major).
    ``pattern_to_bits`` with a leading step axis at radix 1."""
    shift = np.arange(radix)[None, :, None] * d + np.arange(d)[None, None, :]
    return ((np.asarray(pattern)[:, None, None] >> shift) & 1).astype(bool)


@lru_cache(maxsize=None)
def radix_pattern_order(d: int, radix: int) -> np.ndarray:
    """int32[2^(radix*d)] — step-major fused pattern ids listed in the
    k=1 crawl's survivor VISIT order.  The radix-1 crawl emits a level's
    survivors sorted by per-level pattern with EARLIER levels most
    significant; the step-major fused id c = Σ_t p_t·2^(t·d) sorts the
    LAST step most significant, so a fused prune walked in ascending-c
    order would list the same survivor set in a different order (and,
    under f_max truncation, could keep a different subset).  Walking
    fused children as ``order[rank]`` with
    rank = Σ_t p_t·2^((radix−1−t)·d) restores the k=1 order exactly.
    Identity at radix=1."""
    C = 1 << (radix * d)
    mask = (1 << d) - 1
    out = np.empty(C, np.int32)
    for c in range(C):
        rank = 0
        for t in range(radix):
            rank |= ((c >> (t * d)) & mask) << ((radix - 1 - t) * d)
        out[rank] = c
    return out
