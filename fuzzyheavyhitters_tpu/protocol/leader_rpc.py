"""RPC leader: drives two collector servers over the control plane.

The process-level twin of protocol/driver.Leader, speaking the 8-verb RPC
(ref: src/bin/leader.rs:185-297): batched key upload, level loop with
``tree_crawl`` → reconstruct ``v0 - v1`` → threshold → fused prune/advance,
the F255 last level, and final heavy-hitter reconstruction with the same
``max(1, threshold·nreqs)`` floor (leader.rs:193-194, 245-246).
"""

from __future__ import annotations

import asyncio

import jax
import numpy as np

from .. import obs as obsmod
from ..obs import metrics as obsmetrics
from ..ops.fields import F255, FE62
from ..ops.ibdcf import IbDcfKeyBatch
from ..utils.config import Config
from . import collect
from .driver import CrawlResult
from .rpc import CollectorClient


def _key_chunk(keys: IbDcfKeyBatch, sl: slice):
    return tuple(np.asarray(leaf)[sl] for leaf in keys)


class RpcLeader:
    def __init__(
        self,
        cfg: Config,
        client0: CollectorClient,
        client1: CollectorClient,
        min_bucket: int = 1,
    ):
        self.cfg = cfg
        self.c0, self.c1 = client0, client1
        self.min_bucket = min_bucket  # pin >1 only on compile-bound hosts
        self.paths: np.ndarray | None = None
        self.n_nodes = 0
        self.has_sketch = False
        # leader-side telemetry: level spans (the heartbeat names the
        # level a wedged crawl died in) + survivor gauges
        self.obs = obsmetrics.Registry("leader")
        # the clients predate this registry (connect() runs first); rebind
        # their control-plane byte accounting so control_bytes_* land on
        # the leader's registry, not the process default
        client0.obs = client1.obs = self.obs

    async def _both(self, verb: str, req=None):
        return await asyncio.gather(self.c0.call(verb, req), self.c1.call(verb, req))

    async def upload_keys(
        self,
        keys0: IbDcfKeyBatch,
        keys1: IbDcfKeyBatch,
        sketch0=None,
        sketch1=None,
    ):
        """Batched async key upload with a ROLLING in-flight window (ref:
        leader.rs:340-364: 1000 addkey batches in flight, refilled as each
        completes — not drained in bursts: a stop-and-wait gather leaves
        the pipe empty while the slowest request of each burst finishes).
        Optional sketch key batches ride in the same requests
        (malicious-secure mode)."""
        n = np.asarray(keys0.cw_seed).shape[0]
        bs = max(1, self.cfg.addkey_batch_size)
        self.has_sketch = sketch0 is not None

        def sk_chunk(sk, sl):
            if sk is None:
                return None
            return [np.asarray(x)[sl] for x in jax.tree.leaves(sk)]

        window = 256
        sem = asyncio.Semaphore(window)

        async def send_one(client, keys, sketch, sl):
            # the request dict is built INSIDE the window so at most
            # ``window`` chunks are materialized/pickled at a time
            async with sem:
                await client.call(
                    "add_keys",
                    {"keys": _key_chunk(keys, sl), "sketch": sk_chunk(sketch, sl)},
                )

        with self.obs.span("upload_keys"):
            tasks = []
            for lo in range(0, n, bs):
                sl = slice(lo, min(lo + bs, n))
                tasks.append(send_one(self.c0, keys0, sketch0, sl))
                tasks.append(send_one(self.c1, keys1, sketch1, sl))
            await asyncio.gather(*tasks)
        self.obs.count("keys_uploaded", n)

    async def _run_one_level(self, level: int, nreqs: int, thresh: int):
        """One crawl->reconstruct->threshold->prune round under a level
        span (the heartbeat names this level while it runs).  Returns
        ``(counts_kept, alive_after_verify)`` with ``counts_kept`` None
        when the crawl died out at this level."""
        cfg = self.cfg
        d, L = cfg.n_dims, cfg.data_len
        last = level == L - 1
        alive_after_verify = None
        if self.has_sketch and level != 1:
            # malicious-security gate first, so failing clients'
            # liveness flags flip before this level's counts are
            # taken.  Level 0 runs the FULL depth-1 check (both root
            # children per dim) — the first threshold never sees
            # unverified counts; levels >= 2 verify the
            # frontier-following shares stored by the previous prune.
            # The depth-1 frontier re-verify (level 1) is skipped: its
            # triples were consumed by the level-0 full check (see
            # rpc.sketch_verify / sketch.py scope note).
            a0, _ = await self._both("sketch_verify", {"level": level})
            alive_after_verify = np.asarray(a0)
        verb = "tree_crawl_last" if last else "tree_crawl"
        # alternate the garbling server per level (the reference's
        # gc_sender flip, leader.rs:204-210) to split garbling cost
        s0, s1 = await self._both(
            verb, {"level": level, "garbler": level % 2}
        )
        if last:
            v = np.asarray(F255.sub(s0, s1))  # leader-side reconstruct
            counts = v[..., 0].astype(np.uint32)  # counts < 2^32 by def
            if np.any(v[..., 1:]):  # boundary check: must survive -O
                raise RuntimeError("non-count residue in F255 share")
        else:
            v = np.asarray(FE62.canon(FE62.sub(s0, s1)))
            if np.any(v > nreqs):  # e.g. a share-sign/role mismatch
                raise RuntimeError("count reconstruction out of range")
            counts = v.astype(np.uint32)
        keep = counts >= thresh
        keep[self.n_nodes :, :] = False
        parent, pattern, n_alive = collect.compact_survivors(
            keep, cfg.f_max, self.min_bucket
        )
        pat_bits = collect.pattern_to_bits(pattern, d)
        self.obs.gauge("survivors", n_alive, level=level)
        if n_alive == 0:
            return None, alive_after_verify
        if last:
            await self._both(
                "tree_prune_last",
                {
                    "parent_idx": parent,
                    "pattern_bits": pat_bits,
                    "n_alive": n_alive,
                },
            )
        else:
            await self._both(
                "tree_prune",
                {
                    "level": level,
                    "parent_idx": parent,
                    "pattern_bits": pat_bits,
                    "n_alive": n_alive,
                },
            )
        new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + 1), bool)
        for i in range(n_alive):
            new_paths[i, :, :-1] = self.paths[parent[i]]
            new_paths[i, :, -1] = pat_bits[i]
        self.paths = new_paths
        self.n_nodes = n_alive
        return counts[parent[:n_alive], pattern[:n_alive]], alive_after_verify

    async def run(self, nreqs: int) -> CrawlResult:
        cfg = self.cfg
        d, L = cfg.n_dims, cfg.data_len
        await self._both("tree_init", {"root_bucket": self.min_bucket})
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        thresh = max(1, int(cfg.threshold * nreqs))
        counts_kept = np.zeros(0, np.uint32)
        alive_before_leaf = None  # liveness after the latest verify
        for level in range(L):
            with self.obs.span("level", level=level):
                counts_kept, alive = await self._run_one_level(
                    level, nreqs, thresh
                )
            if alive is not None:
                alive_before_leaf = alive
            if counts_kept is None:
                return CrawlResult(
                    paths=np.zeros((0, d, level + 1), bool),
                    counts=np.zeros(0, np.uint32),
                )
        if self.has_sketch and L > 1:
            # final F255 leaf-payload check (surviving leaves; counts for
            # this collection are already taken — the verdict gates the
            # liveness flags for any further use and flags forged leaves).
            # Warn only on NEW exclusions relative to the latest verify:
            # a client caught mid-tree stays excluded and must not read
            # as a leaf forgery.  data_len == 1 skips this call entirely:
            # there the level-0 full check IS the leaf check, and a second
            # opening of triples_last under a fresh challenge would leak
            # <r - r', x> (see rpc.sketch_verify).
            a0, _ = await self._both("sketch_verify", {"level": L})
            prev = (
                alive_before_leaf
                if alive_before_leaf is not None
                else np.ones_like(np.asarray(a0))
            )
            if np.any(prev & ~np.asarray(a0)):
                obsmod.emit(
                    "sketch.leaf_forgery",
                    severity="warn",
                    new_exclusions=int(np.sum(prev & ~np.asarray(a0))),
                )
        # final reconstruction from re-served leaf shares: v0 - v1 per
        # surviving leaf (ref: collect.rs:993-1029 final_shares/final_values;
        # the crawl-time counts are only the pruning signal)
        f0, f1 = await self._both("final_shares")
        v = np.asarray(F255.sub(f0["shares"], f1["shares"]))
        final_counts = v[..., 0].astype(np.uint32)
        if np.any(v[..., 1:]) or not np.array_equal(final_counts, counts_kept):
            raise RuntimeError("final share reconstruction mismatch")
        return CrawlResult(paths=self.paths, counts=final_counts)
