"""RPC leader: drives two collector servers over the control plane.

The process-level twin of protocol/driver.Leader, speaking the 8-verb RPC
(ref: src/bin/leader.rs:185-297): batched key upload, level loop with
``tree_crawl`` → reconstruct ``v0 - v1`` → threshold → fused prune/advance,
the F255 last level, and final heavy-hitter reconstruction with the same
``max(1, threshold·nreqs)`` floor (leader.rs:193-194, 245-246).
"""

from __future__ import annotations

import asyncio
import collections as _collections
import contextlib
import time

import jax
import numpy as np

from .. import obs as obsmod
from ..obs import metrics as obsmetrics
from ..obs import trace as obstrace
from ..ops.fields import F255, FE62
from ..ops.ibdcf import IbDcfKeyBatch
from ..resilience import policy as respolicy
from ..utils import guards
from ..utils.config import Config
from . import collect
from .driver import CrawlResult
from .rpc import CollectorClient, ServerRestartedError


def _key_chunk(keys: IbDcfKeyBatch, sl: slice):
    return tuple(np.asarray(leaf)[sl] for leaf in keys)


class RpcLeader:
    def __init__(
        self,
        cfg: Config,
        client0: CollectorClient,
        client1: CollectorClient,
        min_bucket: int = 1,
    ):
        self.cfg = cfg
        self.c0, self.c1 = client0, client1
        self.min_bucket = min_bucket  # pin >1 only on compile-bound hosts
        self.paths: np.ndarray | None = None
        self.n_nodes = 0
        self.has_sketch = False
        self._f_bucket = min_bucket  # current frontier bucket (shard plan)
        self._boot_ids: dict = {}  # last known server boot ids
        self._mesh_faults: dict = {}  # last seen mesh.faults counts
        # leader-side telemetry: level spans (the heartbeat names the
        # level a wedged crawl died in) + survivor gauges.  A leader
        # driving a non-default collection names it in the registry so
        # the heartbeat/report show the (session, phase) pair.
        self.collection = getattr(client0, "collection", None) or "default"
        self.obs = obsmetrics.Registry(
            "leader" if self.collection == "default"
            else f"leader:{self.collection}"
        )
        # the clients predate this registry (connect() runs first); rebind
        # their control-plane byte accounting so control_bytes_* land on
        # the leader's registry, not the process default
        client0.obs = client1.obs = self.obs

    @staticmethod
    async def _all(*coros):
        """Gather with cancel-on-first-failure.  Plain ``asyncio.gather``
        leaves the other awaitables RUNNING when one raises — for this
        client that means an orphaned call still replaying its verb while
        the supervisor rolls both servers back, and a replayed
        never-executed ``tree_prune``/``add_keys`` landing AFTER a
        ``tree_restore``/``reset`` would corrupt the restored state.
        Cancelling the siblings kills their replay loops; anything that
        already executed server-side is answered from the dedup cache."""
        tasks = [asyncio.ensure_future(c) for c in coros]
        # fhh-lint: disable=unbounded-await (every child is a client call
        # bounded by its own per-verb wall-clock budget; a second timeout
        # here would race the real one)
        done, pending = await asyncio.wait(
            tasks, return_when=asyncio.FIRST_EXCEPTION
        )
        # retrieve EVERY done task's exception (not just the first): an
        # unretrieved sibling failure would be dumped on the event loop
        # as "Task exception was never retrieved" noise at GC time
        errs = [(t, t.exception()) for t in done]
        failed = next((t for t, e in errs if e is not None), None)
        if failed is not None:
            for t in pending:
                t.cancel()
            for t in pending:
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # fhh-lint: disable=broad-except (cancellation sweep: sibling errors are subsumed by the first failure, re-raised below)
                    pass
            raise failed.exception()
        return [t.result() for t in tasks]

    async def _both(self, verb: str, req=None):
        return await self._all(self.c0.call(verb, req), self.c1.call(verb, req))

    async def upload_keys(
        self,
        keys0: IbDcfKeyBatch,
        keys1: IbDcfKeyBatch,
        sketch0=None,
        sketch1=None,
        which: int | None = None,
    ):
        """Batched async key upload with a ROLLING in-flight window (ref:
        leader.rs:340-364: 1000 addkey batches in flight, refilled as each
        completes — not drained in bursts: a stop-and-wait gather leaves
        the pipe empty while the slowest request of each burst finishes).
        Optional sketch key batches ride in the same requests
        (malicious-secure mode).  ``which`` (0 or 1) uploads to ONE
        server only — the recovery path re-seeding a restarted server."""
        n = np.asarray(keys0.cw_seed).shape[0]
        bs = max(1, self.cfg.addkey_batch_size)
        if which is None:
            self.has_sketch = sketch0 is not None

        def sk_chunk(sk, sl):
            if sk is None:
                return None
            return [np.asarray(x)[sl] for x in jax.tree.leaves(sk)]

        window = 256
        sem = asyncio.Semaphore(window)

        async def send_one(client, keys, sketch, sl):
            # the request dict is built INSIDE the window so at most
            # ``window`` chunks are materialized/pickled at a time
            async with sem:
                await client.call(
                    "add_keys",
                    {"keys": _key_chunk(keys, sl), "sketch": sk_chunk(sketch, sl)},
                )

        with self.obs.span("upload_keys"):
            tasks = []
            for lo in range(0, n, bs):
                sl = slice(lo, min(lo + bs, n))
                if which in (None, 0):
                    tasks.append(send_one(self.c0, keys0, sketch0, sl))
                if which in (None, 1):
                    tasks.append(send_one(self.c1, keys1, sketch1, sl))
            # cancel-on-first-failure: an orphaned add_keys replay landing
            # after a recovery reset would append a duplicate key chunk
            await self._all(*tasks)
        self.obs.count("keys_uploaded", n)

    async def warmup(self, f_buckets=None) -> dict:
        """Ask both servers to pre-compile the per-``f_bucket`` crawl
        programs (rpc.CollectorServer.warmup) so bucket recompiles land
        BEFORE measured crawl time — with ``FHH_COMPILE_CACHE`` set the
        compiles also persist across processes.  Default bucket plan:
        powers of two from ``min_bucket`` up to ``cfg.f_max`` (the exact
        ladder ``collect.bucket_for`` walks as the frontier grows).
        Call after ``upload_keys`` (the servers need the key shapes);
        any time before or during the crawl is safe — warmup touches no
        protocol state."""
        if f_buckets is None:
            f_buckets, b = [], max(1, self.min_bucket)
            while b <= self.cfg.f_max:
                f_buckets.append(b)
                b *= 2
            if f_buckets and f_buckets[-1] != self.cfg.f_max:
                # non-power-of-two f_max: bucket_for caps at f_max itself,
                # so the largest (most expensive) shape is f_max, not the
                # last doubled rung — warm it too
                f_buckets.append(self.cfg.f_max)
        with self.obs.span("warmup"):
            r0, r1 = await self._both(
                "warmup",
                {
                    "f_buckets": [int(b) for b in f_buckets],
                    # warm THIS leader's path + span plan, which may
                    # override the servers' own config (bench legs)
                    "ot_path": self.cfg.ot_path,
                    "secure_spans": bool(
                        self.cfg.secure_exchange
                        and not self.cfg.secure_whole_level
                        and self.cfg.crawl_shard_nodes
                    ),
                    # the shard layout this leader BELIEVES the servers
                    # run (multi-chip client sharding): the servers warm
                    # their own live layout regardless, but a skew is
                    # warned about at warmup time instead of surfacing
                    # as fresh compiles on the measured clock
                    "data_shards": int(self.cfg.server_data_devices),
                },
            )
        return {"f_buckets": list(f_buckets), "s0": r0, "s1": r1}

    async def _crawl_level(self, level: int, last: bool):
        """This level's crawl verbs, sharded when ``cfg.crawl_shard_nodes``
        says so: one verb per deterministic node span
        (``collect.shard_spans``) — the data plane is positional, so both
        servers must work the same span at the same time.  With
        ``cfg.crawl_pipeline_depth`` > 1 up to that many span verbs ride
        in flight at once with in-order reassembly (the servers serialize
        execution on their verb lock in frame-arrival order, so the
        positional data plane stays matched while span k+1's device
        expand overlaps span k's GC/OT network phase); any in-flight
        transient fault quiesces the pipeline and falls back to the
        sequential per-span retry below, so PR 4's recovery and ratchet
        semantics are untouched.  Sequentially each span runs under its
        own retry (:meth:`_shard_call`).  A mid-level fault costs the
        lost span(s), not the level."""
        verb = "tree_crawl_last" if last else "tree_crawl"
        # alternate the garbling server per FUSED level (the reference's
        # gc_sender flip, leader.rs:204-210) to split garbling cost; under
        # radix-2^k fusion the bases are 0, k, 2k, … so the flip counts
        # round trips (level // k), not bit-levels — level % 2 would pin
        # one garbler forever at even k.  The equality-test path rides the
        # verb too, so both servers follow THIS leader's config even when
        # it differs from their own (the bench's GC-reference leg depends
        # on that)
        rdx = max(1, int(self.cfg.crawl_radix_bits))
        req = {"level": level, "garbler": (level // rdx) % 2,
               "ot_path": self.cfg.ot_path}
        spans = collect.shard_spans(self._f_bucket, self.cfg.crawl_shard_nodes)
        if self.cfg.secure_exchange and self.cfg.secure_whole_level:
            # whole-level secure batching: every (node, client) wire of
            # the level garbles/evaluates as ONE device program per
            # f_bucket rung — node-sharding the GC/OT batch into
            # host-sized chunks (and pipelining those chunks) loses more
            # to fragmented kernels than the overlap wins.  A mid-level
            # fault then re-runs the level, not a span
            # (cfg.secure_whole_level=False restores span granularity).
            return await self._both(verb, req)
        if len(spans) == 1:
            return await self._both(verb, req)
        depth = max(1, int(getattr(self.cfg, "crawl_pipeline_depth", 1)))
        rerun = False
        if depth > 1 and len(spans) > 1:
            try:
                return await self._crawl_level_pipelined(
                    verb, req, spans, min(depth, len(spans)), level
                )
            except respolicy.TRANSIENT_ERRORS as err:
                if isinstance(err, ServerRestartedError):
                    raise  # lost state: the supervisor owns full recovery
                await self._quiesce_after_pipeline_fault(level, err)
                rerun = True
        parts0, parts1 = [], []
        for span in spans:
            s0, s1 = await self._shard_call(verb, dict(req, shard=list(span)))
            if rerun:  # fallback re-execution after a pipeline fault
                self.obs.count("shards_rerun", level=level)
            # fhh-lint: disable=host-sync-in-hot-loop (wire responses:
            # already host numpy off the control socket, no device sync)
            parts0.append(np.asarray(s0))
            # fhh-lint: disable=host-sync-in-hot-loop (wire response)
            parts1.append(np.asarray(s1))
        return np.concatenate(parts0, axis=0), np.concatenate(parts1, axis=0)

    async def _crawl_level_pipelined(
        self, verb: str, req: dict, spans: list, depth: int, level: int
    ):
        """Bounded-depth software pipeline over the level's spans: a
        sliding window of up to ``depth`` span verbs in flight, refilled
        as the OLDEST completes (in-order reassembly — the concatenated
        result is positional).  The per-span verbs hit both servers in
        frame order, so server-side execution order matches the
        sequential path exactly; only the leader-side await structure
        changes, which is what lets span k+1's expand (dispatched by the
        server on frame arrival, rpc.py ``_pre_expand``) run while span
        k's exchange is on the wire.  Telemetry: ``pipeline_depth`` /
        ``pipeline_overlap`` (sum of span busy-seconds beyond the level's
        wall-clock) / ``pipeline_stalls`` (head-of-line waits while a
        LATER span had already finished) per level."""

        def launch(span):
            async def one():
                t0 = time.monotonic()
                r = await self._all(
                    self.c0.call(verb, dict(req, shard=list(span))),
                    self.c1.call(verb, dict(req, shard=list(span))),
                )
                return r, time.monotonic() - t0

            return asyncio.ensure_future(one())

        t_level = time.monotonic()
        it = iter(spans)
        # fhh-lint: disable=unbounded-queue (bounded by construction: at most `depth` span tasks live at once — the refill below only appends after the head pops)
        window: _collections.deque = _collections.deque()
        for _ in range(depth):
            span = next(it, None)
            if span is not None:
                window.append(launch(span))
        parts0, parts1 = [], []
        busy = 0.0
        stalls = 0
        try:
            while window:
                head = window.popleft()
                if not head.done() and any(t.done() for t in window):
                    stalls += 1
                # fhh-lint: disable=unbounded-await (each span call is
                # bounded by its own per-verb wall-clock budget)
                (s0, s1), dt = await head
                busy += dt
                # fhh-lint: disable=host-sync-in-hot-loop (wire response)
                parts0.append(np.asarray(s0))
                # fhh-lint: disable=host-sync-in-hot-loop (wire response)
                parts1.append(np.asarray(s1))
                nxt = next(it, None)
                if nxt is not None:
                    window.append(launch(nxt))
        except BaseException:
            # quiesce step 1: no new spans, cancel the in-flight window
            # (their replay loops die; whatever already executed
            # server-side drains under the server's verb lock)
            for t in window:
                t.cancel()
            for t in window:
                with contextlib.suppress(Exception, asyncio.CancelledError):
                    await t
            raise
        wall = time.monotonic() - t_level
        self.obs.gauge("pipeline_depth", depth, level=level)
        self.obs.timer_add(
            "pipeline_overlap", max(0.0, busy - wall), level=level
        )
        if stalls:
            self.obs.count("pipeline_stalls", stalls, level=level)
        return np.concatenate(parts0, axis=0), np.concatenate(parts1, axis=0)

    async def _quiesce_after_pipeline_fault(self, level: int, err) -> None:
        """Quiesce step 2, after the in-flight window is cancelled: break
        any data-plane exchange wedged by the fault (a span that reached
        only ONE server leaves its peer blocked in a ``_swap`` recv
        holding the verb lock — ``plane_break`` closes the peer transport
        from BOTH ends without taking that lock, so the wedged verbs fail
        loudly and release), then re-key the plane through the normal
        locked ``plane_reset`` and verify neither server restarted.  The
        caller then re-runs the whole level sequentially; the servers'
        per-span caches overwrite, so the re-run is bit-identical."""
        self.obs.count("pipeline_faults", level=level)
        obsmod.emit(
            "pipeline.quiesce",
            severity="warn",
            level=level,
            error=f"{type(err).__name__}: {err}",
        )
        await self._all(
            self.c0.call("plane_break"), self.c1.call("plane_break")
        )
        if await self._reprobe_and_reset():
            raise err  # restarted server: full recovery owns it

    async def _shard_call(self, verb: str, req: dict):
        """One shard's verbs on both servers, retried under the shared
        policy: a transient mid-level fault re-keys the data plane
        (``plane_reset`` — a half-executed secure shard leaves the two
        servers' OT streams desynchronized, so the plane must re-handshake
        before the span re-runs) and re-issues JUST this span.  A changed
        boot id escalates unhandled — lost state is the supervisor's
        problem, not a shard retry's."""
        pol = respolicy.SHARD_POLICY
        attempt = 0
        while True:
            try:
                return await self._all(
                    self.c0.call(verb, req), self.c1.call(verb, req)
                )
            except respolicy.TRANSIENT_ERRORS as err:
                attempt += 1
                if isinstance(err, ServerRestartedError) or attempt >= pol.attempts:
                    raise
                if await self._reprobe_and_reset():
                    raise  # restarted server: full recovery owns it
                self.obs.count("shards_rerun", level=int(req["level"]))
                obsmod.emit(
                    "resilience.shard_rerun",
                    severity="warn",
                    level=int(req["level"]),
                    span=req.get("shard"),
                    attempt=attempt,
                    error=f"{type(err).__name__}: {err}",
                )
                await asyncio.sleep(pol.delay(attempt - 1))

    async def _run_one_level(self, level: int, nreqs: int, thresh: int):
        """One crawl->reconstruct->threshold->prune round under a level
        span (the heartbeat names this level while it runs).  Under
        radix-2^k fusion (``cfg.crawl_radix_bits``) ``level`` is the BASE
        bit-level of the fused step and the round covers bit-levels
        ``level .. level+r-1`` with ``r = min(k, L - level)`` — one round
        trip per fused level, 2^(d·r) count columns, and ``r`` path bits
        appended per dim.  Returns ``(counts_kept, alive_after_verify)``
        with ``counts_kept`` None when the crawl died out at this level."""
        cfg = self.cfg
        d, L = cfg.n_dims, cfg.data_len
        r = min(max(1, int(cfg.crawl_radix_bits)), L - level)
        last = level + r == L
        alive_after_verify = None
        if self.has_sketch and level != 1:
            # malicious-security gate first, so failing clients'
            # liveness flags flip before this level's counts are
            # taken.  Level 0 runs the FULL depth-1 check (both root
            # children per dim) — the first threshold never sees
            # unverified counts; levels >= 2 verify the
            # frontier-following shares stored by the previous prune.
            # The depth-1 frontier re-verify (level 1) is skipped: its
            # triples were consumed by the level-0 full check (see
            # rpc.sketch_verify / sketch.py scope note).
            a0, _ = await self._both("sketch_verify", {"level": level})
            alive_after_verify = np.asarray(a0)
        s0, s1 = await self._crawl_level(level, last)
        if last:
            v = np.asarray(F255.sub(s0, s1))  # leader-side reconstruct
            counts = v[..., 0].astype(np.uint32)  # counts < 2^32 by def
            if np.any(v[..., 1:]):  # boundary check: must survive -O
                raise RuntimeError("non-count residue in F255 share")
        else:
            v = np.asarray(FE62.canon(FE62.sub(s0, s1)))
            if np.any(v > nreqs):  # e.g. a share-sign/role mismatch
                raise RuntimeError("count reconstruction out of range")
            counts = v.astype(np.uint32)
        # radix_pattern_order permutes the fused (step-major) child
        # columns into the order a k=1 crawl would visit them, so
        # compact_survivors' walk — and therefore any f_max truncation —
        # is bit-identical to the sequential crawl (identity at r=1)
        order = collect.radix_pattern_order(d, r)
        keep = counts[:, order] >= thresh
        keep[self.n_nodes :, :] = False
        parent, rank, n_alive = collect.compact_survivors(
            keep, cfg.f_max, self.min_bucket
        )
        pattern = order[rank]
        pat_bits = collect.pattern_to_bits_radix(pattern, d, r)
        self.obs.gauge("survivors", n_alive, level=level)
        if n_alive == 0:
            return None, alive_after_verify
        self._f_bucket = int(parent.shape[0])  # next level's shard plan
        # r == 1 sends the historical 2-D [F', d] pattern wire (the
        # transcript ratchet absorbs the wire bytes — k=1 must stay
        # digest-identical); r > 1 sends the fused [F', r, d] form
        wire_bits = pat_bits[:, 0, :] if r == 1 else pat_bits
        if last:
            await self._both(
                "tree_prune_last",
                {
                    "parent_idx": parent,
                    "pattern_bits": wire_bits,
                    "n_alive": n_alive,
                },
            )
        else:
            await self._both(
                "tree_prune",
                {
                    "level": level,
                    "parent_idx": parent,
                    "pattern_bits": wire_bits,
                    "n_alive": n_alive,
                },
            )
        new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + r), bool)
        for i in range(n_alive):
            new_paths[i, :, : -r] = self.paths[parent[i]]
            for t in range(r):
                new_paths[i, :, -r + t] = pat_bits[i, t]
        self.paths = new_paths
        self.n_nodes = n_alive
        return counts[parent[:n_alive], pattern[:n_alive]], alive_after_verify

    async def run(self, nreqs: int) -> CrawlResult:
        # one distributed trace per crawl (obs.trace): every verb this
        # leader issues below carries the trace id, so both servers'
        # spans land in ONE merged timeline.  FHH_PROFILE additionally
        # wraps the crawl in a jax.profiler capture (per-level captures
        # are the servers' FHH_PROFILE_LEVELS hook).
        with obstrace.root("crawl"), obstrace.profile_capture("crawl"):
            return await self._run(nreqs)

    async def _run(self, nreqs: int) -> CrawlResult:
        cfg = self.cfg
        d, L = cfg.n_dims, cfg.data_len
        await self._both("tree_init", {"root_bucket": self.min_bucket})
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        self._f_bucket = self.min_bucket
        thresh = max(1, int(cfg.threshold * nreqs))
        counts_kept = np.zeros(0, np.uint32)
        alive_before_leaf = None  # liveness after the latest verify
        # radix-2^k fusion: one round trip per FUSED level — bases
        # 0, k, 2k, …, ⌈L/k⌉ rounds total (the tail round covers the
        # remaining L mod k bit-levels when k ∤ L)
        rdx = max(1, int(cfg.crawl_radix_bits))
        for level in range(0, L, rdx):
            r = min(rdx, L - level)
            with self.obs.span("level", level=level) as sp_level:
                counts_kept, alive = await self._run_one_level(
                    level, nreqs, thresh
                )
            # leader-side per-level latency histogram (SLO surface:
            # p50/p95 in the run report's slo section + the bench line)
            self.obs.observe("level_latency", sp_level.seconds)
            if alive is not None:
                alive_before_leaf = alive
            if counts_kept is None:
                return CrawlResult(
                    paths=np.zeros((0, d, level + r), bool),
                    counts=np.zeros(0, np.uint32),
                )
        if self.has_sketch and L > 1:
            # final F255 leaf-payload check (surviving leaves; counts for
            # this collection are already taken — the verdict gates the
            # liveness flags for any further use and flags forged leaves).
            # Warn only on NEW exclusions relative to the latest verify:
            # a client caught mid-tree stays excluded and must not read
            # as a leaf forgery.  data_len == 1 skips this call entirely:
            # there the level-0 full check IS the leaf check, and a second
            # opening of triples_last under a fresh challenge would leak
            # <r - r', x> (see rpc.sketch_verify).
            a0, _ = await self._both("sketch_verify", {"level": L})
            prev = (
                alive_before_leaf
                if alive_before_leaf is not None
                else np.ones_like(np.asarray(a0))
            )
            if np.any(prev & ~np.asarray(a0)):
                obsmod.emit(
                    "sketch.leaf_forgery",
                    severity="warn",
                    new_exclusions=int(np.sum(prev & ~np.asarray(a0))),
                )
        # final reconstruction from re-served leaf shares: v0 - v1 per
        # surviving leaf (ref: collect.rs:993-1029 final_shares/final_values;
        # the crawl-time counts are only the pruning signal)
        f0, f1 = await self._both("final_shares")
        v = np.asarray(F255.sub(f0["shares"], f1["shares"]))
        final_counts = v[..., 0].astype(np.uint32)
        if np.any(v[..., 1:]) or not np.array_equal(final_counts, counts_kept):
            raise RuntimeError("final share reconstruction mismatch")
        return CrawlResult(paths=self.paths, counts=final_counts)

    # -- fault-tolerant crawl (resilience layer) -------------------------

    @staticmethod
    async def _probe(client) -> dict:
        """``status`` with restart absorption: the reconnect handshake may
        discover a new boot id and poison the FIRST call with
        ServerRestartedError; the second call runs against the fresh boot
        and is replay-free by construction."""
        try:
            return await client.call("status")
        except ServerRestartedError:
            return await client.call("status")

    async def _reprobe_and_reset(self) -> list:
        """The shared fault-recovery preamble: probe s0, re-establish the
        data plane via the dialer, probe s1, and diff both boot ids
        against the known table (updating it).  Returns the indices of
        servers that RESTARTED — a previously-unknown boot id is learned,
        not treated as a restart."""
        st0 = await self._probe(self.c0)
        await self.c0.call("plane_reset")
        st1 = await self._probe(self.c1)
        restarted = []
        for i, st in enumerate((st0, st1)):
            known = self._boot_ids.get(i)
            if known is not None and st["boot_id"] != known:
                restarted.append(i)
            self._boot_ids[i] = st["boot_id"]
        return restarted

    async def _recover(self, keys0, keys1, sketch0, sketch1, stash) -> int:
        """Bring both servers back to one consistent state after any
        control-plane, data-plane, or server loss; returns the next level
        to run.  With a checkpoint stash: redial, re-establish the data
        plane, re-seed restarted servers' keys (sketch material
        included), ``tree_restore`` both to the stash level.  Without
        one: full restart from level 0."""
        if stash is None and sketch0 is not None:
            # refuse BEFORE touching any server: restart-from-scratch
            # would re-upload the SAME Beaver triple shares and commit a
            # NEW ratchet root (fresh coin flip) — run 2's openings
            # d' = <r', x> - a against run 1's d = <r, x> - a hand the
            # servers <r - r', x> of every honest payload, the exact leak
            # the ratchet prevents.  The supervisor banks an init (level
            # -1) checkpoint precisely so this branch is unreachable with
            # a working FHH_CKPT_DIR; without one, the only sound rerun
            # uses fresh client sketch keys.
            raise ValueError(
                "sketch crawl cannot restart from scratch: re-opening "
                "the same Beaver slabs under a fresh challenge root "
                "would leak <r - r', x> — configure FHH_CKPT_DIR so "
                "recovery can roll back, or rerun with fresh sketch keys"
            )
        # probe s0 first: the supervisor's client redials under policy
        st0 = await self._probe(self.c0)
        # re-establish the data plane via the DIALER side, always: a
        # surviving s0's plane may be half-dead, and a restarted s1 can't
        # even serve its control plane until s0 redials (its start()
        # blocks on the plane accept before binding the RPC listener)
        await self.c0.call("plane_reset")
        st1 = await self._probe(self.c1)
        restarted = []
        for i, st in enumerate((st0, st1)):
            if st["boot_id"] != self._boot_ids.get(i):
                restarted.append(i)
            self._boot_ids[i] = st["boot_id"]
        # device-loss vs server-loss: a server with an INTACT boot id
        # whose mesh FAULT counter advanced since the leader last looked
        # lost a device, not itself — either it already re-sharded in
        # place (rpc._mesh_recover counts reshards too) or it escalated
        # for want of a usable checkpoint; the faults counter covers
        # both, where reshards alone would miss the escalation.  The
        # delta matters: the counters are cumulative per boot, so an old
        # fault must not re-attribute a later, unrelated recovery wave
        # (attribution is "since the last probe" — a silently recovered
        # reshard between waves lands on the next one).  Name both so
        # the postmortem (and the recovery tests) can tell the cases
        # apart without scraping server logs.
        device_loss = []
        for i, st in enumerate((st0, st1)):
            f = int((st.get("mesh") or {}).get("faults") or 0)
            if i not in restarted and f > self._mesh_faults.get(i, 0):
                device_loss.append(i)
            self._mesh_faults[i] = f
        if restarted or device_loss:
            obsmod.emit(
                "resilience.loss_classified",
                server_loss=restarted,
                device_loss=device_loss,
            )
        if stash is None:
            # no checkpoint to stand on: restart the crawl from scratch
            # (sketch mode was refused above — it can never restart)
            await self._both("reset")
            await self.upload_keys(keys0, keys1, sketch0, sketch1)
            await self._both("tree_init", {"root_bucket": self.min_bucket})
            self.paths = np.zeros((1, self.cfg.n_dims, 0), bool)
            self.n_nodes = 1
            self._f_bucket = self.min_bucket
            obsmod.emit(
                "resilience.restarted_from_scratch",
                severity="warn",
                restarted_servers=restarted,
            )
            return 0
        level = stash["level"]
        for i in restarted:
            # a restarted server lost its key batch; re-seed it before
            # tree_restore re-concatenates (NO reset here: reset would
            # delete the very checkpoint files we are about to restore)
            await self.upload_keys(keys0, keys1, sketch0, sketch1, which=i)
        r0, r1 = await self._both("tree_restore", {"level": level})
        if int(r0["level"]) != level or int(r1["level"]) != level:
            raise RuntimeError(
                f"restored levels diverge: s0={r0['level']} s1={r1['level']} "
                f"leader stash={level}"
            )
        self.paths = stash["paths"].copy()
        self.n_nodes = stash["n_nodes"]
        self._f_bucket = stash["f_bucket"]
        obsmod.emit(
            "resilience.restored",
            level=level,
            restarted_servers=restarted,
        )
        # next base on the fused level grid: a checkpoint banks the state
        # AFTER the fused level at base ``level``, so the crawl resumes at
        # level + r (level -1 is the sketch init checkpoint: resume at 0)
        rdx = max(1, int(self.cfg.crawl_radix_bits))
        if level < 0:
            return 0
        return level + min(rdx, self.cfg.data_len - level)

    async def run_supervised(
        self,
        nreqs: int,
        keys0: IbDcfKeyBatch,
        keys1: IbDcfKeyBatch,
        sketch0=None,
        sketch1=None,
        *,
        checkpoint_every: int = 8,
        max_recoveries: int = 4,
        warmup: bool = False,
    ) -> CrawlResult:
        """The fault-tolerant twin of :meth:`run`, owning the WHOLE crawl
        (reset + upload + levels + final reconstruction) because recovery
        needs the key batches to re-seed a restarted server.

        Per completed ``checkpoint_every`` levels it instructs both
        servers to ``tree_checkpoint`` and stashes the leader-side path
        bookkeeping; on any transport loss, server restart, or verb
        failure it rolls BOTH servers back to the last stash (fresh
        data-plane handshake included) and re-runs only the lost levels.
        Counts are exact re-runs: a recovered crawl's results are
        bit-identical to a fault-free one.

        Malicious (sketch) mode is supervised too: pass the clients'
        sketch key batches, and recovery re-seeds them alongside the
        ibDCF keys.  The per-level challenge RATCHET (sketch.py) makes
        the rollback sound — a re-run level derives the identical
        challenge from the committed root + restored transcript digest,
        so re-opening its Beaver slab is a bit-identical replay, never a
        second opening.  Checkpointing degrades gracefully: servers
        without a checkpoint dir disable it (recovery then means
        restart-from-scratch), keeping supervision usable everywhere."""
        # one distributed trace per supervised crawl — recovery waves,
        # restores, and the re-run levels all land in the SAME timeline
        with obstrace.root("crawl"), obstrace.profile_capture("crawl"):
            return await self._run_supervised(
                nreqs, keys0, keys1, sketch0, sketch1,
                checkpoint_every=checkpoint_every,
                max_recoveries=max_recoveries, warmup=warmup,
            )

    async def _run_supervised(
        self,
        nreqs: int,
        keys0: IbDcfKeyBatch,
        keys1: IbDcfKeyBatch,
        sketch0=None,
        sketch1=None,
        *,
        checkpoint_every: int = 8,
        max_recoveries: int = 4,
        warmup: bool = False,
    ) -> CrawlResult:
        cfg = self.cfg
        d, L = cfg.n_dims, cfg.data_len
        if cfg.malicious and sketch0 is None:
            # refuse BEFORE touching the servers: proceeding would upload
            # keys without their sketch material and silently run a
            # malicious-mode collection semi-honest
            raise ValueError(
                "run_supervised in malicious mode needs the sketch key "
                "batches (pass sketch0/sketch1)"
            )
        thresh = max(1, int(cfg.threshold * nreqs))
        await self._both("reset")
        await self.upload_keys(keys0, keys1, sketch0, sketch1)
        if warmup:
            # per-f_bucket compile warmup before any crawl time is spent;
            # rides inside the supervised flow because reset() above
            # cleared any earlier upload (warmup needs the key shapes)
            await self.warmup()
        await self._both("tree_init", {"root_bucket": self.min_bucket})
        self.paths = np.zeros((1, d, 0), bool)
        self.n_nodes = 1
        self._f_bucket = self.min_bucket
        self._boot_ids = {
            0: self.c0.boot_id,
            1: self.c1.boot_id,
        }
        stash = None  # leader-side bookkeeping at the last checkpoint
        counts_kept = np.zeros(0, np.uint32)
        alive_before_leaf = None  # liveness after the latest sketch verify
        # zero-touch the recovery counters so a fault-free supervised run
        # still reports a (zeroed) recovery section in the run report
        for c in ("recoveries", "levels_rerun", "shards_rerun"):
            self.obs.count(c, 0)
        ckpt_enabled = True
        if sketch0 is not None:
            # INIT checkpoint (level -1): sketch mode cannot restart from
            # scratch (see _recover's refusal — same triples under a new
            # root leak <r - r', x>), so bank a rollback point BEFORE any
            # Beaver slab opens: a fault ahead of the first level
            # checkpoint then restores the committed root + empty
            # transcript and replays from level 0 under the identical
            # challenge sequence.  Not counted as a crawl checkpoint —
            # no crawl progress is banked by it.
            try:
                await self._both("tree_checkpoint", {"level": -1})
                stash = {
                    "level": -1,
                    "paths": self.paths.copy(),
                    "n_nodes": 1,
                    "counts": counts_kept.copy(),
                    "f_bucket": self._f_bucket,
                    "alive": None,
                }
            except RuntimeError as e:
                ckpt_enabled = False
                obsmod.emit(
                    "resilience.checkpoint_disabled",
                    severity="warn",
                    error=str(e),
                )
        recoveries = 0
        level = 0
        rdx = max(1, int(cfg.crawl_radix_bits))
        while level < L:
            # radix-2^k fusion: this round covers bit-levels
            # level .. level+r-1; the next base is level + r
            r = min(rdx, L - level)
            try:
                with self.obs.span("level", level=level) as sp_level:
                    counts_kept, alive = await self._run_one_level(
                        level, nreqs, thresh
                    )
                self.obs.observe("level_latency", sp_level.seconds)
                if alive is not None:
                    alive_before_leaf = alive
                if counts_kept is None:
                    return CrawlResult(
                        paths=np.zeros((0, d, level + r), bool),
                        counts=np.zeros(0, np.uint32),
                    )
                if (
                    ckpt_enabled
                    and level + r < L
                    and (level + r) % checkpoint_every == 0
                ):
                    try:
                        await self._both("tree_checkpoint", {"level": level})
                        stash = {
                            "level": level,
                            "paths": self.paths.copy(),
                            "n_nodes": self.n_nodes,
                            "counts": counts_kept.copy(),
                            "f_bucket": self._f_bucket,
                            "alive": (
                                None
                                if alive_before_leaf is None
                                else alive_before_leaf.copy()
                            ),
                        }
                        self.obs.count("crawl_checkpoints", level=level)
                    except RuntimeError as e:
                        # servers can't checkpoint (no FHH_CKPT_DIR):
                        # supervise without — recovery restarts from 0
                        ckpt_enabled = False
                        obsmod.emit(
                            "resilience.checkpoint_disabled",
                            severity="warn",
                            error=str(e),
                        )
                level += r
            except (ConnectionError, TimeoutError, RuntimeError) as err:
                while True:
                    recoveries += 1
                    self.obs.count("recoveries")
                    obsmod.emit(
                        "resilience.recover",
                        severity="warn",
                        level=level,
                        attempt=recoveries,
                        error=f"{type(err).__name__}: {err}",
                    )
                    if recoveries > max_recoveries:
                        raise err
                    try:
                        level = await self._recover(
                            keys0, keys1, sketch0, sketch1, stash
                        )
                        break
                    except (ConnectionError, TimeoutError, RuntimeError) as e2:
                        err = e2  # recovery itself failed: another round
                if stash is not None:
                    counts_kept = stash["counts"].copy()
                    alive_before_leaf = (
                        None if stash["alive"] is None
                        else stash["alive"].copy()
                    )
                else:
                    counts_kept = np.zeros(0, np.uint32)
                    alive_before_leaf = None
                self.obs.count("levels_rerun")
        if self.has_sketch and L > 1:
            # final F255 leaf-payload check, as in run() (read-only from
            # the crawl's perspective: the verdict gates liveness flags)
            a0, _ = await self._both("sketch_verify", {"level": L})
            prev = (
                alive_before_leaf
                if alive_before_leaf is not None
                else np.ones_like(np.asarray(a0))
            )
            if np.any(prev & ~np.asarray(a0)):
                obsmod.emit(
                    "sketch.leaf_forgery",
                    severity="warn",
                    new_exclusions=int(np.sum(prev & ~np.asarray(a0))),
                )
        # final reconstruction, as in run() (final_shares is read-only:
        # the client's transparent replay covers transient losses here)
        f0, f1 = await self._both("final_shares")
        v = np.asarray(F255.sub(f0["shares"], f1["shares"]))
        final_counts = v[..., 0].astype(np.uint32)
        if np.any(v[..., 1:]) or not np.array_equal(final_counts, counts_kept):
            raise RuntimeError("final share reconstruction mismatch")
        return CrawlResult(paths=self.paths, counts=final_counts)


# ---------------------------------------------------------------------------
# Streaming ingest: the windowed front-door driver
# ---------------------------------------------------------------------------

# a client's submit backoff: quick first retries (token refill horizons
# are sub-second at sane rates), generous attempt count — a flooding
# client spends its time HERE, sleeping, which is exactly the
# backpressure story (it stalls itself, not the crawl)
INGEST_POLICY = respolicy.RetryPolicy(
    base_s=0.02, cap_s=0.5, factor=2.0, attempts=16
)


class IngestOverloadedError(RuntimeError):
    """Every backed-off retry of one submission was answered Overloaded —
    the caller is flooding faster than the front door will ever admit.
    Deliberately NOT transport-shaped: retrying at the RPC layer cannot
    help, the caller must slow down or drop."""


# Runtime twin of the fhh-race guard map — the "WindowedIngest.*"
# entries of pyproject [tool.fhh-lint.guards] (drift-tested in
# tests/test_concurrency.py); see rpc._SERVER_GUARDS for the contract.
_INGEST_GUARDS = {
    "window": "_submit_lock",
    "_journal": "_submit_lock",
    "_journaled": "_submit_lock",
    "_sealed": "_submit_lock",
}


class WindowedIngest:
    """Leader-side driver of the streaming front door: continuous
    ``submit_keys`` into tumbling windows, ``seal_window`` at each
    boundary, and :meth:`crawl_window` running the NORMAL level loop on
    the frozen snapshot while later submissions keep landing — the
    online successor of the one-bulk-upload ``upload_keys`` flow.

    Admission protocol: server 0 is the GATE — its verdict (admit slot /
    shed / Overloaded) is obtained first, then MIRRORED to server 1, so
    the two pools stay positionally identical without requiring the two
    servers to agree on arrival order.  Overloaded verdicts back off
    under ``policy`` (honoring the gate's ``retry_after_s`` hint) and
    re-attempt: a flooding client stalls itself.

    Window-consistent recovery: every admitted/shed submission is
    journaled until its window's crawl completes.  On a server restart
    the driver restores the last ingest checkpoint (``tree_restore`` of
    the ``ingest_only`` blob banked at each seal — pools, per-``sub_id``
    verdicts, reservoir RNG state), then REPLAYS the journal in mirror
    form — the recorded-verdict dedup makes the combination exactly-once
    (nothing lost, nothing double-counted) — re-seals, reloads, and
    re-runs the window's crawl.  Results are bit-exact vs a batch crawl
    over the same admitted key set (asserted in tests and bench)."""

    def __init__(self, lead: RpcLeader, *, policy=None, checkpoint=True):
        self.lead = lead
        self.cfg = lead.cfg
        self.window = 0
        self.policy = policy or INGEST_POLICY
        # a dedicated registry so the heartbeat names the ingest phase
        # (span "ingest" per window) independently of the crawl's spans;
        # a non-default collection is named in the registry so the
        # report's sessions rollup attributes its ingest counters
        _coll = getattr(lead, "collection", None) or "default"
        self.obs = obsmetrics.Registry(
            "ingest" if _coll == "default" else f"ingest:{_coll}"
        )
        self._span_ctx = None
        self._journal: dict[int, list] = {}  # window -> submission records
        self._journaled: set = set()  # sub_ids already journaled
        self._sealed: dict[int, dict] = {}  # window -> seal stats
        self._n_subs = 0
        self._ckpt = bool(checkpoint)
        self._have_ckpt = False
        # gate->mirror ordering: the mirror MUST apply verdicts in the
        # gate's decision order (pools are positional), so the
        # gate-call + mirror-call pair serializes on this lock; backoff
        # sleeps happen OUTSIDE it, so a rejected (flooding) client
        # stalls only itself while others keep submitting
        self._submit_lock = asyncio.Lock()
        # one recovery at a time: a concurrent submit and crawl may both
        # observe the same fault — the second waiter re-probes (cheap,
        # idempotent) instead of interleaving journal replays
        self._recover_lock = asyncio.Lock()
        # seed restart detection: recovery compares boot ids against the
        # ones the clients learned at connect time
        for i, c in ((0, lead.c0), (1, lead.c1)):
            if lead._boot_ids.get(i) is None:
                lead._boot_ids[i] = getattr(c, "boot_id", None)
        # LAST: the sanitizer (a no-op unless FHH_DEBUG_GUARDS=1 or
        # cfg.debug_guards) wraps the already-constructed guarded state
        guards.install(
            self, _INGEST_GUARDS,
            force=bool(getattr(self.cfg, "debug_guards", False)),
        )

    # -- window lifecycle -------------------------------------------------

    # fhh-race: atomic (telemetry-only read of the window id for the span label: one event-loop slice, and a label one boundary stale costs nothing)
    def _ensure_span(self) -> None:
        if self._span_ctx is None:
            with guards.unguarded(
                "telemetry-only window-id read for the span label "
                "(fhh-race atomic contract on _ensure_span)"
            ):
                w = self.window
            # fhh-lint: disable=span-discipline (explicitly managed context: the ingest span opens at the first submit and _exit_span closes it at the seal boundary — a with-block cannot straddle the two call sites)
            self._span_ctx = self.obs.span("ingest", level=w)
            self._span_ctx.__enter__()

    def _exit_span(self) -> None:
        if self._span_ctx is not None:
            self._span_ctx.__exit__(None, None, None)
            self._span_ctx = None

    async def submit(self, client_id, k0_chunk, k1_chunk, *,
                     sk0_chunk=None, sk1_chunk=None,
                     sub_id: str | None = None) -> dict:
        """Submit one client's key-share chunks (k0 to server 0, k1 to
        server 1) into the CURRENT window.  Blocks through Overloaded
        verdicts under the backoff policy; returns the gate verdict
        (``admitted`` or ``shed``).  Raises
        :class:`IngestOverloadedError` when every attempt was rejected.

        Malicious mode: ``sk0_chunk``/``sk1_chunk`` carry the client's
        sketch key leaves (the ``upload_keys`` sk_chunk form — flat
        ``jax.tree.leaves`` of its :class:`~.sketch.SketchKeyBatch`
        share); they ride the same admission verdict, the same journal
        record, and the same recovery replay as the ibDCF chunks."""
        self._ensure_span()
        t_admit = time.perf_counter()  # ingest-admit SLO clock (e2e:
        # gate + mirror + every Overloaded backoff this submission ate)
        if sub_id is None:
            # unique per LOGICAL submission; reused across transport
            # replays and recovery re-submissions so the servers'
            # recorded verdicts dedup them.  Window-independent: a
            # backed-off retry that straddles a seal lands in the NEW
            # current window under the same id.
            self._n_subs += 1
            sub_id = f"{self.lead.c0.session_id}:{self._n_subs}"
        k0_chunk = tuple(np.asarray(a) for a in k0_chunk)
        k1_chunk = tuple(np.asarray(a) for a in k1_chunk)
        if (sk0_chunk is None) != (sk1_chunk is None):
            raise ValueError(
                "sketch chunks come in pairs: pass sk0_chunk AND "
                "sk1_chunk (one per server's share) or neither"
            )
        if sk0_chunk is not None:
            sk0_chunk = [np.asarray(a) for a in sk0_chunk]
            sk1_chunk = [np.asarray(a) for a in sk1_chunk]
        n_keys = int(k0_chunk[0].shape[0])
        attempt = 0
        faults = 0
        while True:
            # the lock pins gate-order == mirror-order (positional
            # pools); a rejected attempt releases it before backing off.
            # The window id is read UNDER the lock: a concurrent
            # seal_window may have advanced it while this task waited,
            # and a stale id would target a sealed window.
            async with self._submit_lock:
                w = self.window
                base = {
                    "window": w, "sub_id": sub_id,
                    "client_id": str(client_id),
                }
                # the gate+mirror pair (and its fault recovery) runs
                # entirely under this lock hold: once the gate admits,
                # nothing — not even a seal — may interleave before the
                # mirror lands, or the two pools' seal stats diverge
                while True:
                    try:
                        r0 = await self.lead.c0.call(
                            "submit_keys",
                            dict(base, keys=k0_chunk, sketch=sk0_chunk),
                        )
                        if r0.get("overloaded"):
                            break
                        rec = {
                            "sub_id": sub_id,
                            "client_id": str(client_id),
                            "window": w,
                            "slot": r0.get("slot"),
                            "shed": bool(r0.get("shed")),
                            "k0": k0_chunk,
                            "k1": k1_chunk,
                            "sk0": sk0_chunk,
                            "sk1": sk1_chunk,
                        }
                        # journal BEFORE the mirror call: if s1 restarts
                        # mid-mirror, the recovery replay carries this
                        # record and the retried mirror dedups against it
                        # (a faulted retry of the same sub_id — the gate
                        # answers it as a dup — must not journal twice)
                        if sub_id not in self._journaled:
                            self._journal.setdefault(w, []).append(rec)
                            self._journaled.add(sub_id)
                        await self.lead.c1.call(
                            "submit_keys",
                            dict(
                                base, keys=k1_chunk, sketch=sk1_chunk,
                                mirror={"slot": rec["slot"],
                                        "shed": rec["shed"]},
                            ),
                        )
                        break
                    except respolicy.TRANSIENT_ERRORS:
                        # restart or transport loss mid-submission:
                        # recover the ingest state and re-attempt the
                        # SAME sub_id — recorded verdicts make the
                        # retry exactly-once
                        faults += 1
                        if faults > 8:
                            raise
                        await self._recover_ingest()
            if not r0.get("overloaded"):
                break
            if r0.get("scope") == "burst":
                # permanent by configuration: no refill horizon ever
                # covers a chunk larger than the burst — backing off
                # would be 16 futile round trips
                self.obs.count("ingest_rejected", level=w)
                raise IngestOverloadedError(
                    f"submission {sub_id} ({n_keys} keys) exceeds the "
                    "gate's burst allowance — split the chunk or raise "
                    "ingest_burst_keys"
                )
            attempt += 1
            self.obs.count("ingest_rejected", level=w)
            if attempt >= self.policy.attempts:
                raise IngestOverloadedError(
                    f"submission {sub_id} rejected {attempt} times "
                    f"(last scope: {r0.get('scope')}) — client is "
                    "over the front door's sustained capacity"
                )
            await asyncio.sleep(
                max(
                    float(r0.get("retry_after_s", 0.0)),
                    self.policy.delay(attempt - 1),
                )
            )
        if rec["shed"]:
            # fhh-lint: disable=stale-read-across-await (deliberate snapshot: the stats must label the window this submission actually LANDED in — the admission-time id banked under the lock; a post-await re-read would mislabel it with whatever window is current now)
            self.obs.count("ingest_shed_subs", level=w)
        else:
            # fhh-lint: disable=stale-read-across-await (deliberate snapshot, same contract as the shed branch: the admitted count labels the window this submission LANDED in — the id banked under the lock at gate time, not whatever window is current after the backoff awaits)
            self.obs.count("ingest_admitted", n_keys, level=w)
        self.obs.observe("ingest_admit", time.perf_counter() - t_admit)
        return r0

    async def seal_window(self) -> dict:
        """Freeze the current window on both servers (tumbling-window
        boundary), bank the ingest checkpoint, and open the next window.
        Returns the gate's seal stats (keys/subs/shed/rejected)."""
        faults = 0
        while True:
            # the WHOLE boundary — window-id read, seal pair, stats
            # bank, window advance — under one submit-lock hold: it must
            # not race a half-mirrored submission (gate applied, mirror
            # in flight), and the id must be read UNDER the lock — a
            # pre-lock read could re-seal a window a concurrent boundary
            # already advanced past and ROLL THE WINDOW COUNTER BACK
            # (fhh-race caught this: the PR-7 stale-window-id shape,
            # this time on the seal path)
            async with self._submit_lock:
                w = self.window
                try:
                    r0, r1 = await self.lead._both(
                        "window_seal", {"window": w}
                    )
                except respolicy.TRANSIENT_ERRORS:
                    faults += 1
                    if faults > 8:
                        raise
                    await self._recover_ingest()
                    continue
                # sk_root rides the comparison: the two servers derive
                # the window's challenge root from their own session
                # coin flip — a plane re-key landing between the two
                # seal-time handshakes would commit DIFFERENT roots,
                # and a root mismatch silently excludes every honest
                # client in the window (divergent challenge streams)
                if (
                    r0["keys"], r0["subs"], r0.get("sk_root")
                ) != (r1["keys"], r1["subs"], r1.get("sk_root")):
                    raise RuntimeError(
                        f"window {w} pools diverged at seal: "
                        f"gate {r0} vs mirror {r1}"
                    )
                # bank the DRIVER's seal instant with the stats: the
                # start of this window's seal-to-hitters SLO clock
                # (observed when crawl_window serves its hitters)
                self._sealed[w] = dict(r0, sealed_at=time.time())
                self.window = w + 1
                break
        self._exit_span()
        # shed keys include reservoir-replaced occupants the driver
        # cannot see per-submit — the seal stats are authoritative
        self.obs.count("ingest_shed", int(r0["shed_keys"]), level=w)
        self.obs.count("ingest_windows")
        obsmod.emit(
            "ingest.window_boundary",
            window=w,
            keys=int(r0["keys"]),
            subs=int(r0["subs"]),
            shed=int(r0["shed_keys"]),
            rejected=int(r0["rejected"]),
        )
        if self._ckpt:
            # bank the pools at the boundary: the rollback point a
            # kill-mid-window recovers to (ingest-only blob, level -1)
            try:
                await self.lead._both(
                    "tree_checkpoint", {"level": -1, "ingest_only": True}
                )
                self._have_ckpt = True
            except RuntimeError as e:
                self._ckpt = False  # no FHH_CKPT_DIR: journal replay covers
                obsmod.emit(
                    "ingest.checkpoint_disabled", severity="warn", error=str(e)
                )
        return r0

    # -- windowed crawl + recovery ---------------------------------------

    async def crawl_window(self, w: int, *, max_recoveries: int = 4):
        """Run the normal level loop over SEALED window ``w``'s frozen
        pool; ingest into later windows continues concurrently
        (``submit_keys`` bypasses the servers' verb lock).  On any
        transport loss / server restart the driver recovers ingest state
        (checkpoint restore + journal replay), reloads the window, and
        re-runs its crawl — results stay bit-exact because the frozen
        pool is reconstructed exactly and the crawl is deterministic.

        One distributed trace per WINDOW: the nested ``run()`` reuses
        it, so window_load, every level, and the final reconstruction
        share the window's trace id; the seal-to-hitters latency
        (seal instant banked by :meth:`seal_window` -> hitters served
        here) lands in the driver's ``seal_to_hitters`` histogram — the
        first-class SLO the always-on dashboard reads."""
        with obstrace.root("window"):
            return await self._crawl_window(w, max_recoveries=max_recoveries)

    async def _crawl_window(self, w: int, *, max_recoveries: int = 4):
        async with self._submit_lock:
            stats = self._sealed.get(w)
        if stats is None:
            raise RuntimeError(f"crawl_window: window {w} is not sealed")
        nreqs = int(stats["keys"])
        recoveries = 0
        while True:
            try:
                l0, _ = await self.lead._both("window_load", {"window": w})
                # a malicious window's crawl must run the sketch_verify
                # gates (the batch flow learns this at upload_keys; the
                # windowed flow learns it from the loaded pool)
                self.lead.has_sketch = bool(l0.get("sketch"))
                with self.obs.span("window_crawl", level=w):
                    res = await self.lead.run(nreqs)
                break
            except (ConnectionError, TimeoutError, RuntimeError) as err:
                recoveries += 1
                self.obs.count("ingest_recoveries")
                obsmod.emit(
                    "ingest.window_recover",
                    severity="warn",
                    window=w,
                    attempt=recoveries,
                    error=f"{type(err).__name__}: {err}",
                )
                if recoveries > max_recoveries:
                    raise
                try:
                    # under the submit lock (same _submit_lock ->
                    # _recover_lock order as submit/seal): the journal
                    # replay rebuilds pools POSITIONALLY, so a live
                    # gate+mirror pair interleaving with it could land
                    # between replayed records and diverge the two
                    # servers' slot order (fhh-race caught the unlocked
                    # form)
                    async with self._submit_lock:
                        await self._recover_ingest()
                except respolicy.TRANSIENT_ERRORS:
                    # a server still coming back up: the next loop turn
                    # re-probes (bounded by max_recoveries)
                    continue
        # seal -> hitters served: the driver-side seal-to-hitters SLO
        # observation (the servers observe their own copy at
        # final_shares; both merge into the report's slo section)
        # fhh-lint: disable=stale-read-across-await (deliberate snapshot: the SLO clock starts at the SEAL-time instant banked in the stats row — a sealed window's stats never mutate, and re-reading under the lock would return the identical row or None after the prune below)
        sealed_at = stats.get("sealed_at")
        if sealed_at is not None:
            self.obs.observe(
                "seal_to_hitters", max(0.0, time.time() - sealed_at)
            )
        # the window is crawled: its journal, journaled-id set, and seal
        # stats (and any earlier) are done — bounded driver memory
        # mirrors the servers' bounded pools.  Under the submit lock:
        # the prune itself never suspends, but the discipline is that
        # EVERY journal/seal-table access holds the lock, and a submit
        # mid-await must not watch its window's records vanish
        async with self._submit_lock:
            for old in [k for k in self._journal if k <= w]:
                for rec in self._journal[old]:
                    self._journaled.discard(rec["sub_id"])
                del self._journal[old]
            for old in [k for k in self._sealed if k <= w]:
                del self._sealed[old]
        return res

    async def _recover_ingest(self) -> None:
        """Bring both servers' INGEST state back to this driver's view:
        probe boot ids, re-key the data plane, and for every restarted
        server restore the last ingest checkpoint then replay the journal
        (mirror form, recorded verdicts deduping) and re-seal the sealed
        windows.  The crawl state is NOT restored here — the caller
        reloads the window and re-runs, which rebuilds it exactly."""
        async with self._recover_lock:
            await self._recover_ingest_locked()

    async def _recover_ingest_locked(self) -> None:
        lead = self.lead
        restarted = await lead._reprobe_and_reset()
        for i in restarted:
            client = lead.c0 if i == 0 else lead.c1
            if self._have_ckpt:
                try:
                    await client.call("tree_restore", {"level": -1})
                except RuntimeError as e:
                    obsmod.emit(
                        "ingest.restore_failed",
                        severity="warn",
                        server=i,
                        error=str(e),
                    )
            await self._replay_journal(client, i)
            for w in sorted(self._sealed):
                req = {"window": w}
                root = self._sealed[w].get("sk_root")
                if root is not None:
                    # the ORIGINAL window challenge root banked at first
                    # seal: a journal-rebuilt pool on a restarted server
                    # must commit THIS root, never derive a fresh one
                    # (same Beaver slabs under a new root leak
                    # <r - r', x> on the window's re-run)
                    req["sk_root"] = root
                await client.call("window_seal", req)
            obsmod.emit("ingest.server_reseeded", server=i)

    async def _replay_journal(self, client, which: int) -> None:
        """Re-submit every journaled record to ONE server in mirror form
        (the recorded gate verdicts, not fresh admission): entries the
        restored checkpoint already carries answer as dups, the rest
        rebuild the pool positionally identical to the pre-fault one."""
        replayed = 0
        for w in sorted(self._journal):
            for rec in self._journal[w]:
                await client.call(
                    "submit_keys",
                    {
                        "window": rec["window"],
                        "sub_id": rec["sub_id"],
                        "client_id": rec["client_id"],
                        "keys": rec["k0"] if which == 0 else rec["k1"],
                        "sketch": rec.get("sk0" if which == 0 else "sk1"),
                        "mirror": {"slot": rec["slot"], "shed": rec["shed"]},
                    },
                )
                replayed += 1
        if replayed:
            self.obs.count("ingest_journal_replays", replayed)

    # -- fleet: live migration + whole-host failover ----------------------

    async def migrate(self, new_lead: RpcLeader) -> dict:
        """Live-migrate this session onto ``new_lead``'s host pair
        mid-stream (protocol/fleet.py decides *where*; this is *how*).

        Quiesce: the whole transfer runs under ``_submit_lock`` →
        ``_recover_lock`` (the same order submit/seal/crawl-recovery
        take), so no gate+mirror pair is half-landed and no boundary is
        mid-advance; the servers additionally refuse to export mid-level.
        Steps, ordered so a failure at ANY point leaves the source
        authoritative (the source copy is only retired LAST):

        1. ``session_export`` on both source servers (stamped blobs);
        2. ``session_import`` on both destination servers — stamp-
           verified, validate-before-mutate, and the destination
           re-keys its own per-session base-OT/coin-flip plane;
        3. rebind the driver to ``new_lead`` and replay the journal in
           mirror form: recorded verdicts dedup everything the export
           already carried, so every in-flight ``sub_id`` lands exactly
           once;
        4. re-seal the sealed windows with the ORIGINAL banked challenge
           roots (a migrated malicious window re-opens the IDENTICAL
           challenge — never a second opening);
        5. bank a fresh ingest checkpoint on the destination, then
           retire the source copy (drops its retained pools — satellite
           of the bounded-retention contract)."""
        async with self._submit_lock:
            async with self._recover_lock:
                old = self.lead
                x0 = await old.c0.call("session_export", {})
                x1 = await old.c1.call("session_export", {})
                await new_lead.c0.call("session_import", {
                    "path": x0["path"], "boot": x0["boot"],
                    "epoch": x0["epoch"],
                })
                await new_lead.c1.call("session_import", {
                    "path": x1["path"], "boot": x1["boot"],
                    "epoch": x1["epoch"],
                })
                # the destination pair is now authoritative-in-waiting:
                # rebind, then make its pools exactly-once complete
                new_lead.has_sketch = old.has_sketch
                for i, c in ((0, new_lead.c0), (1, new_lead.c1)):
                    new_lead._boot_ids[i] = getattr(c, "boot_id", None)
                self.lead = new_lead
                replayed = sum(len(v) for v in self._journal.values())
                await self._replay_journal(new_lead.c0, 0)
                await self._replay_journal(new_lead.c1, 1)
                for w in sorted(self._sealed):
                    req = {"window": w}
                    root = self._sealed[w].get("sk_root")
                    if root is not None:
                        req["sk_root"] = root
                    await new_lead._both("window_seal", req)
                if self._ckpt:
                    try:
                        await new_lead._both(
                            "tree_checkpoint",
                            {"level": -1, "ingest_only": True},
                        )
                        self._have_ckpt = True
                    except RuntimeError as e:
                        self._ckpt = False
                        obsmod.emit(
                            "ingest.checkpoint_disabled", severity="warn",
                            error=str(e),
                        )
                # LAST: drop the source copy (both halves) — only after
                # the destination holds the complete, re-sealed state
                await old.c0.call(
                    "session_export",
                    {"retire": True, "epoch": x0["epoch"]},
                )
                await old.c1.call(
                    "session_export",
                    {"retire": True, "epoch": x1["epoch"]},
                )
                windows = [int(w) for w in x0.get("windows", [])]
        self.obs.count("ingest_migrations")
        obsmod.emit(
            "ingest.session_migrated", windows=windows, replayed=replayed,
        )
        return {"windows": windows, "replayed": replayed}

    async def failover_to(self, new_lead: RpcLeader, *,
                          level: int = -1) -> dict:
        """Whole-host failover: the source pair is DEAD (dead boot id on
        probe — fleet.FleetDirectory.probe) — adopt ``new_lead``'s
        surviving pair from this session's newest banked checkpoint in
        the shared store.  Same machinery as :meth:`migrate` minus the
        export/retire half: ``session_import`` of the ``level``-stamped
        ingest blob (the one seal_window banks at every boundary), then
        journal replay + re-seal with the original challenge roots.  A
        session that never checkpointed still recovers: the journal
        replay alone rebuilds every pool positionally."""
        async with self._submit_lock:
            async with self._recover_lock:
                new_lead.has_sketch = self.lead.has_sketch
                imported = False
                for c in (new_lead.c0, new_lead.c1):
                    if self._have_ckpt:
                        try:
                            await c.call("session_import", {"level": level})
                            imported = True
                        except RuntimeError as e:
                            obsmod.emit(
                                "ingest.restore_failed", severity="warn",
                                error=str(e),
                            )
                for i, c in ((0, new_lead.c0), (1, new_lead.c1)):
                    new_lead._boot_ids[i] = getattr(c, "boot_id", None)
                self.lead = new_lead
                replayed = sum(len(v) for v in self._journal.values())
                await self._replay_journal(new_lead.c0, 0)
                await self._replay_journal(new_lead.c1, 1)
                for w in sorted(self._sealed):
                    req = {"window": w}
                    root = self._sealed[w].get("sk_root")
                    if root is not None:
                        req["sk_root"] = root
                    await new_lead._both("window_seal", req)
        self.obs.count("ingest_failovers")
        obsmod.emit(
            "ingest.session_failed_over", imported=imported,
            replayed=replayed,
        )
        return {"imported": imported, "replayed": replayed}


# ---------------------------------------------------------------------------
# Multi-tenant: N concurrent collections against ONE server pair
# ---------------------------------------------------------------------------


class MultiCollectionDriver:
    """Run N collections CONCURRENTLY against one collector server pair
    (the multi-tenant driver of ROADMAP items b/c).

    Each job gets its own client pair — the ``collection`` field of the
    ``__hello__`` handshake binds every connection to its
    per-collection server session (protocol/sessions.py) — and its own
    :class:`RpcLeader`, then all jobs run concurrently on one event
    loop: while tenant A's level waits on the GC/OT wire, tenant B's
    expand dispatches (the servers' TenantScheduler counts those stall
    fills).  Results are bit-identical to solo runs of the same keys by
    construction: independent trees, independent FSS keys, independent
    OT streams per session — asserted end-to-end in
    tests/test_sessions.py and gated in ``bench_multitenant``."""

    def __init__(self, cfg: Config, host0: str, port0: int,
                 host1: str, port1: int, *, min_bucket: int = 1,
                 budgets: respolicy.VerbBudgets | None = None):
        self.cfg = cfg
        self._addr0 = (host0, port0)
        self._addr1 = (host1, port1)
        self.min_bucket = min_bucket
        self.budgets = budgets
        self.leaders: dict[str, RpcLeader] = {}

    async def open(self, collection: str) -> RpcLeader:
        """Connect one collection's client pair and build its leader
        (``reset`` included, so the session starts clean)."""
        kw = {"collection": collection}
        if self.budgets is not None:
            kw["budgets"] = self.budgets
        c0 = await CollectorClient.connect(*self._addr0, **kw)
        c1 = await CollectorClient.connect(*self._addr1, **kw)
        lead = RpcLeader(self.cfg, c0, c1, min_bucket=self.min_bucket)
        await lead._both("reset")
        self.leaders[collection] = lead
        return lead

    async def close(self) -> None:
        for lead in self.leaders.values():
            for c in (lead.c0, lead.c1):
                await c.aclose()
        self.leaders.clear()

    async def run_collections(self, jobs: list, *, supervised: bool = True,
                              warmup: bool = False,
                              checkpoint_every: int = 8) -> dict:
        """Run every job concurrently; returns {collection: CrawlResult}.

        ``jobs``: list of dicts ``{collection, nreqs, keys0, keys1}``
        with optional ``sketch0``/``sketch1`` (malicious mode).
        ``supervised`` routes through :meth:`RpcLeader.run_supervised`
        (per-tenant checkpoints in the session's own namespace,
        per-tenant recovery); False runs the bare upload+run path.  A
        single tenant's failure does not tear the others down — it is
        reported under its collection key as the raised exception."""

        async def one(job: dict):
            key = str(job["collection"])
            lead = self.leaders.get(key) or await self.open(key)
            if supervised:
                return await lead.run_supervised(
                    int(job["nreqs"]), job["keys0"], job["keys1"],
                    job.get("sketch0"), job.get("sketch1"),
                    checkpoint_every=checkpoint_every, warmup=warmup,
                )
            await lead.upload_keys(
                job["keys0"], job["keys1"],
                job.get("sketch0"), job.get("sketch1"),
            )
            if warmup:
                await lead.warmup()
            return await lead.run(int(job["nreqs"]))

        keys = [str(j["collection"]) for j in jobs]
        done = await asyncio.gather(
            *(one(j) for j in jobs), return_exceptions=True
        )
        results = dict(zip(keys, done))
        failed = {k: r for k, r in results.items() if isinstance(r, BaseException)}
        if failed:
            obsmod.emit(
                "tenancy.collections_failed",
                severity="warn",
                collections=sorted(failed),
                errors={k: f"{type(e).__name__}: {e}" for k, e in failed.items()},
            )
        return results
