"""Secure server↔server data plane: batched GC equality + OT b2a conversion.

This is the 2PC core the reference runs inside ``tree_crawl``
(ref: src/collect.rs:419-482 driving src/equalitytest.rs:25-191 and ocelot
OT): per (node, client), the two servers hold share-bit strings that agree
on every compared position iff the client's ball contains the node's box;
a garbled-circuit equality test XOR-shares that predicate, and a 1-of-2 OT
converts each XOR share into an additive field share via the ``r1 - r0 = 1``
trick (collect.rs:439-471), so per-node counts can be summed as field
shares neither server can open alone.

TPU-native shape: everything is batched device tensors —

- strings for ALL (node, child-pattern, client) triples come from one
  bit-extraction on the packed share-bit tensor (``child_strings``);
- one ``garble_equality_delta`` garbles the whole batch; evaluator input
  labels ride the IKNP Δ-OT (ops/otext.py) with ``R = s``, so label
  delivery costs one u-matrix message (vs the reference's per-wire OT);
- the b2a payloads travel under chosen-payload OT pads from the same
  extension session; FE62 payloads are one 128-bit block, F255 payloads
  two blocks — the reference's ``Block`` vs ``BlockPair`` split
  (collect.rs:439-471 vs 775-916);
- per-node share sums are alive-gated field reductions on device
  (collect.rs:487-501's ``add_lazy`` loop as one ``field.sum``).

The step functions here are sans-IO.  protocol/rpc.py strings the
WHOLE-LEVEL flow over the data-plane socket (ev u-matrix → gb planar
message — the 1-of-2^S payload table for S ≤ ``OT2S_MAX_S``, else the
packed garbled batch with the b2a payloads riding the output labels —
ONE round trip and ONE fused device program per side per level, see
``gb_step_level``/``ev_open_level`` below; the older flat-wire
``gb_step_fused``/``gb_step_ot4`` forms remain as parity oracles);
parallel/mesh.py runs the explicit two-round math (ev u → gb batch →
ev b2a u → gb ciphertexts) with ``ppermute`` transfers on the 2-chip
axis, where an extra round costs microseconds, not tunnel RTTs.

Wire-share semantics: the garbler's per-test share is ``r1 = r0 ± 1``
(+1 when server 0 garbles, −1 when server 1 does — the garbler flips per
level, ref rpc.rs:20-23); the evaluator receives ``r0`` when the strings
are equal, else ``r1`` — so ``v0 - v1 = [x == y]`` per test REGARDLESS
of which side garbled, and summed shares reconstruct counts exactly like
``keep_values`` (collect.rs:945-964).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import gc, otext, prg
from ..ops.fields import F255, FE62

# ---------------------------------------------------------------------------
# String extraction: packed share bits -> per-(node, pattern, client) strings
# ---------------------------------------------------------------------------


def _string_positions(d: int) -> np.ndarray:
    """uint32[2^d, 2d] — packed-bit positions of child pattern c's compared
    string, ordered (dim-major, side minor): the tensor twin of the
    reference's left||right bit-string layout (collect.rs:393-410), reading
    direction ``(c >> j) & 1`` per dim (child order: lib.rs:125-129)."""
    out = np.empty((1 << d, 2 * d), np.uint32)
    for c in range(1 << d):
        k = 0
        for j in range(d):
            r = (c >> j) & 1
            for s in range(2):
                out[c, k] = j * 4 + s * 2 + r
                k += 1
    return out


@partial(jax.jit, static_argnames=("d",))
def child_strings(packed: jax.Array, d: int) -> jax.Array:
    """uint32[F, N] packed share bits -> bool[F, 2^d, N, 2d] strings."""
    pos = jnp.asarray(_string_positions(d))  # [C, S]
    return ((packed[:, None, :, None] >> pos[None, :, None, :]) & 1).astype(bool)


def _string_positions_radix(d: int, radix: int) -> np.ndarray:
    """uint32[2^(radix*d), 2*d*radix] — packed-bit positions of fused child
    pattern c's compared string under the radix layout (collect.py
    ``_radix_positions``).  The fused string is the step-major concatenation
    of the per-depth membership strings along c's path: column
    k = t*2d + j*2 + s holds dim j / side s of the depth-(t+1) node reached
    by steps 0..t.  Equality over the concatenation == AND of the per-depth
    equalities, which is what makes fused pruning identical to k sequential
    radix-1 prunes.  Reduces to ``_string_positions`` columns at radix=1."""
    if radix == 1:
        return _string_positions(d)
    T = (1 << (radix + 1)) - 2  # packed bits per (dim, side)
    C = 1 << (radix * d)
    out = np.empty((C, 2 * d * radix), np.uint32)
    for c in range(C):
        node = [0] * d
        k = 0
        for t in range(radix):
            base = (2 << t) - 2  # offset of depth-(t+1) nodes in the subtree
            for j in range(d):
                node[j] |= ((c >> (t * d + j)) & 1) << t
                for s in range(2):
                    out[c, k] = j * 2 * T + s * T + base + node[j]
                    k += 1
    return out


@partial(jax.jit, static_argnames=("d", "radix"))
def _child_strings_radix_jit(packed: jax.Array, d: int, radix: int) -> jax.Array:
    pos = jnp.asarray(_string_positions_radix(d, radix))  # [C, S']
    return ((packed[:, None, :, None] >> pos[None, :, None, :]) & 1).astype(bool)


def child_strings_radix(packed: jax.Array, d: int, radix: int) -> jax.Array:
    """uint32[F, N] radix-packed share bits -> bool[F, 2^(radix*d), N,
    2*d*radix] fused strings.  radix=1 delegates to ``child_strings`` so a
    k=1 crawl hits the exact compiled program it always has."""
    if radix == 1:
        return child_strings(packed, d)
    return _child_strings_radix_jit(packed, d, radix)


# ---------------------------------------------------------------------------
# Field payload codecs (OT payload width: FE62 one block, F255 two blocks)
# ---------------------------------------------------------------------------


def payload_words(field) -> int:
    return 8 if field is F255 else 4


def field_to_words(field, v) -> jax.Array:
    b = field.to_blocks(v)
    if field is F255:
        return b.reshape(b.shape[:-2] + (8,))
    return b


def words_to_field(field, w) -> jax.Array:
    if field is F255:
        return field.from_blocks(w.reshape(w.shape[:-1] + (2, 4)))
    return field.from_blocks(w)


def derive_seed(base: np.ndarray, purpose: int, level: int, ctr: int = 0) -> np.ndarray:
    """Per-(purpose, level, crawl-counter) PRG seed from a session seed."""
    s = np.array(base, np.uint32, copy=True)
    s[1] ^= np.uint32(ctr)
    s[2] ^= np.uint32(purpose)
    s[3] ^= np.uint32(level)
    return s


# ---------------------------------------------------------------------------
# Protocol steps (sans-IO).  Roles: garbler = server 0 (gc_sender=true,
# ref: leader.rs:204-205 pins the role per request), evaluator = server 1.
# ---------------------------------------------------------------------------


def ev_step1(rcv: otext.OtExtReceiver, y_flat):
    """Evaluator: request input labels.  y_flat bool[B, S] -> (u message,
    T rows uint32[B*S, 4] — the Δ-OT labels-to-be).  ``y_flat`` may stay
    a DEVICE array — fetching it first costs a tunnel round trip and the
    extension consumes it on device anyway."""
    B, S = y_flat.shape
    u, t = rcv.extend(jnp.reshape(jnp.asarray(y_flat), (B * S,)))
    return u, t


def gb_step1(snd: otext.OtExtSender, u_msg, x_flat, gc_seed):
    """Garbler: derive evaluator zero-labels from the extension and garble.

    Returns (batch to send, mask bool[B] — the garbler's XOR shares)."""
    B, S = x_flat.shape
    q = snd.extend(B * S, u_msg)
    y0 = q.reshape(B, S, 4)
    return gc.garble_equality_delta(
        jnp.asarray(snd.s_block), y0, jnp.asarray(gc_seed), x_flat
    )


def ev_step2(batch: gc.GarbledEqBatch, t_rows, B: int, S: int) -> jax.Array:
    """Evaluator: labels are the Δ-OT T rows; evaluate -> XOR shares bool[B]."""
    return gc.eval_equality(batch, jnp.asarray(t_rows).reshape(B, S, 4))


def ev_step3(rcv: otext.OtExtReceiver, e_bits):
    """Evaluator: open the b2a OT with its GC output shares as choices.
    Returns (u message, T2 rows, idx0 — the pad tweak base).  ``e_bits``
    may stay a device array (see ev_step1)."""
    idx0 = rcv.consumed
    u2, t2 = rcv.extend(jnp.asarray(e_bits))
    return u2, t2, idx0


def b2a_payload_pair(field, b2a_seed, B: int, garbler: int):
    """The sender's b2a share pair, in ONE place for every flow: sample
    ``r0`` from the seed stream, keep ``r1 = r0 ± 1`` — +1 when server 0
    is the sender, −1 when server 1 is — so the leader's uniform
    ``v0 - v1`` reconstruction holds whichever server sends
    (collect.rs:439-456's ordering with the alternating-garbler sign).
    Returns (r1 — the sender's additive shares, w0, w1 — the two payloads
    as OT words)."""
    W = payload_words(field)
    r_words = prg.stream_words(jnp.asarray(b2a_seed, jnp.uint32), B * W).reshape(B, W)
    r0 = field.sample(r_words)
    one = field.from_int(1)
    r1 = field.sub(r0, one) if garbler else field.add(r0, one)
    return r1, field_to_words(field, r0), field_to_words(field, r1)


def b2a_encrypt(field, q2_rows, s_block, mask, b2a_seed, idx0, garbler: int = 0):
    """Stateless b2a sender core: sample (r0, r1 = r0 ± 1), order payloads
    by ``mask`` (collect.rs:439-456), encrypt under the OT pads derived
    from the Q rows.  Returns (c0, c1 ciphertext words [B, W], r1 — the
    sender's additive shares).  Shared by the socket path (gb_step2) and
    the mesh kernel (parallel/mesh.py) so the trick lives in exactly one
    place."""
    mask = jnp.asarray(mask, bool)
    B = mask.shape[0]
    W = payload_words(field)
    q2_rows = jnp.asarray(q2_rows)
    pad0 = otext.ot_hash(q2_rows, W, idx0)
    pad1 = otext.ot_hash(q2_rows ^ jnp.asarray(s_block), W, idx0)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    m0 = jnp.where(mask[:, None], w0, w1)
    m1 = jnp.where(mask[:, None], w1, w0)
    return m0 ^ pad0, m1 ^ pad1, r1


def b2a_decrypt(field, t2_rows, idx0, c0, c1, e_bits):
    """Stateless b2a receiver core: decrypt the choice-side ciphertext with
    the T-row pad -> field values (r0 where equal, r1 where not)."""
    W = payload_words(field)
    pad = otext.ot_hash(jnp.asarray(t2_rows), W, idx0)
    e = jnp.asarray(e_bits, bool)
    ct = jnp.where(e[:, None], jnp.asarray(c1), jnp.asarray(c0))
    return words_to_field(field, ct ^ pad)


def gb_step2(snd: otext.OtExtSender, u2_msg, mask, b2a_seed, field, garbler: int = 0):
    """Garbler: extend the b2a OT — extension and pad hash as ONE jitted
    program (:meth:`otext.OtExtSender.extend_pads`) — and encrypt the
    ordered payload pair under the pads.  Bit-identical to the
    :func:`b2a_encrypt` form (same hash, same index base), which the
    mesh keeps for its in-jit collective flow.

    Returns (c0, c1 ciphertext words [B, W], field values [B] — the
    garbler's additive shares, always r1 = r0 ± 1 by ``garbler`` side)."""
    mask = jnp.asarray(mask, bool)
    B = mask.shape[0]
    W = payload_words(field)
    _, pad0, pad1 = snd.extend_pads(B, u2_msg, W)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    m0 = jnp.where(mask[:, None], w0, w1)
    m1 = jnp.where(mask[:, None], w1, w0)
    return m0 ^ pad0, m1 ^ pad1, r1


def ev_step4(rcv: otext.OtExtReceiver, t2_rows, idx0, c0, c1, e_bits, field):
    """Evaluator: decrypt its chosen payload -> field values [B] (its
    additive shares: r0 where equal, r1 where not)."""
    return b2a_decrypt(field, t2_rows, idx0, c0, c1, e_bits)


# ---------------------------------------------------------------------------
# 1-of-2^S fast path: equality via chosen-payload OT (no garbled circuit)
# ---------------------------------------------------------------------------
#
# Each equality test compares S = 2·n_dims bits (two interval sides per
# dim).  The full GC machinery (S-1 AND gates, 4 garble + 2 eval hashes
# per gate, tables + labels + decode on the wire) exists to compute
# [x == y] for S-bit x, y.  But an S-bit y is a 1-of-2^S choice, and the
# test's S Δ-OT rows (t_j = q_j ^ y_j·s) already encode it: combining
# the rows with distinct GF(2^128) coefficients, T = ⊕_j x^j·t_j =
# Q ^ o_y where Q = ⊕_j x^j·q_j and o_c = ⊕_j c_j·x^j·s, gives the
# receiver exactly ONE of the 2^S sender-computable pads H(Q ^ o_c) —
# the offsets are pairwise distinct for any s != 0 because the doubling
# ladder is a basis of an S-dimensional subspace (otext.gf128_offsets).
# The sender encrypts payload m_{[x == c]} under pad c; the receiver
# opens pad y and learns m_{[x == y]} — the whole equality test +
# payload b2a in 2^S + 1 hashes/test and ZERO garbling, so
# multi-dimensional crawls skip the garbled circuit entirely on the
# equality test.  This is the classic 1-of-N OT-extension pad
# construction (Kolesnikov-Kumaresan 2013 shape) under the same
# circular-correlation-robust-hash assumption the Δ-OT pads and the GC
# fused payload already rest on (every pad offset is a fixed GF(2^128)-
# linear function of s).
#
# Cost crossover: per test the table costs 2^S·W ciphertext words vs the
# GC batch's (S-1)·8 + 4S + 1 + 2W — at S = 2 the table is ~40% of the
# GC bytes and 5 vs 9 hashes; at S = 6 it is ~3.5x the bytes but still
# ~1/3 the hash count and no tree — ``OT2S_MAX_S`` caps the auto path at
# the point where the 2^S table stops paying (beyond it the GC path,
# whose wire is linear in S, takes over).  The GC path also remains the
# arbitrary-S fallback and the reference-parity oracle; ``EQ_OT4``
# (historical name, kept because tests and deployments toggle it) turns
# the fast path off entirely.

EQ_OT4: bool = True

# auto-path ceiling for the 1-of-2^S table (S = 2·n_dims; 6 covers the
# 3-dim roadmap workloads).  Protocol-legal up to 128; the 2^S·W wire
# and HBM growth is why the default stops at 6.
OT2S_MAX_S: int = 6

# Engine flag for the planar ot2s kernels (ops/otext_pallas.py), exactly
# like gc.GC_PALLAS: True routes the packed encrypt/decrypt through the
# fused Pallas kernels on a real chip; CPU hosts always run the XLA
# twins.  Wire bytes are engine-independent (parity-tested).
OT2S_PALLAS: bool = True

_OT2S_DOMAIN = 0x0F4E4F54  # ot_hash tweak-domain of the per-test pads
_OT4_DOMAIN = _OT2S_DOMAIN  # historical alias


def _ot2s_pallas_engine() -> bool:
    from ..utils import effective_platform

    return OT2S_PALLAS and effective_platform() != "cpu"


def ot_path(S: int, override: str = "auto") -> str:
    """Which equality-test engine a level of string width ``S`` runs:
    ``"ot2s"`` (1-of-2^S chosen-payload OT) or ``"gc"`` (garbled
    circuit).  ``override`` is the config knob (utils/config.Config
    ``ot_path``): "auto" picks ot2s for S <= OT2S_MAX_S (unless EQ_OT4
    is off), "ot2s"/"gc" force a path — forcing ot2s past the ceiling is
    a loud error rather than a silent 2^S blowup.  Both servers derive
    the path from the same (cfg, S), so the wire format always agrees."""
    if override == "gc":
        return "gc"
    if override == "ot2s":
        if S > OT2S_MAX_S:
            raise ValueError(
                f"ot_path='ot2s' forced at S={S}: the 1-of-2^S table is "
                f"capped at S={OT2S_MAX_S} (2^S ciphertexts per test) — "
                "use the GC path for wider strings"
            )
        return "ot2s"
    if override != "auto":
        raise ValueError(f"unknown ot_path {override!r}")
    return "ot2s" if (EQ_OT4 and 2 <= S <= OT2S_MAX_S) else "gc"


def _ot4_use(S: int) -> bool:
    """Historical predicate (bench/tests): does the auto path skip GC?"""
    return ot_path(S) == "ot2s"


@partial(jax.jit, static_argnames=("n_words",))
def ot2s_encrypt(q_rows, s_block, x_flat, m_v0, m_v1, n_words: int,
                 idx_offset):
    """Sender side: q_rows uint32[B, S, 4] (this batch's extension rows),
    x_flat bool[B, S] (the sender's share-bit strings), payloads
    m_v0/m_v1 uint32[B, n_words] for result 0 / 1.  Returns cts
    uint32[2^S, B, n_words] indexed by the receiver's string as a
    little-endian S-bit integer c = Σ y_j·2^j."""
    q_rows = jnp.asarray(q_rows, jnp.uint32)
    x_flat = jnp.asarray(x_flat, bool)
    S = q_rows.shape[1]
    comb = otext.gf128_comb(q_rows)  # [B, 4] = ⊕ x^j·q_j
    offs = otext.gf128_offsets(jnp.asarray(s_block, jnp.uint32), S)
    x_int = jnp.zeros(x_flat.shape[0], jnp.uint32)
    for j in range(S):
        x_int = x_int | (x_flat[:, j].astype(jnp.uint32) << j)
    pads = otext.ot_hash(
        comb[None] ^ offs[:, None, :], n_words, idx_offset,
        domain=_OT2S_DOMAIN,
    )  # [2^S, B, n_words]
    eq = jnp.arange(1 << S, dtype=jnp.uint32)[:, None] == x_int[None]
    m = jnp.where(
        eq[..., None], jnp.asarray(m_v1, jnp.uint32)[None],
        jnp.asarray(m_v0, jnp.uint32)[None],
    )
    return m ^ pads


@partial(jax.jit, static_argnames=("n_words",))
def ot2s_decrypt(t_rows, y_flat, cts, n_words: int, idx_offset):
    """Receiver side: t_rows uint32[B, S, 4], y_flat bool[B, S] (its own
    share-bit strings — the extension's choice bits), cts uint32[2^S, B,
    n_words].  Returns uint32[B, n_words] = m_{[x == y]} per test."""
    t_rows = jnp.asarray(t_rows, jnp.uint32)
    y_flat = jnp.asarray(y_flat, bool)
    S = t_rows.shape[1]
    comb = otext.gf128_comb(t_rows)  # [B, 4] = Q ^ o_y
    pad = otext.ot_hash(comb, n_words, idx_offset, domain=_OT2S_DOMAIN)
    y_int = jnp.zeros(y_flat.shape[0], jnp.uint32)
    for j in range(S):
        y_int = y_int | (y_flat[:, j].astype(jnp.uint32) << j)
    # one-hot select instead of take_along_axis: the gather lowers poorly
    # on TPU (measured 1.5x slower at the flagship 524288-test batch)
    sel = (
        jnp.arange(1 << S, dtype=jnp.uint32)[:, None] == y_int[None]
    ).astype(jnp.uint32)
    ct = jnp.sum(
        jnp.asarray(cts, jnp.uint32) * sel[..., None], axis=0,
        dtype=jnp.uint32,
    )
    return ct ^ pad


def ot4_encrypt(q_rows, s_block, x_flat, m_v0, m_v1, n_words: int, idx_offset):
    """The S = 2 specialization, kept under its historical name — now a
    view of :func:`ot2s_encrypt` (identical bits: gf128_comb/offsets at
    S = 2 reproduce the original {0, s, 2s, s^2s} table)."""
    return ot2s_encrypt(q_rows, s_block, x_flat, m_v0, m_v1, n_words,
                        idx_offset)


def ot4_decrypt(t_rows, y_flat, cts, n_words: int, idx_offset):
    """S = 2 view of :func:`ot2s_decrypt` (historical name)."""
    return ot2s_decrypt(t_rows, y_flat, cts, n_words, idx_offset)


def gb_step_ot4(snd: otext.OtExtSender, u_msg, x_flat, b2a_seed, field,
                garbler: int = 0):
    """Garbler/sender level step on the S = 2 fast path: extend the Δ-OT,
    derive (r0, r1 = r0 ± 1), and encrypt the 1-of-4 payload table — the
    whole level in one message (cts ravel), like :func:`gb_step_fused`.

    Returns (cts uint32[4, B, W], vals — the sender's additive shares)."""
    x_flat = jnp.asarray(x_flat, bool)
    B, S = x_flat.shape
    assert S == 2, "ot4 path is the S == 2 specialization"
    idx0 = snd.consumed
    q = snd.extend(B * S, u_msg)
    W = payload_words(field)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    # result 1 (strings equal) -> receiver learns r0 (collect.rs:439-456)
    cts = ot4_encrypt(
        q.reshape(B, S, 4), jnp.asarray(snd.s_block), x_flat, w1, w0, W, idx0
    )
    return cts, r1


def ev_open_ot4(rcv: otext.OtExtReceiver, t_rows, y_flat, msg, B: int,
                field, idx0: int):
    """Receiver twin of :func:`gb_step_ot4`: open the 1-of-4 table with the
    combined T rows -> field values [B] (r0 where equal, else r1)."""
    W = payload_words(field)
    cts = jnp.asarray(msg).reshape(4, B, W)
    w = ot4_decrypt(jnp.asarray(t_rows).reshape(B, 2, 4), y_flat, cts, W, idx0)
    return words_to_field(field, w)


# ---------------------------------------------------------------------------
# WHOLE-LEVEL packed flow: one device program per side, planar wire
# ---------------------------------------------------------------------------
#
# The deployment flow (protocol/rpc.py since round 6): every (node,
# pattern, client) test of a level rides ONE message built by ONE fused
# device program per side — the 1-of-2^S table for S <= OT2S_MAX_S, the
# packed garbled batch (b2a payloads under the output labels) beyond it.
# The wire format is the PLANAR plane layout of ops/gc_pallas.py /
# ops/otext_pallas.py, padded to ``padded_tests(B)`` tests: on a real
# chip the buffer is the fused kernel's output raveled in place (no
# test-major transposes between garbling and the fetch, none between the
# upload and evaluation), and the XLA twins emit byte-identical planes so
# the format is engine-independent.  ``idx0`` is the extension session's
# pre-batch consumed counter on BOTH sides, as everywhere else.
#
# Security note on the PAD SLOTS: the padded tests garble/encrypt
# zero-valued inputs, so the wire's pad region publishes hashes of
# offset-only inputs (H(o_c, idx) for the ot2s table; a degenerate
# known-input garbled instance for the GC batch).  Both are query shapes
# the circular-correlation-robust-hash assumption already covers — the
# receiver's legitimate queries have the same linear-in-s structure —
# and the receiver discards the slots; B itself is public protocol
# state, so the pad boundary reveals nothing new.


@partial(jax.jit, static_argnames=("n_words",))
def _ot2s_encrypt_packed_xla(q_rows, s_block, x_flat, m_v0, m_v1,
                             n_words: int, idx_offset):
    from ..ops import gc_pallas
    from ..ops.gc import _pad_tests

    B = q_rows.shape[0]
    bp = gc_pallas.padded_tests(B)
    # encrypt the zero-padded slots too (exactly the kernel's padded
    # planar inputs), so the wire buffer is byte-identical per engine
    cts = ot2s_encrypt(
        _pad_tests(q_rows, bp), s_block, _pad_tests(x_flat, bp),
        _pad_tests(m_v0, bp), _pad_tests(m_v1, bp), n_words, idx_offset,
    )
    p = gc_pallas._planarize(jnp.transpose(cts, (1, 0, 2)), bp, bp)
    return jnp.ravel(p)


@partial(jax.jit, static_argnames=("S", "n_words"))
def _ot2s_decrypt_packed_xla(t_rows, y_flat, msg, S: int, n_words: int,
                             idx_offset):
    from ..ops import gc_pallas

    B = t_rows.shape[0]
    bp = gc_pallas.padded_tests(B)
    planes = jnp.asarray(msg, jnp.uint32).reshape(
        (1 << S) * n_words, bp // gc_pallas.GROUP,
        gc_pallas.SUB, gc_pallas.LANES,
    )
    cts = gc_pallas._unplanarize(planes, B).reshape(B, 1 << S, n_words)
    return ot2s_decrypt(
        t_rows, y_flat, jnp.transpose(cts, (1, 0, 2)), n_words, idx_offset
    )


def ot2s_encrypt_packed(q_rows, s_block, x_flat, m_v0, m_v1,
                        n_words: int, idx_offset):
    """Engine dispatcher: planar-wire 1-of-2^S sender table (fused Pallas
    kernel on a real chip, byte-identical XLA twin elsewhere)."""
    q_rows = jnp.asarray(q_rows, jnp.uint32)
    if _ot2s_pallas_engine():
        from ..ops import otext_pallas

        return otext_pallas.ot2s_encrypt(
            q_rows, s_block, x_flat, m_v0, m_v1, n_words, idx_offset,
            domain=_OT2S_DOMAIN,
        )
    return _ot2s_encrypt_packed_xla(
        q_rows, jnp.asarray(s_block, jnp.uint32), jnp.asarray(x_flat, bool),
        jnp.asarray(m_v0, jnp.uint32), jnp.asarray(m_v1, jnp.uint32),
        n_words, idx_offset,
    )


def ot2s_decrypt_packed(t_rows, y_flat, msg, n_words: int, idx_offset):
    """Engine dispatcher twin: open the planar 1-of-2^S table ->
    uint32[B, n_words]."""
    t_rows = jnp.asarray(t_rows, jnp.uint32)
    if _ot2s_pallas_engine():
        from ..ops import otext_pallas

        return otext_pallas.ot2s_decrypt(
            t_rows, y_flat, msg, n_words, idx_offset, domain=_OT2S_DOMAIN
        )
    return _ot2s_decrypt_packed_xla(
        t_rows, jnp.asarray(y_flat, bool), msg, t_rows.shape[1], n_words,
        idx_offset,
    )


def gb_step_level(snd: otext.OtExtSender, u_msg, x_flat, gc_seed, b2a_seed,
                  field, garbler: int = 0, path: str = "auto"):
    """Garbler/sender whole-level step: extend the Δ-OT, derive the b2a
    share pair, and build the level's ONE planar message — the 1-of-2^S
    table or the packed garbled batch, by :func:`ot_path`.

    Returns (msg, vals — the sender's additive shares r1 = r0 ± 1)."""
    x_flat = jnp.asarray(x_flat, bool)
    B, S = x_flat.shape
    p = ot_path(S, path)
    idx0 = snd.consumed
    q = snd.extend(B * S, u_msg)
    W = payload_words(field)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    # result 1 (strings equal) -> receiver learns r0 (collect.rs:439-456)
    if p == "ot2s":
        msg = ot2s_encrypt_packed(
            q.reshape(B, S, 4), jnp.asarray(snd.s_block), x_flat, w1, w0,
            W, idx0,
        )
    else:
        msg, _ = gc.garble_equality_payload_packed(
            jnp.asarray(snd.s_block), q.reshape(B, S, 4),
            jnp.asarray(gc_seed), x_flat, w1, w0, W, idx0,
        )
    return msg, r1


def ev_open_level(t_rows, y_flat, msg, B: int, S: int, field, idx0: int,
                  path: str = "auto"):
    """Evaluator/receiver whole-level twin: open the planar message with
    the Δ-OT T rows -> field values [B] (r0 where equal, else r1)."""
    p = ot_path(S, path)
    W = payload_words(field)
    if p == "ot2s":
        w = ot2s_decrypt_packed(
            jnp.asarray(t_rows).reshape(B, S, 4), y_flat, msg, W, idx0
        )
    else:
        _, w = gc.eval_equality_payload_packed(
            msg, jnp.asarray(t_rows).reshape(B, S, 4), W, idx0
        )
    return words_to_field(field, w)


# ---------------------------------------------------------------------------
# FUSED socket flow: the b2a payloads ride the GC output labels
# ---------------------------------------------------------------------------
#
# The two-round flow above (ev u -> gb batch -> ev u2 -> gb ciphertexts)
# follows the reference's GC-then-OT structure (collect.rs:419-482).  But
# the evaluator's b2a choice bit is exactly its GC output share — and its
# garbled OUTPUT LABEL already encodes that choice 1-of-2 (labels differ
# by R with the select bit in the lsb).  Encrypting the two payloads under
# the two possible output labels (ops/gc.garble_equality_payload) delivers
# the b2a OT for free inside the garbled batch: ONE protocol round trip
# per level (ev u -> gb batch+cts), one fetch fewer on each side — through
# a remote-chip tunnel each removed fetch is a full ~0.1 s RTT.  Security
# rests on the same circular-correlation-robust hash assumption as the
# Δ-OT pads (labels differ by R = s); the mesh path keeps the explicit
# two-round form (device-resident, RTT-free, and its collectives are
# already minimal).


def ev_step1_fused(rcv: otext.OtExtReceiver, y_flat):
    """Evaluator round 1: like :func:`ev_step1` but also captures the
    pre-extension consumed counter — the payload-pad index base both
    sides must agree on (the garbler captures the same value)."""
    idx0 = rcv.consumed
    u, t = ev_step1(rcv, y_flat)
    return u, t, idx0


def gb_step_fused(snd: otext.OtExtSender, u_msg, x_flat, gc_seed, b2a_seed,
                  field, garbler: int = 0):
    """Garbler: extend the input-label Δ-OT, garble, and attach the b2a
    payloads under the output labels — the whole level in one message.

    Returns (packed message, vals — the garbler's additive shares
    ``r1 = r0 ± 1`` by garbling side, as in :func:`b2a_encrypt`)."""
    x_flat = jnp.asarray(x_flat, bool)
    B, S = x_flat.shape
    idx0 = snd.consumed
    q = snd.extend(B * S, u_msg)
    W = payload_words(field)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    # v = 1 (strings equal) -> evaluator learns r0, else r1: the ordering
    # of collect.rs:439-456 with the choice implicit in the output label
    batch, cts, _ = gc.garble_equality_payload(
        jnp.asarray(snd.s_block), q.reshape(B, S, 4), jnp.asarray(gc_seed),
        x_flat, w1, w0, W, idx0,
    )
    return pack_gc_payload_batch(batch, cts), r1


def ev_open_fused(rcv: otext.OtExtReceiver, t_rows, msg, B: int, S: int,
                  field, idx0: int):
    """Evaluator round 2: evaluate the batch and open the payload under
    the output label -> field values [B] (r0 where equal, else r1)."""
    W = payload_words(field)
    batch, cts = unpack_gc_payload_batch(jnp.asarray(msg), B, S, W)
    _, w = gc.eval_equality_payload(
        batch, jnp.asarray(t_rows).reshape(B, S, 4), cts, W, idx0
    )
    return words_to_field(field, w)


# ---------------------------------------------------------------------------
# Wire packing: one buffer per message
# ---------------------------------------------------------------------------
#
# Through a remote-chip tunnel every device->host fetch costs a full round
# trip (~120 ms measured) regardless of size, so a message that fetches
# three arrays pays three RTTs.  Packing the garbled batch (and the b2a
# ciphertext pair) into ONE u32 vector on device makes each data-plane
# message one fetch + one pickle; the peer re-uploads once and slices on
# device.


@jax.jit
def pack_gc_batch(batch: gc.GarbledEqBatch) -> jax.Array:
    return jnp.concatenate([
        jnp.ravel(batch.tables),
        jnp.ravel(batch.gb_labels),
        jnp.ravel(batch.decode).astype(jnp.uint32),
    ])


@partial(jax.jit, static_argnames=("B", "S"))
def unpack_gc_batch(buf: jax.Array, B: int, S: int) -> gc.GarbledEqBatch:
    buf = jnp.asarray(buf)
    nt = B * (S - 1) * 2 * 4
    nl = B * S * 4
    return gc.GarbledEqBatch(
        tables=buf[:nt].reshape(B, S - 1, 2, 4),
        gb_labels=buf[nt : nt + nl].reshape(B, S, 4),
        decode=buf[nt + nl :] != 0,
    )


@jax.jit
def pack_gc_payload_batch(batch: gc.GarbledEqBatch, cts: jax.Array) -> jax.Array:
    return jnp.concatenate([pack_gc_batch(batch), jnp.ravel(cts)])


@partial(jax.jit, static_argnames=("B", "S", "W"))
def unpack_gc_payload_batch(buf: jax.Array, B: int, S: int, W: int):
    buf = jnp.asarray(buf)
    base = B * (S - 1) * 2 * 4 + B * S * 4 + B
    return unpack_gc_batch(buf[:base], B, S), buf[base:].reshape(2, B, W)


# ---------------------------------------------------------------------------
# Alive-gated per-node share sums (collect.rs:487-501)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("field",))
def node_share_sums(field, vals, weight) -> jax.Array:
    """vals: field elements [F, C, N(, limbs)]; weight: bool[F, C, N].
    Returns per-(node, pattern) share sums [F, C(, limbs)].  Dead clients
    and dead nodes are gated to zero — identically on both servers, since
    liveness flags and the frontier alive mask are public protocol state
    (ref: collect.rs:495)."""
    if field.limb_shape:
        vals = jnp.where(weight[..., None], vals, 0)
        return field.sum(vals, axis=2)
    vals = jnp.where(weight, vals, 0)
    return field.sum(vals, axis=2)


def alive_weight(alive_nodes, alive_keys, C: int) -> np.ndarray:
    """bool[F, C, N] gating weight from the public liveness masks."""
    a_n = np.asarray(alive_nodes, bool)
    a_k = np.asarray(alive_keys, bool)
    return np.broadcast_to(
        a_n[:, None, None] & a_k[None, None, :], (a_n.shape[0], C, a_k.shape[0])
    )


# ---------------------------------------------------------------------------
# Warmup: compile the per-shape kernel chain without touching live state
# ---------------------------------------------------------------------------


# one process-wide throwaway OT session for ALL warmup calls: the
# session exists only to drive compiles, and a fresh Chou-Orlandi base
# exchange costs ~1.5 s of host-side scalar crypto — paying it once per
# (bucket, level-kind, server) made warmup base-OT-bound.  Reuse is
# sound: every warm call is self-consistent (its own u/t/idx0), the
# counters just keep advancing, and nothing derived from the session
# ever leaves warm_level_kernels.
_warm_sessions: tuple | None = None


def _warm_pair():
    global _warm_sessions
    if _warm_sessions is None:
        _warm_sessions = otext.inprocess_pair()
    return _warm_sessions


def warm_level_kernels(packed, d: int, field, path: str = "auto",
                       share_sums=None, radix: int = 1) -> None:
    """Run the WHOLE per-level 2PC kernel chain — string extraction,
    Δ-OT extension, the b2a share pair (both garbling signs), the
    whole-level equality message (1-of-2^S table or packed garbled
    batch, whichever :func:`ot_path` picks for this shape under the
    config's ``path`` knob — the fused otext/gc programs included),
    payload open, alive-gated share sums — on a THROWAWAY in-process OT
    session, so every jit program a real level of this shape will
    dispatch is compiled (and lands in the persistent compile cache,
    utils/compile_cache) before measured crawl time starts.  The live OT
    sessions and the data plane are never touched; the outputs are
    discarded.

    The wire arrays (ev u-matrix, the sender's planar message) ROUND
    TRIP through host numpy exactly like the live socket path: jit
    executables key on input shardings/placements, so feeding the
    consumer the producer's still-on-device output would warm programs a
    live crawl — whose wire inputs arrive as host pickles — never
    dispatches.  Single-device runs happened to tolerate the mismatch;
    a multi-chip server (parallel/server_mesh.py) does not, because its
    device-side outputs carry mesh shardings the live host inputs lack.

    ``share_sums`` overrides the share-sum reduction (the multi-chip
    server passes its ICI-psum form, ``ServerMesh.node_share_sums``, so
    the sharded reduction program is warmed too); None = the
    single-device :func:`node_share_sums`.

    ``radix`` > 1 warms the fused radix-2^k shapes: the string stage
    reads the radix packed layout and the equality chain runs at the
    fused width S' = 2*d*radix (which may route through the GC ladder
    where the radix-1 shape took ot2s — :func:`ot_path` decides from
    S' exactly as the live crawl does)."""
    strs = child_strings_radix(packed, d, radix)
    F_, C, N, S = strs.shape
    B = F_ * C * N
    flat = strs.reshape(B, S)
    snd, rcv = _warm_pair()
    zero = np.zeros(4, np.uint32)
    gseed, bseed = derive_seed(zero, 1, 0), derive_seed(zero, 2, 0)
    u, t_rows, idx0 = ev_step1_fused(rcv, flat)
    u = np.asarray(u)  # wire round trip (see docstring)
    # the real crawl alternates the garbler per level, so each server
    # runs BOTH payload-pair signs (r0 + 1 and r0 - 1) at this shape
    for g in (0, 1):
        b2a_payload_pair(field, bseed, B, g)
    msg, _ = gb_step_level(snd, u, flat, gseed, bseed, field, 0, path=path)
    msg = np.asarray(msg)  # wire round trip (see docstring)
    vals = ev_open_level(t_rows, flat, msg, B, S, field, idx0, path=path)
    w = jnp.ones((F_, C, N), bool)
    reduce_fn = node_share_sums if share_sums is None else share_sums
    jax.block_until_ready(
        reduce_fn(field, vals.reshape((F_, C, N) + field.limb_shape), w)
    )


def warm_level_kernels_sharded(ks, packed, d: int, F: int, N: int, field,
                               path: str = "auto", radix: int = 1) -> None:
    """The :func:`warm_level_kernels` contract for a ROW-SHARDED kernel
    level (parallel/kernel_shard.py): compile the sharded flat builder,
    both roles of the row-sharded extension, the per-shard equality
    kernel at BOTH garbling signs (the live crawl alternates the garbler
    per level and the sign is a static of the compiled program), the
    per-shard open, and the scatter + ICI-psum share-sum program — on
    the same throwaway OT session, with the u-matrix and the planar
    frame round-tripping through host numpy exactly like the live
    socket path (per-shard assembly + re-upload included, so the
    device_put placements match live).  ``packed`` arrives in its live
    mesh sharding (the client-axis expansion layout)."""
    from ..parallel import kernel_shard

    flat = kernel_shard.shard_flat(ks, packed, d, F, N, radix)
    snd, rcv = _warm_pair()
    zero = np.zeros(4, np.uint32)
    gseed, bseed = derive_seed(zero, 1, 0), derive_seed(zero, 2, 0)
    p = ot_path(2 * d * radix, path)
    vals_r = None
    for g in (0, 1):
        _, _, _, vals_r = kernel_shard.run_level_pair(
            ks, snd, rcv, flat, flat, gseed, bseed, field, g, p
        )
    C = 1 << (d * radix)
    w = np.ones((F, C, N), bool)
    jax.block_until_ready(
        kernel_shard.share_sums(ks, field, vals_r, w, F, C, N)
    )
