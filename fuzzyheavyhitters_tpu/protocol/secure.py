"""Secure server↔server data plane: batched GC equality + OT b2a conversion.

This is the 2PC core the reference runs inside ``tree_crawl``
(ref: src/collect.rs:419-482 driving src/equalitytest.rs:25-191 and ocelot
OT): per (node, client), the two servers hold share-bit strings that agree
on every compared position iff the client's ball contains the node's box;
a garbled-circuit equality test XOR-shares that predicate, and a 1-of-2 OT
converts each XOR share into an additive field share via the ``r1 - r0 = 1``
trick (collect.rs:439-471), so per-node counts can be summed as field
shares neither server can open alone.

TPU-native shape: everything is batched device tensors —

- strings for ALL (node, child-pattern, client) triples come from one
  bit-extraction on the packed share-bit tensor (``child_strings``);
- one ``garble_equality_delta`` garbles the whole batch; evaluator input
  labels ride the IKNP Δ-OT (ops/otext.py) with ``R = s``, so label
  delivery costs one u-matrix message (vs the reference's per-wire OT);
- the b2a payloads travel under chosen-payload OT pads from the same
  extension session; FE62 payloads are one 128-bit block, F255 payloads
  two blocks — the reference's ``Block`` vs ``BlockPair`` split
  (collect.rs:439-471 vs 775-916);
- per-node share sums are alive-gated field reductions on device
  (collect.rs:487-501's ``add_lazy`` loop as one ``field.sum``).

The step functions here are sans-IO.  protocol/rpc.py strings the FUSED
flow over the data-plane socket (ev u-matrix → gb garbled batch with the
b2a payloads riding the output labels — ONE round trip per level, see
``gb_step_fused`` below); parallel/mesh.py runs the explicit two-round
math (ev u → gb batch → ev b2a u → gb ciphertexts) with ``ppermute``
transfers on the 2-chip axis, where an extra round costs microseconds,
not tunnel RTTs.

Wire-share semantics: the garbler's per-test share is ``r1 = r0 ± 1``
(+1 when server 0 garbles, −1 when server 1 does — the garbler flips per
level, ref rpc.rs:20-23); the evaluator receives ``r0`` when the strings
are equal, else ``r1`` — so ``v0 - v1 = [x == y]`` per test REGARDLESS
of which side garbled, and summed shares reconstruct counts exactly like
``keep_values`` (collect.rs:945-964).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import gc, otext, prg
from ..ops.fields import F255, FE62

# ---------------------------------------------------------------------------
# String extraction: packed share bits -> per-(node, pattern, client) strings
# ---------------------------------------------------------------------------


def _string_positions(d: int) -> np.ndarray:
    """uint32[2^d, 2d] — packed-bit positions of child pattern c's compared
    string, ordered (dim-major, side minor): the tensor twin of the
    reference's left||right bit-string layout (collect.rs:393-410), reading
    direction ``(c >> j) & 1`` per dim (child order: lib.rs:125-129)."""
    out = np.empty((1 << d, 2 * d), np.uint32)
    for c in range(1 << d):
        k = 0
        for j in range(d):
            r = (c >> j) & 1
            for s in range(2):
                out[c, k] = j * 4 + s * 2 + r
                k += 1
    return out


@partial(jax.jit, static_argnames=("d",))
def child_strings(packed: jax.Array, d: int) -> jax.Array:
    """uint32[F, N] packed share bits -> bool[F, 2^d, N, 2d] strings."""
    pos = jnp.asarray(_string_positions(d))  # [C, S]
    return ((packed[:, None, :, None] >> pos[None, :, None, :]) & 1).astype(bool)


# ---------------------------------------------------------------------------
# Field payload codecs (OT payload width: FE62 one block, F255 two blocks)
# ---------------------------------------------------------------------------


def payload_words(field) -> int:
    return 8 if field is F255 else 4


def field_to_words(field, v) -> jax.Array:
    b = field.to_blocks(v)
    if field is F255:
        return b.reshape(b.shape[:-2] + (8,))
    return b


def words_to_field(field, w) -> jax.Array:
    if field is F255:
        return field.from_blocks(w.reshape(w.shape[:-1] + (2, 4)))
    return field.from_blocks(w)


def derive_seed(base: np.ndarray, purpose: int, level: int, ctr: int = 0) -> np.ndarray:
    """Per-(purpose, level, crawl-counter) PRG seed from a session seed."""
    s = np.array(base, np.uint32, copy=True)
    s[1] ^= np.uint32(ctr)
    s[2] ^= np.uint32(purpose)
    s[3] ^= np.uint32(level)
    return s


# ---------------------------------------------------------------------------
# Protocol steps (sans-IO).  Roles: garbler = server 0 (gc_sender=true,
# ref: leader.rs:204-205 pins the role per request), evaluator = server 1.
# ---------------------------------------------------------------------------


def ev_step1(rcv: otext.OtExtReceiver, y_flat):
    """Evaluator: request input labels.  y_flat bool[B, S] -> (u message,
    T rows uint32[B*S, 4] — the Δ-OT labels-to-be).  ``y_flat`` may stay
    a DEVICE array — fetching it first costs a tunnel round trip and the
    extension consumes it on device anyway."""
    B, S = y_flat.shape
    u, t = rcv.extend(jnp.reshape(jnp.asarray(y_flat), (B * S,)))
    return u, t


def gb_step1(snd: otext.OtExtSender, u_msg, x_flat, gc_seed):
    """Garbler: derive evaluator zero-labels from the extension and garble.

    Returns (batch to send, mask bool[B] — the garbler's XOR shares)."""
    B, S = x_flat.shape
    q = snd.extend(B * S, u_msg)
    y0 = q.reshape(B, S, 4)
    return gc.garble_equality_delta(
        jnp.asarray(snd.s_block), y0, jnp.asarray(gc_seed), x_flat
    )


def ev_step2(batch: gc.GarbledEqBatch, t_rows, B: int, S: int) -> jax.Array:
    """Evaluator: labels are the Δ-OT T rows; evaluate -> XOR shares bool[B]."""
    return gc.eval_equality(batch, jnp.asarray(t_rows).reshape(B, S, 4))


def ev_step3(rcv: otext.OtExtReceiver, e_bits):
    """Evaluator: open the b2a OT with its GC output shares as choices.
    Returns (u message, T2 rows, idx0 — the pad tweak base).  ``e_bits``
    may stay a device array (see ev_step1)."""
    idx0 = rcv.consumed
    u2, t2 = rcv.extend(jnp.asarray(e_bits))
    return u2, t2, idx0


def b2a_payload_pair(field, b2a_seed, B: int, garbler: int):
    """The sender's b2a share pair, in ONE place for every flow: sample
    ``r0`` from the seed stream, keep ``r1 = r0 ± 1`` — +1 when server 0
    is the sender, −1 when server 1 is — so the leader's uniform
    ``v0 - v1`` reconstruction holds whichever server sends
    (collect.rs:439-456's ordering with the alternating-garbler sign).
    Returns (r1 — the sender's additive shares, w0, w1 — the two payloads
    as OT words)."""
    W = payload_words(field)
    r_words = prg.stream_words(jnp.asarray(b2a_seed, jnp.uint32), B * W).reshape(B, W)
    r0 = field.sample(r_words)
    one = field.from_int(1)
    r1 = field.sub(r0, one) if garbler else field.add(r0, one)
    return r1, field_to_words(field, r0), field_to_words(field, r1)


def b2a_encrypt(field, q2_rows, s_block, mask, b2a_seed, idx0, garbler: int = 0):
    """Stateless b2a sender core: sample (r0, r1 = r0 ± 1), order payloads
    by ``mask`` (collect.rs:439-456), encrypt under the OT pads derived
    from the Q rows.  Returns (c0, c1 ciphertext words [B, W], r1 — the
    sender's additive shares).  Shared by the socket path (gb_step2) and
    the mesh kernel (parallel/mesh.py) so the trick lives in exactly one
    place."""
    mask = jnp.asarray(mask, bool)
    B = mask.shape[0]
    W = payload_words(field)
    q2_rows = jnp.asarray(q2_rows)
    pad0 = otext.ot_hash(q2_rows, W, idx0)
    pad1 = otext.ot_hash(q2_rows ^ jnp.asarray(s_block), W, idx0)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    m0 = jnp.where(mask[:, None], w0, w1)
    m1 = jnp.where(mask[:, None], w1, w0)
    return m0 ^ pad0, m1 ^ pad1, r1


def b2a_decrypt(field, t2_rows, idx0, c0, c1, e_bits):
    """Stateless b2a receiver core: decrypt the choice-side ciphertext with
    the T-row pad -> field values (r0 where equal, r1 where not)."""
    W = payload_words(field)
    pad = otext.ot_hash(jnp.asarray(t2_rows), W, idx0)
    e = jnp.asarray(e_bits, bool)
    ct = jnp.where(e[:, None], jnp.asarray(c1), jnp.asarray(c0))
    return words_to_field(field, ct ^ pad)


def gb_step2(snd: otext.OtExtSender, u2_msg, mask, b2a_seed, field, garbler: int = 0):
    """Garbler: extend the b2a OT and run :func:`b2a_encrypt`.

    Returns (c0, c1 ciphertext words [B, W], field values [B] — the
    garbler's additive shares, always r1 = r0 ± 1 by ``garbler`` side)."""
    B = jnp.asarray(mask).shape[0]
    idx0 = snd.consumed
    q2 = snd.extend(B, u2_msg)
    return b2a_encrypt(field, q2, snd.s_block, mask, b2a_seed, idx0, garbler)


def ev_step4(rcv: otext.OtExtReceiver, t2_rows, idx0, c0, c1, e_bits, field):
    """Evaluator: decrypt its chosen payload -> field values [B] (its
    additive shares: r0 where equal, r1 where not)."""
    return b2a_decrypt(field, t2_rows, idx0, c0, c1, e_bits)


# ---------------------------------------------------------------------------
# S = 2 fast path: equality via 1-of-4 chosen-payload OT (no garbled circuit)
# ---------------------------------------------------------------------------
#
# For one-dimensional crawls (the flagship zipf/rides shape) each equality
# test compares S = 2 bits — the two interval sides of the single dim.  The
# full GC machinery (1 AND gate, 4 garble + 2 eval hashes, tables + labels
# + decode on the wire) exists to compute [x == y] for 2-bit x, y.  But a
# 2-bit y is a 1-of-4 choice, and the test's two Δ-OT rows (t_j = q_j ^
# y_j·s) already encode it: combining the rows with distinct GF(2^128)
# coefficients, T = t_0 ^ 2·t_1 = Q ^ (y_0·s ^ y_1·2s) where Q = q_0 ^
# 2·q_1, gives the receiver exactly ONE of the four sender-computable pads
# H(Q ^ o_c), o_c = c_0·s ^ c_1·2s, c in {0,1}² — pairwise distinct
# offsets since doubling is invertible and s != 0.  The sender encrypts
# payload m_{[x == c]} under pad c; the receiver opens pad y and learns
# m_{[x == y]} — the whole equality test + payload b2a in 5 hashes/test
# (4 garbler + 1 evaluator) instead of the GC path's 9, with ~40% of its
# wire bytes (4 ciphertexts vs tables + labels + decode + 2 ciphertexts).
# This is the classic 1-of-N OT-extension pad construction (Kolesnikov-
# Kumaresan 2013 shape) under the same circular-correlation-robust-hash
# assumption the Δ-OT pads and the GC fused payload already rest on.
#
# The GC path (ops/gc.py) remains for S > 2 (multi-dim tests need the
# AND-tree) and as the reference-parity mode; ``EQ_OT4`` selects the fast
# path for S == 2 everywhere (it is pure protocol math — no Pallas — so it
# runs identically on CPU test hosts and chips; both modes stay tested).

EQ_OT4: bool = True

_OT4_DOMAIN = 0x0F4E4F54  # ot_hash tweak-domain of the per-test 1-of-4 pads


def _ot4_use(S: int) -> bool:
    return EQ_OT4 and S == 2


@partial(jax.jit, static_argnames=("n_words",))
def ot4_encrypt(q_rows, s_block, x_flat, m_v0, m_v1, n_words: int, idx_offset):
    """Sender side: q_rows uint32[B, 2, 4] (this batch's extension rows),
    x_flat bool[B, 2] (the sender's share-bit strings), payloads
    m_v0/m_v1 uint32[B, n_words] for result 0 / 1.  Returns cts
    uint32[4, B, n_words] indexed by the receiver's string as a little-
    endian 2-bit integer c = y_0 + 2·y_1."""
    q_rows = jnp.asarray(q_rows, jnp.uint32)
    x_flat = jnp.asarray(x_flat, bool)
    s = jnp.asarray(s_block, jnp.uint32)
    comb = q_rows[:, 0] ^ otext.gf128_double(q_rows[:, 1])  # [B, 4]
    s2 = otext.gf128_double(s)
    x_int = x_flat[:, 0].astype(jnp.uint32) + 2 * x_flat[:, 1].astype(jnp.uint32)
    offs = jnp.stack([
        jnp.zeros_like(s), s, s2, s ^ s2
    ])  # [4, 4] — offset of choice c = c0·s ^ c1·2s
    pads = otext.ot_hash(
        comb[None] ^ offs[:, None, :], n_words, idx_offset, domain=_OT4_DOMAIN
    )  # [4, B, n_words]
    eq = jnp.arange(4, dtype=jnp.uint32)[:, None] == x_int[None]  # [4, B]
    m = jnp.where(
        eq[..., None], jnp.asarray(m_v1, jnp.uint32)[None],
        jnp.asarray(m_v0, jnp.uint32)[None],
    )
    return m ^ pads


@partial(jax.jit, static_argnames=("n_words",))
def ot4_decrypt(t_rows, y_flat, cts, n_words: int, idx_offset):
    """Receiver side: t_rows uint32[B, 2, 4], y_flat bool[B, 2] (its own
    share-bit strings — the extension's choice bits), cts uint32[4, B,
    n_words].  Returns uint32[B, n_words] = m_{[x == y]} per test."""
    t_rows = jnp.asarray(t_rows, jnp.uint32)
    y_flat = jnp.asarray(y_flat, bool)
    comb = t_rows[:, 0] ^ otext.gf128_double(t_rows[:, 1])  # [B, 4]
    pad = otext.ot_hash(comb, n_words, idx_offset, domain=_OT4_DOMAIN)
    y_int = y_flat[:, 0].astype(jnp.uint32) + 2 * y_flat[:, 1].astype(jnp.uint32)
    # one-hot select instead of take_along_axis: the gather lowers poorly
    # on TPU (measured 1.5x slower at the flagship 524288-test batch)
    sel = (jnp.arange(4, dtype=jnp.uint32)[:, None] == y_int[None]).astype(
        jnp.uint32
    )
    ct = jnp.sum(
        jnp.asarray(cts, jnp.uint32) * sel[..., None], axis=0,
        dtype=jnp.uint32,
    )
    return ct ^ pad


def gb_step_ot4(snd: otext.OtExtSender, u_msg, x_flat, b2a_seed, field,
                garbler: int = 0):
    """Garbler/sender level step on the S = 2 fast path: extend the Δ-OT,
    derive (r0, r1 = r0 ± 1), and encrypt the 1-of-4 payload table — the
    whole level in one message (cts ravel), like :func:`gb_step_fused`.

    Returns (cts uint32[4, B, W], vals — the sender's additive shares)."""
    x_flat = jnp.asarray(x_flat, bool)
    B, S = x_flat.shape
    assert S == 2, "ot4 path is the S == 2 specialization"
    idx0 = snd.consumed
    q = snd.extend(B * S, u_msg)
    W = payload_words(field)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    # result 1 (strings equal) -> receiver learns r0 (collect.rs:439-456)
    cts = ot4_encrypt(
        q.reshape(B, S, 4), jnp.asarray(snd.s_block), x_flat, w1, w0, W, idx0
    )
    return cts, r1


def ev_open_ot4(rcv: otext.OtExtReceiver, t_rows, y_flat, msg, B: int,
                field, idx0: int):
    """Receiver twin of :func:`gb_step_ot4`: open the 1-of-4 table with the
    combined T rows -> field values [B] (r0 where equal, else r1)."""
    W = payload_words(field)
    cts = jnp.asarray(msg).reshape(4, B, W)
    w = ot4_decrypt(jnp.asarray(t_rows).reshape(B, 2, 4), y_flat, cts, W, idx0)
    return words_to_field(field, w)


# ---------------------------------------------------------------------------
# FUSED socket flow: the b2a payloads ride the GC output labels
# ---------------------------------------------------------------------------
#
# The two-round flow above (ev u -> gb batch -> ev u2 -> gb ciphertexts)
# follows the reference's GC-then-OT structure (collect.rs:419-482).  But
# the evaluator's b2a choice bit is exactly its GC output share — and its
# garbled OUTPUT LABEL already encodes that choice 1-of-2 (labels differ
# by R with the select bit in the lsb).  Encrypting the two payloads under
# the two possible output labels (ops/gc.garble_equality_payload) delivers
# the b2a OT for free inside the garbled batch: ONE protocol round trip
# per level (ev u -> gb batch+cts), one fetch fewer on each side — through
# a remote-chip tunnel each removed fetch is a full ~0.1 s RTT.  Security
# rests on the same circular-correlation-robust hash assumption as the
# Δ-OT pads (labels differ by R = s); the mesh path keeps the explicit
# two-round form (device-resident, RTT-free, and its collectives are
# already minimal).


def ev_step1_fused(rcv: otext.OtExtReceiver, y_flat):
    """Evaluator round 1: like :func:`ev_step1` but also captures the
    pre-extension consumed counter — the payload-pad index base both
    sides must agree on (the garbler captures the same value)."""
    idx0 = rcv.consumed
    u, t = ev_step1(rcv, y_flat)
    return u, t, idx0


def gb_step_fused(snd: otext.OtExtSender, u_msg, x_flat, gc_seed, b2a_seed,
                  field, garbler: int = 0):
    """Garbler: extend the input-label Δ-OT, garble, and attach the b2a
    payloads under the output labels — the whole level in one message.

    Returns (packed message, vals — the garbler's additive shares
    ``r1 = r0 ± 1`` by garbling side, as in :func:`b2a_encrypt`)."""
    x_flat = jnp.asarray(x_flat, bool)
    B, S = x_flat.shape
    idx0 = snd.consumed
    q = snd.extend(B * S, u_msg)
    W = payload_words(field)
    r1, w0, w1 = b2a_payload_pair(field, b2a_seed, B, garbler)
    # v = 1 (strings equal) -> evaluator learns r0, else r1: the ordering
    # of collect.rs:439-456 with the choice implicit in the output label
    batch, cts, _ = gc.garble_equality_payload(
        jnp.asarray(snd.s_block), q.reshape(B, S, 4), jnp.asarray(gc_seed),
        x_flat, w1, w0, W, idx0,
    )
    return pack_gc_payload_batch(batch, cts), r1


def ev_open_fused(rcv: otext.OtExtReceiver, t_rows, msg, B: int, S: int,
                  field, idx0: int):
    """Evaluator round 2: evaluate the batch and open the payload under
    the output label -> field values [B] (r0 where equal, else r1)."""
    W = payload_words(field)
    batch, cts = unpack_gc_payload_batch(jnp.asarray(msg), B, S, W)
    _, w = gc.eval_equality_payload(
        batch, jnp.asarray(t_rows).reshape(B, S, 4), cts, W, idx0
    )
    return words_to_field(field, w)


# ---------------------------------------------------------------------------
# Wire packing: one buffer per message
# ---------------------------------------------------------------------------
#
# Through a remote-chip tunnel every device->host fetch costs a full round
# trip (~120 ms measured) regardless of size, so a message that fetches
# three arrays pays three RTTs.  Packing the garbled batch (and the b2a
# ciphertext pair) into ONE u32 vector on device makes each data-plane
# message one fetch + one pickle; the peer re-uploads once and slices on
# device.


@jax.jit
def pack_gc_batch(batch: gc.GarbledEqBatch) -> jax.Array:
    return jnp.concatenate([
        jnp.ravel(batch.tables),
        jnp.ravel(batch.gb_labels),
        jnp.ravel(batch.decode).astype(jnp.uint32),
    ])


@partial(jax.jit, static_argnames=("B", "S"))
def unpack_gc_batch(buf: jax.Array, B: int, S: int) -> gc.GarbledEqBatch:
    buf = jnp.asarray(buf)
    nt = B * (S - 1) * 2 * 4
    nl = B * S * 4
    return gc.GarbledEqBatch(
        tables=buf[:nt].reshape(B, S - 1, 2, 4),
        gb_labels=buf[nt : nt + nl].reshape(B, S, 4),
        decode=buf[nt + nl :] != 0,
    )


@jax.jit
def pack_gc_payload_batch(batch: gc.GarbledEqBatch, cts: jax.Array) -> jax.Array:
    return jnp.concatenate([pack_gc_batch(batch), jnp.ravel(cts)])


@partial(jax.jit, static_argnames=("B", "S", "W"))
def unpack_gc_payload_batch(buf: jax.Array, B: int, S: int, W: int):
    buf = jnp.asarray(buf)
    base = B * (S - 1) * 2 * 4 + B * S * 4 + B
    return unpack_gc_batch(buf[:base], B, S), buf[base:].reshape(2, B, W)


# ---------------------------------------------------------------------------
# Alive-gated per-node share sums (collect.rs:487-501)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("field",))
def node_share_sums(field, vals, weight) -> jax.Array:
    """vals: field elements [F, C, N(, limbs)]; weight: bool[F, C, N].
    Returns per-(node, pattern) share sums [F, C(, limbs)].  Dead clients
    and dead nodes are gated to zero — identically on both servers, since
    liveness flags and the frontier alive mask are public protocol state
    (ref: collect.rs:495)."""
    if field.limb_shape:
        vals = jnp.where(weight[..., None], vals, 0)
        return field.sum(vals, axis=2)
    vals = jnp.where(weight, vals, 0)
    return field.sum(vals, axis=2)


def alive_weight(alive_nodes, alive_keys, C: int) -> np.ndarray:
    """bool[F, C, N] gating weight from the public liveness masks."""
    a_n = np.asarray(alive_nodes, bool)
    a_k = np.asarray(alive_keys, bool)
    return np.broadcast_to(
        a_n[:, None, None] & a_k[None, None, :], (a_n.shape[0], C, a_k.shape[0])
    )


# ---------------------------------------------------------------------------
# Warmup: compile the per-shape kernel chain without touching live state
# ---------------------------------------------------------------------------


def warm_level_kernels(packed, d: int, field) -> None:
    """Run the WHOLE per-level 2PC kernel chain — string extraction,
    Δ-OT extension, equality (1-of-4 OT or GC + fused b2a, whichever this
    shape uses), payload open, alive-gated share sums — on a THROWAWAY
    in-process OT session, so every jit program a real level of this
    shape will dispatch is compiled (and lands in the persistent compile
    cache, utils/compile_cache) before measured crawl time starts.  The
    live OT sessions and the data plane are never touched; the outputs
    are discarded."""
    strs = child_strings(packed, d)
    F_, C, N, S = strs.shape
    B = F_ * C * N
    flat = strs.reshape(B, S)
    snd, rcv = otext.inprocess_pair()
    zero = np.zeros(4, np.uint32)
    gseed, bseed = derive_seed(zero, 1, 0), derive_seed(zero, 2, 0)
    u, t_rows, idx0 = ev_step1_fused(rcv, flat)
    if _ot4_use(S):
        msg, _ = gb_step_ot4(snd, u, flat, bseed, field, 0)
        vals = ev_open_ot4(rcv, t_rows, flat, msg, B, field, idx0)
    else:
        msg, _ = gb_step_fused(snd, u, flat, gseed, bseed, field, 0)
        vals = ev_open_fused(rcv, t_rows, msg, B, S, field, idx0)
    w = jnp.ones((F_, C, N), bool)
    jax.block_until_ready(
        node_share_sums(
            field, vals.reshape((F_, C, N) + field.limb_shape), w
        )
    )
