"""Control plane: leader↔server RPC + server↔server data-plane socket.

Process topology mirrors the reference (SURVEY.md §2 "distributed
communication backend"):

- **Control plane** — leader connects to both servers and drives the 8-verb
  protocol of the reference's tarpc ``Collector`` service (ref: rpc.rs:56-66):
  ``reset, add_keys, tree_init, tree_crawl, tree_crawl_last, tree_prune,
  tree_prune_last, final_shares``.  Transport: length-prefixed pickle over
  TCP via asyncio (the tarpc+bincode analogue; pickle protocol 5 gives
  zero-copy numpy buffers).
- **Data plane** — one server↔server TCP connection carrying the packed
  share-bit tensors per level (server1 listens on ``port+1``, server0 dials
  with retries — the reference's GC-mesh bootstrap order, server.rs:197-262,
  collapsed from ``num_cpus`` sockets to one because the exchange is a single
  batched tensor, not per-thread GC traffic).  On a shared TPU pod the same
  exchange rides ICI via parallel/mesh.py instead.

Counts come back as **field-element shares**: both servers derive a common
pseudorandom mask stream from a shared seed — the reference hardcodes the
same PRG seed on both servers ("XXX This is bogus", server.rs:331-332) — so
server0 returns ``count + r`` and server1 returns ``r``, and the leader
reconstructs ``v0 - v1`` exactly as ``keep_values`` does
(ref: collect.rs:945-989).  Inner levels use FE62, the last level F255
(ref: rpc.rs:60-62 FE vs FieldElm).

Divergence, by design: the reference's ``tree_prune`` carries an alive list
and servers rebuild child nodes eagerly; here prune and child
materialization are fused — the leader sends (parent_idx, pattern, n_alive)
and the server advances only the survivors (see protocol/collect.py's
memory plan).

Fault tolerance (the resilience layer, this repo's addition — the
reference restarts the whole run on any socket error):

- the leader-side :class:`CollectorClient` RECONNECTS: on transport loss
  it redials under the shared backoff policy
  (``resilience.policy.DIAL_POLICY``), bumps its session *epoch*, and
  replays the calls whose responses never arrived;
- the server answers replays IDEMPOTENTLY: each leader session keeps a
  bounded ``(req_id) → response`` dedup cache plus an in-flight table, so
  a stateful verb (``tree_prune``, ``add_keys``) that already ran is
  answered from cache instead of double-applied;
- ``tree_checkpoint``/``tree_restore`` verbs persist/reload the server's
  crawl state at leader-chosen level boundaries, and ``plane_reset``
  re-establishes the server↔server data plane (redial + fresh
  ``_plane_handshake``) after a peer loss — together they let
  ``RpcLeader.run_supervised`` re-run only the lost levels.

Streaming ingestion (the online front door, ROADMAP "Streaming
ingestion"): instead of one bulk ``add_keys`` upload, clients submit key
chunks continuously via ``submit_keys`` into per-window append-only
pools, gated by resilience/admission.py (token-bucket rate limits,
per-client quotas, bounded pools, reject-vs-reservoir shedding);
``window_seal`` freezes a window at its boundary and ``window_load``
materializes the frozen snapshot as the crawl's key batch — the normal
level loop then runs on it while ingest keeps landing in later windows
(``submit_keys`` bypasses the verb lock like ``add_keys``).  Pools ride
``tree_checkpoint``/``tree_restore`` (entry slots, per-``sub_id``
verdicts, reservoir RNG state), so a kill mid-window neither loses nor
double-counts admitted keys.
"""

from __future__ import annotations

import asyncio
import collections as _collections
import hashlib
import os
import pickle
import secrets as _secrets
import struct
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import metrics as obsmetrics
from ..ops import baseot, dpf, gc, ibdcf, otext, prg
from ..ops.fields import F255, FE62
from ..ops.ibdcf import EvalState, IbDcfKeyBatch
from ..parallel import kernel_shard, server_mesh as smesh
from ..resilience import admission as resadmission
from ..resilience import chaos as reschaos
from ..resilience import policy as respolicy
from ..utils import guards
from ..utils.config import Config
from . import collect, mpc, secure, sketch as sketchmod

_HDR = struct.Struct("<Q")
SHARED_MASK_SEED = b"XXX This is bog\x00"  # 16 B, ref: server.rs:331-332

# structure template for (de)serializing sketch key batches over the wire
_z = np.zeros(0)
_SKETCH_TREEDEF = sketchmod.SketchKeyBatch(
    key=dpf.DpfKeyBatch(_z, _z, _z, _z, _z, _z),
    mac_key=_z,
    mac_key2=_z,
    mac_key_last=_z,
    mac_key2_last=_z,
    triples=mpc.TripleBatch(_z, _z, _z),
    triples_last=mpc.TripleBatch(_z, _z, _z),
)


async def _send(writer: asyncio.StreamWriter, obj, count=None,
                flush: bool = True) -> None:
    """``count``, when given, is called with the framed byte size — the
    data-plane accounting hook (obs counters).  ``flush=False`` skips the
    ``drain()`` backpressure wait: asyncio delivers the buffered bytes
    regardless (drain only waits when the write buffer tops the
    high-water mark), so a burst of consecutive frames can coalesce into
    ONE drain on its final frame instead of one await per frame — but
    some frame in every burst MUST flush, or a dead peer lets the buffer
    grow without bound."""
    data = pickle.dumps(obj, protocol=5)
    if count is not None:
        count(len(data) + _HDR.size)
    writer.write(_HDR.pack(len(data)) + data)
    if flush:
        await writer.drain()


async def _recv(reader: asyncio.StreamReader, count=None):
    """Frame reads are DELIBERATELY unbounded: serve/reader loops wait
    indefinitely for the next frame by design — response waits are
    bounded at the caller (per-verb ``Deadline`` on the pending future)
    and the data plane by TCP keepalive, not by a read timeout here."""
    # fhh-lint: disable=unbounded-await (see docstring)
    hdr = await reader.readexactly(_HDR.size)
    (n,) = _HDR.unpack(hdr)
    if count is not None:
        count(n + _HDR.size)
    # fhh-lint: disable=unbounded-await (see docstring)
    return pickle.loads(await reader.readexactly(n))


async def _fetch(
    x, reg: obsmetrics.Registry | None = None, level: int | None = None
) -> np.ndarray:
    """Device->host fetch OFF the event loop.  A bare ``np.asarray`` on a
    device array blocks the whole loop for a full transfer (a ~110 ms RTT
    on remote-chip tunnels) — serializing the two servers' fetches when
    they share a process (the in-process bench/tests) and starving
    keepalives/concurrent verbs in any deployment.  np.asarray of distinct
    arrays is thread-safe in JAX; the GIL releases during the copy.

    ``reg`` counts the fetch: on remote-chip tunnels fetch COUNT, not byte
    count, is the latency floor (each is a full round trip), so the run
    report carries both.  ``level`` attributes the fetch when the call
    site sits outside any span (span-active callers inherit)."""
    if reg is not None:
        reg.count("device_fetches", level=level)
    _start_host_copy(x)
    return await asyncio.to_thread(np.asarray, x)


def _start_host_copy(x) -> None:
    """Kick off the device->host DMA for ``x`` without blocking (the
    ``copy_to_host_async`` half of a double-buffered fetch): the copy
    proceeds while the caller does other work — another span's expand
    dispatch, a peer exchange — and the later ``np.asarray`` completes
    against an already-landed (or in-flight) buffer instead of starting
    the transfer then.  Best-effort: plain numpy inputs and JAX builds
    without the method fall through to the synchronous copy."""
    fn = getattr(x, "copy_to_host_async", None)
    if fn is None:
        return
    try:
        fn()
    except Exception:  # fhh-lint: disable=broad-except (pure prefetch hint: any failure means the sync np.asarray path simply does the whole copy)
        pass


def _mask_words(level: int, n: int, blocks_for: int) -> np.ndarray:
    """Shared pseudorandom mask words for one level (both servers derive the
    same stream, so shares cancel on reconstruction).  Host NumPy on
    purpose: the mask is tiny (F·2^d elements) and the device version
    would cost a device->host round trip per level per server — a full
    tunnel RTT on remote-chip deployments."""
    seed = prg.seeds_from_bytes(SHARED_MASK_SEED)[0].copy()
    seed[3] ^= np.uint32(level)
    return prg.np_stream_words(seed, n * blocks_for).reshape(n, blocks_for)


def mask_fe62(level: int, n: int) -> np.ndarray:
    # host twin of FE62.sample: the device version sampled ~KB of masks on
    # the accelerator and fetched them back — one tunnel RTT per level for
    # work NumPy does in microseconds (flagged by fhh-lint
    # host-sync-in-hot-loop, round 6)
    return FE62.np_sample(_mask_words(level, n, 4))


def mask_f255(level: int, n: int) -> np.ndarray:
    return F255.np_sample(_mask_words(level, n, 8))


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

# dedup bounds: the cache must cover every req_id a client could still
# replay — at most its in-flight window (the key-upload window of 256 in
# leader_rpc.upload_keys is the largest) plus slack — and is bounded by
# BYTES as well as count: verb responses carry share arrays (tree_crawl,
# final_shares) that can each be MBs at production scale, and a
# count-only bound would pin ~1024 of them.  Sessions are bounded too, so
# a leader that reconnects under fresh session ids can't grow server
# memory without bound.
_SESSION_CACHE_CAP = 1024
_SESSION_CACHE_BYTES = 128 << 20
_SESSION_CAP = 8


def _resp_nbytes(resp) -> int:
    """Approximate retained size of a cached response (array payloads
    dominate; containers/scalars get a flat floor)."""
    if isinstance(resp, np.ndarray):
        return resp.nbytes + 64
    if isinstance(resp, dict):
        return 64 + sum(_resp_nbytes(v) for v in resp.values())
    if isinstance(resp, (list, tuple)):
        return 64 + sum(_resp_nbytes(v) for v in resp)
    return 64


class _Session:
    """One leader session's idempotent-replay state: responses already
    sent (``cache``) and verbs still executing (``inflight``).  A replay
    of a cached req_id is answered from the cache; a replay of an
    in-flight req_id awaits the SAME execution — the verb never runs
    twice either way."""

    __slots__ = (
        "epoch", "cache", "sizes", "bytes_total", "inflight", "last_seen"
    )

    def __init__(self):
        self.epoch = 0
        self.cache: _collections.OrderedDict = _collections.OrderedDict()
        self.sizes: dict[int, int] = {}
        self.bytes_total = 0
        self.inflight: dict[int, asyncio.Future] = {}
        self.last_seen = time.monotonic()

    def put(self, req_id, resp) -> None:
        """Cache a response under the count AND byte bounds.  The newest
        entry always survives even when it alone exceeds the byte cap —
        replay correctness of the in-flight call beats the bound."""
        nb = _resp_nbytes(resp)
        self.cache[req_id] = resp
        self.sizes[req_id] = nb
        self.bytes_total += nb
        while len(self.cache) > 1 and (
            len(self.cache) > _SESSION_CACHE_CAP
            or self.bytes_total > _SESSION_CACHE_BYTES
        ):
            old, _ = self.cache.popitem(last=False)
            self.bytes_total -= self.sizes.pop(old, 0)


class _WindowPool:
    """One ingest window's append-only key pool (the streaming front
    door's unit of work: protocol verbs ``submit_keys`` → ``window_seal``
    → ``window_load``).

    ``entries`` holds admitted submissions (tuples of key arrays, the
    same chunk shape ``add_keys`` receives) in arrival order; once the
    reservoir shed policy engages, the list freezes into a SLOT TABLE
    and replacements overwrite in place.  ``verdicts`` records every
    FINAL outcome by ``sub_id`` so at-least-once delivery (reconnect
    replays, recovery journal replays) answers the recorded verdict
    instead of double-admitting or re-advancing the sampler's RNG.
    Overloaded rejections are deliberately NOT recorded — a backed-off
    retry is a fresh attempt against refilled tokens."""

    __slots__ = (
        "window", "wa", "entries", "verdicts", "keys",
        "admitted_keys", "shed_keys", "rejected", "sealed",
    )

    def __init__(self, window: int, wa: resadmission.WindowAdmission):
        self.window = int(window)
        self.wa = wa
        self.entries: list = []
        self.verdicts: dict = {}
        self.keys = 0
        self.admitted_keys = 0
        self.shed_keys = 0
        self.rejected = 0
        self.sealed = False

    def apply(self, sub_id: str, chunk: tuple,
              v: resadmission.Verdict) -> dict:
        """Commit one gate verdict to the pool; returns the wire
        response (the mirror server replays it via :meth:`apply_mirror`)."""
        n_keys = int(chunk[0].shape[0])
        if not v.admitted and v.scope is not None:
            self.rejected += 1
            return {
                "admitted": False, "overloaded": True, "scope": v.scope,
                "retry_after_s": round(float(v.retry_after_s), 4),
                "window": self.window,
            }
        if not v.admitted:  # reservoir shed this submission
            resp = {"admitted": False, "shed": True, "window": self.window}
            self.verdicts[sub_id] = resp
            self.shed_keys += n_keys
            return resp
        if v.slot is None:
            self.entries.append(chunk)
            self.keys += n_keys
        else:
            old = self.entries[v.slot]
            old_n = int(old[0].shape[0])
            self.entries[v.slot] = chunk
            self.keys += n_keys - old_n
            self.shed_keys += old_n
            # keep the admission ledger's occupancy honest under
            # variable-size chunks
            self.wa.keys += n_keys - old_n
        self.admitted_keys += n_keys
        resp = {"admitted": True, "slot": v.slot, "window": self.window}
        self.verdicts[sub_id] = resp
        return resp

    def apply_mirror(self, sub_id: str, chunk: tuple, mirror: dict,
                     client_id: str | None = None) -> dict:
        """Replay the GATE server's verdict on the peer pool so both
        servers' windows stay positionally identical.  Validates loudly —
        a mirror that cannot apply means the two pools diverged, which
        must never be papered over."""
        n_keys = int(chunk[0].shape[0])
        slot = mirror.get("slot")
        if self.wa.shed == resadmission.SHED_RESERVOIR:
            if self.wa.sub_keys is None:
                self.wa.sub_keys = n_keys  # uniform-chunk contract holds
            if mirror.get("shed") or slot is not None:
                # a restored GATE being rebuilt by the recovery journal:
                # the replayed verdict consumed one sampler draw in its
                # first life — advance the restored stream past it (the
                # verdict itself is applied verbatim below), so
                # post-recovery live admissions continue the SAME
                # seed-reproducible sequence.  When the reservoir
                # engaged only AFTER the last checkpoint, there is no
                # sampler to advance yet: bank the draw so the eventual
                # engagement fast-forwards past it.  A mirror server
                # never re-engages a reservoir, so this is harmless
                # bookkeeping outside recovery.
                if self.wa.reservoir is not None:
                    self.wa.reservoir.offer(1)
                else:
                    self.wa.pending_draws += 1
        if mirror.get("shed"):
            resp = {"admitted": False, "shed": True, "window": self.window}
            self.verdicts[sub_id] = resp
            self.shed_keys += n_keys
            return resp
        if slot is None:
            if self.keys + n_keys > self.wa.max_keys:
                raise RuntimeError(
                    f"ingest mirror overflows window {self.window}: "
                    f"{self.keys} + {n_keys} > {self.wa.max_keys} "
                    "(gate/mirror pools diverged)"
                )
            self.entries.append(chunk)
            self.keys += n_keys
            # keep the admission ledger in lockstep: a recovery journal
            # replay rebuilds a restarted GATE through this path, and its
            # later live decisions must see the true occupancy
            self.wa.subs += 1
            self.wa.keys += n_keys
            self.wa._charge(client_id, n_keys)
        else:
            slot = int(slot)
            if not 0 <= slot < len(self.entries):
                raise RuntimeError(
                    f"ingest mirror names slot {slot} of a "
                    f"{len(self.entries)}-slot window {self.window} pool "
                    "(gate/mirror pools diverged)"
                )
            old_n = int(self.entries[slot][0].shape[0])
            self.entries[slot] = chunk
            self.keys += n_keys - old_n
            self.shed_keys += old_n
            self.wa.keys += n_keys - old_n
            self.wa._charge(client_id, n_keys)
        self.admitted_keys += n_keys
        resp = {"admitted": True, "slot": slot, "window": self.window}
        self.verdicts[sub_id] = resp
        return resp

    def stats(self) -> dict:
        return {
            "window": self.window,
            "sealed": self.sealed,
            "keys": self.keys,
            "subs": len(self.entries),
            "admitted_keys": self.admitted_keys,
            "shed_keys": self.shed_keys,
            "rejected": self.rejected,
        }


# Runtime twin of the fhh-race guard map — the "CollectorServer.*"
# entries of pyproject [tool.fhh-lint.guards], attr -> owning asyncio
# lock (drift-tested against the pyproject table in
# tests/test_concurrency.py).  Under FHH_DEBUG_GUARDS=1 (or
# Config.debug_guards) utils/guards.py arms a GuardedState descriptor
# per entry, so every access asserts the lock is held by the current
# task — the dynamic validation of the `# fhh-race: holds=` contracts
# the static analyzer cannot see through _dispatch's dynamic getattr.
_SERVER_GUARDS = {
    "frontier": "_verb_lock",
    "keys": "_verb_lock",
    "keys_parts": "_verb_lock",
    "alive_keys": "_verb_lock",
    "_expand_ready": "_verb_lock",
    "_ingest_pools": "_verb_lock",
    "_admission": "_verb_lock",
    "_sessions": "_verb_lock",
    "_sketch_parts": "_verb_lock",
    "_sketch_root": "_verb_lock",
    "_ratchet_digest": "_verb_lock",
}


@dataclass
class CollectorServer:
    """One collector server process (ref: server.rs:44-172).

    ``server_id`` 0 dials the peer, 1 listens (ref: server.rs:208-233).
    """

    server_id: int
    cfg: Config
    keys_parts: list = field(default_factory=list)
    keys: IbDcfKeyBatch | None = None
    alive_keys: np.ndarray | None = None
    frontier: collect.Frontier | None = None
    _children: object | None = None  # expand-time child-state cache
    _peer_reader: asyncio.StreamReader | None = None
    _peer_writer: asyncio.StreamWriter | None = None
    _ot: object | None = None  # secure-plane marker (both endpoints below)
    _ot_snd: object | None = None  # extension sender (levels this side garbles)
    _ot_rcv: object | None = None  # extension receiver (levels it evaluates)
    _sec_seed: np.ndarray | None = None  # session seed for GC/b2a randomness
    _crawl_ctr: int = 0  # makes per-crawl garbling randomness unique
    _last_shares: np.ndarray | None = None  # last-level leaf count shares
    # mid-level sharding: per-shard child caches / leaf shares keyed by
    # span lo, assembled at prune time (collect.children_cat); a shard
    # re-run simply overwrites its slot
    _shard_children: dict = field(default_factory=dict)
    _shard_last: dict = field(default_factory=dict)
    _shard_level: int | None = None
    _mask_cache: tuple | None = None  # ((level, F, f255), full-level rows)
    # pipelined-crawl expand stage: device work dispatched at FRAME
    # ARRIVAL (before the verb lock) keyed by (kind, level, span), so
    # span k+1's FSS expansion runs while span k's open stage is on the
    # data plane.  Entries are pure functions of (keys, frontier, level,
    # span) — reuse across a shard re-run is bit-identical — and every
    # frontier mutation (prune/restore/init/reset) clears the dict.
    _expand_ready: dict = field(default_factory=dict)
    _sketch_parts: list = field(default_factory=list)
    _sketch: object | None = None  # SketchKeyBatch (malicious-secure mode)
    _sketch_states: object | None = None  # DpfEvalState [F, N, d], frontier-following
    _sketch_pids: np.ndarray | None = None  # int32[F, d] per-dim prefix ids
    _sketch_depth: int = 0  # how far the sketch frontier has advanced
    _sketch_pairs: tuple | None = None  # (pair shares [F, N, d, lanes], depth)
    _sketch_pairs_field: object | None = None
    _sketch_seed: np.ndarray | None = None  # coin-flipped session seed
    # challenge ratchet (sketch.py): the root seed committed at tree_init
    # and the boot-independent transcript digest — together they derive
    # each level's challenge, so a recovered level replays the IDENTICAL
    # challenge instead of re-opening triples under fresh randomness
    _sketch_root: np.ndarray | None = None
    _ratchet_digest: bytes | None = None
    # telemetry: phase timers (the reference's 3-phase level taxonomy,
    # collect.rs:412-503, as "fss"/"gc_ot"/"field"), data-plane byte and
    # device-fetch accounting, gc_tests — all per level (obs/report.py
    # names the full schema).  One registry PER server: the bench and the
    # tests run both servers in one process and the run report asserts
    # their accounting consistent against each other.
    obs: obsmetrics.Registry | None = None
    _verb_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # resilience state: where tree_checkpoint persists crawl state (None
    # disables the verb), the boot id that lets a reconnecting leader
    # distinguish "same process, blipped network" from "restarted, state
    # gone", per-leader-session replay dedup, and the peer address kept
    # for plane_reset redials
    ckpt_dir: str | None = None
    _boot_id: str = field(default_factory=lambda: _secrets.token_hex(8))
    _sessions: dict = field(default_factory=dict)
    _peer_addr: tuple | None = None
    _ctl_writers: set = field(default_factory=set)
    # streaming ingest front door: bounded per-window key pools
    # (submit_keys → window_seal → window_load) and the admission gate
    # (resilience/admission.py) deciding admit/shed/Overloaded; tests
    # may swap _admission for one with a manual clock
    _ingest_pools: dict = field(default_factory=dict)
    _admission: object | None = None
    # multi-chip client sharding (parallel/server_mesh.py): the local
    # pjit mesh the client axis shards over, and an optional injected
    # device-loss schedule (resilience.chaos.MeshChaos — reused from the
    # 2-D mesh path; tests and bin/server wire FHH_MESH_FAULTS here)
    _mesh: object | None = None
    _mesh_chaos: object | None = None

    def __post_init__(self):
        if self.obs is None:
            self.obs = obsmetrics.Registry(f"server{self.server_id}")
        if self._mesh is None:
            k = smesh.resolve_data_devices(self.cfg.server_data_devices)
            if k > 1:
                self._mesh = smesh.ServerMesh(k)
        if self._admission is None:
            self._admission = resadmission.AdmissionController(
                max_window_keys=self.cfg.ingest_window_keys,
                rate_keys_per_s=self.cfg.ingest_rate_keys_per_s,
                burst_keys=self.cfg.ingest_burst_keys,
                client_quota=self.cfg.ingest_client_quota,
                shed=self.cfg.ingest_shed,
                seed=self.cfg.ingest_seed,
            )
        # LAST: the sanitizer (a no-op unless FHH_DEBUG_GUARDS=1 or
        # cfg.debug_guards) wraps the already-constructed guarded state
        guards.install(self, _SERVER_GUARDS, force=self.cfg.debug_guards)

    # -- verbs (ref: rpc.rs:56-66) ---------------------------------------

    async def reset(self, _req) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        self.keys_parts.clear()
        self.keys = None
        self.alive_keys = None
        self.frontier = None
        self._children = None
        self._last_shares = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        self._sketch_parts.clear()
        self._sketch = None
        self._sketch_states = None
        self._sketch_pids = None
        self._sketch_depth = 0
        self._sketch_pairs = None
        self._sketch_pairs_field = None
        self._sketch_root = None
        self._ratchet_digest = None
        self._ingest_pools.clear()  # a new collection's front door opens clean
        self._ckpt_clear()  # a new collection must not resume an old one's
        self.obs.reset()  # fresh per-collection phase/byte/fetch accounting
        if self._ot is not None:  # fresh GC/b2a randomness per collection
            self._sec_seed = np.frombuffer(
                _secrets.token_bytes(16), dtype="<u4"
            ).copy()
        return True

    async def add_keys(self, req) -> bool:  # fhh-race: atomic (unlocked upload fast path: append-only, never suspends — many in-flight batches deserialize concurrently by design)
        """req: pytree-of-arrays key batch chunk [B, d, 2] (the tensor form
        of AddKeysRequest, ref: rpc.rs:13-15).  An optional ``sketch`` entry
        carries the clients' malicious-security material (MAC'd payload
        DPFs + triples, protocol/sketch.py)."""
        self.keys_parts.append(IbDcfKeyBatch(*req["keys"]))
        if req.get("sketch") is not None:
            self._sketch_parts.append(
                jax.tree.unflatten(
                    jax.tree.structure(_SKETCH_TREEDEF), req["sketch"]
                )
            )
        return True

    def _planar(self) -> bool:
        """This server's frontier LAYOUT: the process expand engine,
        except under the multi-chip mesh, which pins interleaved/XLA
        (the client axis must be a plain named axis — pallas_call takes
        no sharded operands; same pin as the 2-D mesh bodies)."""
        return collect._expand_engine() and self._mesh is None

    def _concat_keys(self) -> None:
        """Materialize ``self.keys`` from the uploaded chunks (shared by
        ``tree_init`` and ``tree_restore`` — a restored server re-receives
        its key chunks but must NOT re-root its frontier).  Under the
        multi-chip mesh the batch binds the active shard count and the
        key planes land client-axis-sharded across the local devices."""
        self.keys = IbDcfKeyBatch(
            *[
                # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (wire input: the uploaded chunks are host numpy already — np.asarray is a no-copy view; runs once per collection/restore, never per level)
                np.concatenate([np.asarray(p[i]) for p in self.keys_parts])
                for i in range(len(self.keys_parts[0]))
            ]
        )
        if self._mesh is not None:
            self._mesh.bind(self.keys.cw_seed.shape[0])
            self.keys = self._mesh.shard_keys(self.keys)

    async def tree_init(self, req) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        if not self.keys_parts:
            raise RuntimeError("tree_init before add_keys")
        root_bucket = int((req or {}).get("root_bucket", 1))
        self._concat_keys()
        n = self.keys.cw_seed.shape[0]
        self.alive_keys = np.ones(n, bool)
        if self._mesh is not None:
            self.frontier = self._mesh.shard_frontier(
                collect.tree_init(self.keys, root_bucket, planar=False)
            )
        else:
            self.frontier = collect.tree_init(self.keys, root_bucket)
        self._children = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        if self._sketch_parts:
            self._concat_sketch()
            root = dpf.eval_init(self._sketch.key)  # [N, d]
            self._sketch_states = jax.tree.map(
                lambda a: jnp.broadcast_to(a[None], (1,) + a.shape), root
            )
            self._sketch_pids = np.zeros(
                (1, self._sketch.key.root_seed.shape[1]), np.int32
            )
            self._sketch_depth = 0
            self._sketch_pairs = None
            # commit the challenge ratchet: root = the coin flip of the
            # CURRENT data-plane session (unpredictable to clients, who
            # committed their keys before this point), transcript = empty.
            # Both are checkpointed with the frontier, so later plane
            # resets / restarts cannot perturb any level's challenge.
            self._sketch_root = np.asarray(self._sketch_seed, np.uint32).copy()
            self._ratchet_digest = sketchmod.transcript_init()
        return True

    def _concat_sketch(self) -> None:
        """Materialize ``self._sketch`` from the uploaded chunks (shared
        by ``tree_init`` and the sketch ``tree_restore`` path — a restored
        server re-receives its sketch chunks but must NOT re-root its
        frontier-following states)."""
        leaves = [jax.tree.leaves(p) for p in self._sketch_parts]
        # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (wire input: uploaded sketch chunks are host numpy; once per collection/restore)
        cat = [np.concatenate([np.asarray(p[i]) for p in leaves])
               for i in range(len(leaves[0]))]
        self._sketch = jax.tree.unflatten(
            jax.tree.structure(_SKETCH_TREEDEF), cat
        )

    def _challenge_seed(self, level: int) -> np.ndarray:
        """This level's sketch challenge via the ratchet (sketch.py):
        hash(committed root ‖ level ‖ transcript digest).  Falls back to
        the raw session seed only when the ratchet was never committed
        (sketch keys without tree_init — a protocol error soon anyway)."""
        if self._sketch_root is None:
            return self._sketch_seed
        return sketchmod.ratchet_seed(
            self._sketch_root, level, self._ratchet_digest
        )

    async def sketch_verify(self, req) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Malicious-security check (ref intent: the TreeSketchFrontier*
        verb vestiges rpc.rs:40-51, gate at collect.rs:495): sketch inner
        products + Beaver verification over the peer data plane, per
        (client, dim) — a client fails if ANY dim fails; failing clients'
        liveness flags flip before the gated counts are taken.

        Depth semantics: ``level == 0`` verifies the FULL depth-1 level
        (both children of every dim's root, evaluated on the fly) so the
        first threshold never acts on unverified counts; ``level >= 2``
        verifies the depth-``level`` frontier shares stored by the prune
        of ``level - 1``.  Depth 1's frontier re-verify is deliberately
        absent — its Beaver triples were consumed by the level-0 full
        check, and re-opening them under a second challenge would leak
        ``<r - r', x>`` (see protocol/sketch.py scope note).

        The challenge randomness comes from the per-level RATCHET
        (sketch.py): hash(coin-flipped root committed at ``tree_init`` ‖
        level ‖ crawl-transcript digest) — never a public constant (a
        client must not be able to predict r), and DETERMINISTIC given
        the crawl transcript, so a level re-run after checkpoint recovery
        replays the identical challenge instead of re-opening its Beaver
        triple slab under fresh randomness (which would leak
        ``<r - r', x>``)."""
        if self._sketch is None:
            raise RuntimeError("sketch_verify without sketch keys")
        level = int(req["level"])
        k = self._sketch.key
        L = k.data_len
        n, d = k.root_seed.shape[0], k.root_seed.shape[1]
        if level == 0:
            if self._sketch_depth != 0:
                # the root check must run before the first prune: the
                # frontier-following states have advanced past the root,
                # so a late call would verify garbage and corrupt honest
                # clients' liveness flags
                raise RuntimeError(
                    "level-0 full check called after the tree advanced"
                )
            # full-width depth-1 check: both children of the root per dim
            last = L == 1
            fld = F255 if last else FE62
            st = jax.tree.map(lambda a: a[0], self._sketch_states)  # [N, d]
            cw = dpf.level_cw(k, 0)
            cwv = k.cw_val[..., 0, :] if not last else k.cw_val_last
            sides = []
            for c in (False, True):
                _, p = dpf.eval_bit(
                    cw, st, jnp.full((n, d), c), cwv, k.key_idx, fld,
                    sketchmod.LANES,
                )
                sides.append(p)
            pairs_fn = jnp.stack(sides)  # [2, N, d, LANES(, limbs)]
            m_nodes, dpf_level = 2, 0
        else:
            if L == 1:
                # the level-0 full check already consumed triples_last; a
                # second opening under a fresh challenge leaks <r - r', x>
                raise RuntimeError(
                    "data_len=1: the leaf check is the level-0 full check"
                )
            if level == 1:
                # the server is the enforcement boundary, not the leader:
                # depth 1's triples (index 0) were consumed by the level-0
                # full check, and opening them again under level 1's
                # different challenge reveals <r - r', x> of honest
                # clients' payloads
                raise RuntimeError(
                    "depth 1 is covered by the level-0 full check; "
                    "re-verifying it would re-open its Beaver triples"
                )
            if self._sketch_pairs is None or self._sketch_pairs[1] != level:
                raise RuntimeError(f"no stored sketch shares for depth {level}")
            pairs_fn, _ = self._sketch_pairs  # [F, N, d, LANES(, limbs)]
            fld = self._sketch_pairs_field
            last = fld is F255
            m_nodes, dpf_level = pairs_fn.shape[0], level - 1
        challenge = self._challenge_seed(level)
        bs = max(
            1,
            self.cfg.sketch_batch_size_last if last else self.cfg.sketch_batch_size,
        )
        ok_parts = []  # per-batch device verdicts; ONE fetch after the loop
        for lo in range(0, n, bs):
            sl = slice(lo, min(lo + bs, n))
            ks = jax.tree.map(lambda a: a[sl], self._sketch)
            n_sl = min(lo + bs, n) - lo
            r, rands = sketchmod.shared_r_stream(
                fld, challenge, level, m_nodes, n_sl * d
            )
            rands = rands.reshape((n_sl, d, 3) + fld.limb_shape)
            pairs = pairs_fn[:, sl]  # [F, n_sl, d, lanes(, limbs)]
            pairs = jnp.moveaxis(jnp.asarray(pairs), 0, 2)  # [n_sl, d, F, ...]
            out = sketchmod.sketch_output(fld, pairs, r, rands)
            if last:
                trip, mk, mk2 = ks.triples_last, ks.mac_key_last, ks.mac_key2_last
            else:
                trip = mpc.level_slab(ks.triples, dpf_level)
                mk, mk2 = ks.mac_key, ks.mac_key2
            mk = jnp.expand_dims(jnp.asarray(mk), 1)  # broadcast over dims
            mk2 = jnp.expand_dims(jnp.asarray(mk2), 1)
            state = sketchmod.mul_state(fld, out, mk, mk2, trip)
            # one stacked array = one device fetch + one wire message
            # fhh-lint: disable=host-sync-in-hot-loop,chunked-device-readback (wire fetch: the
            # exchange below needs host bytes; one fetch per round trip)
            cs = np.asarray(jnp.stack(mpc.cor_share(fld, state)))
            peer_cs = await self._swap(cs)
            pair_cs = (cs, peer_cs) if self.server_id == 0 else (peer_cs, cs)
            opened = mpc.cor(fld, (pair_cs[0][0], pair_cs[0][1]),
                             (pair_cs[1][0], pair_cs[1][1]))
            # fhh-lint: disable=host-sync-in-hot-loop,chunked-device-readback (wire fetch, as above)
            o = np.asarray(
                mpc.out_share(fld, bool(self.server_id), state, opened)
            )
            peer_o = await self._swap(o)
            # verdicts stay ON DEVICE inside the loop; fetching per batch
            # cost one round trip per `bs` clients (fhh-lint caught it)
            ok_parts.append(mpc.verify(fld, o, peer_o))  # [n_sl, d]
        if ok_parts:
            # fhh-lint: disable=host-sync-in-hot-loop (one post-loop readback)
            ok_nd = np.asarray(jnp.concatenate(ok_parts, axis=0))  # [n, d]
            ok = ok_nd.all(axis=1)
        else:  # n == 0: nothing to verify
            ok = np.ones(n, bool)
        if level != 0:
            # one-shot within a boot: each stored depth's pairs open once;
            # a same-boot duplicate call is answered by the session dedup
            # cache, and a post-recovery re-run reloads the pairs from the
            # checkpoint and replays the IDENTICAL ratcheted challenge
            # (same root, same transcript) — a replay, not a second
            # opening.  The level-0 path has no stored pairs and re-runs
            # under the same identical-challenge argument.
            self._sketch_pairs = None
        self.alive_keys &= ok
        return self.alive_keys.copy()

    def _advance_sketch(self, level: int, parent: np.ndarray, pat_bits: np.ndarray, n_alive: int):
        """Advance the frontier-following sketch DPF states with the same
        survivor table as the count frontier (one 1-D sketch tree per
        dimension; dim j's direction is pattern bit j), storing the new
        depth's value-pair shares gated by node liveness AND per-dim
        prefix DEDUPLICATION: in d > 1 the count frontier is a product —
        two frontier nodes routinely share the same dim-j prefix, and
        counting an honest one-hot entry twice makes ``<r,x>² != <r²,x>``
        (with r_i + r_j in place of a single r).  Each dim keeps only the
        FIRST slot of every distinct prefix; the dedup table derives from
        the public survivor table, so both servers gate identically."""
        L = self.keys.cw_seed.shape[-2]
        last = level == L - 1
        fld = F255 if last else FE62
        k = self._sketch.key  # batch [N, d]
        d = k.root_seed.shape[1]
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(parent)
        st = jax.tree.map(lambda a: a[parent], self._sketch_states)
        direction = jnp.asarray(pat_bits, bool)[:, None, :]  # [F, 1, d]
        cw = tuple(a[None] for a in dpf.level_cw(k, level))  # [1, N, d, ...]
        cwv = (k.cw_val[..., level, :] if not last else k.cw_val_last)[None]
        new_st, pair = dpf.eval_bit(
            cw, st, direction, cwv, k.key_idx[None], fld, sketchmod.LANES
        )  # pair [F, N, d, LANES(, limbs)]
        F2 = parent.shape[0]
        pids = np.zeros((F2, d), np.int32)
        keep = np.zeros((F2, d), bool)
        parent_pid = self._sketch_pids[parent[:n_alive]]  # [n_alive, d]
        for j in range(d):
            key_j = np.stack(
                [parent_pid[:, j], pat_bits[:n_alive, j].astype(np.int32)], 1
            )
            _, inv = np.unique(key_j, axis=0, return_inverse=True)
            pids[:n_alive, j] = inv
            _, first = np.unique(inv, return_index=True)
            keep[first, j] = True
        gate = jnp.asarray(
            keep.reshape((F2, 1, d) + (1,) * (pair.ndim - 3))
        )
        pair = jnp.where(gate, pair, 0)
        self._sketch_states = new_st
        self._sketch_pids = pids
        self._sketch_depth = level + 1
        self._sketch_pairs = (pair, level + 1)
        self._sketch_pairs_field = fld

    # data-plane framing with byte/message accounting; levels attribute
    # via the active span (obs.metrics.Registry.count)
    async def _dp_send(self, obj):
        self.obs.count("data_msgs_sent")
        await _send(
            self._peer_writer, obj,
            count=lambda n: self.obs.count("data_bytes_sent", n),
        )

    async def _dp_recv(self):
        return await _recv(
            self._peer_reader,
            count=lambda n: self.obs.count("data_bytes_recv", n),
        )

    async def _swap(self, obj):
        """Role-ordered data-plane exchange: server 0 writes first, server 1
        reads first — symmetric send-then-recv deadlocks once payloads
        exceed the combined socket buffers (both drains stall)."""
        if self.server_id == 0:
            await self._dp_send(obj)
            return await self._dp_recv()
        peer = await self._dp_recv()
        await self._dp_send(obj)
        return peer

    def _emit_level_phases(self, level: int, fss, gc_ot, field) -> None:
        """Per-level phase line (the successor of the old three prints):
        structured, severity=debug so a 512-level crawl doesn't spam the
        console, totals always available in the run report.  Takes the
        three exited spans — their ``seconds`` is THIS pass's duration,
        where the registry total would inflate on a re-crawled level."""
        obs.emit(
            "level.phases",
            severity="debug",
            server=self.server_id,
            level=level,
            fss_s=fss.seconds,
            gc_ot_s=gc_ot.seconds,
            field_s=field.seconds,
        )

    def _shard_frontier(self, shard):  # fhh-race: atomic (pure slice of the frontier, never suspends; reached from the frame-arrival pre-expand)
        """The frontier view one crawl verb works on: the whole frontier
        (``shard`` None) or the node span ``[lo, hi)`` of it.  Both
        servers receive identical shard spans from the leader, so their
        data-plane exchanges stay positionally matched."""
        if shard is None:
            return self.frontier
        return collect.frontier_slice(
            self.frontier, shard[0], shard[1], planar=self._planar()
        )

    def _stash_children(self, level, shard, children) -> None:
        """Bank one crawl's child-state cache for the coming prune: whole
        level under ``_children``, shards keyed by span ``lo`` (a shard
        RE-RUN overwrites its slot — exactly the retry semantics)."""
        if shard is None:
            self._children = children
            return
        if self._shard_level != int(level):
            # first shard of a new level: drop any stale spans
            self._shard_children.clear()
            self._shard_last.clear()
            self._shard_level = int(level)
        self._children = None  # sharded levels assemble at prune time
        if children is not None:
            self._shard_children[int(shard[0])] = children

    # -- expand stage (device) vs open stage (plane I/O) -----------------
    #
    # The per-span crawl is split so the DEVICE half can run ahead of the
    # PLANE half: ``_do_expand`` dispatches the FSS expansion (+ string
    # extraction in secure mode) and starts the host copy; the open stage
    # consumes it under the verb lock.  ``_maybe_pre_expand`` runs the
    # expand stage at FRAME ARRIVAL — while the previous span's open
    # stage is awaiting the data plane — which is what overlaps span k's
    # GC/OT network phase with span k+1's device compute (the leader
    # keeps both frames in flight via ``crawl_pipeline_depth``).

    def _do_expand(self, level: int, last: bool, shard) -> dict:  # fhh-race: atomic (dispatch-only device work, never suspends; called both under the verb lock and from the frame-arrival pre-expand)
        """Device half of one crawl span: dispatch-only (no sync — a
        block_until_ready here would cost a tunnel RTT); pure function of
        (keys, frontier, level, span), so a shard re-run may reuse it
        bit-identically."""
        frontier = self._shard_frontier(shard)
        packed, children = collect.expand_share_bits(
            self.keys, frontier, level, want_children=not last,
            use_pallas=False if self._mesh is not None else None,
        )
        out = {"packed": packed, "children": children, "frontier": frontier}
        if self.cfg.secure_exchange:
            d = self.keys.cw_seed.shape[1]
            if self._mesh is not None:
                # row-sharded kernel stage (parallel/kernel_shard.py):
                # the whole-level planar test batch partitions along its
                # row/block axis across the data mesh — extension,
                # equality kernels, and b2a all run per shard with a
                # byte-identical wire, so nothing between FSS expansion
                # and the frame serializes onto one device
                F_, N = packed.shape
                C = 1 << d
                B = F_ * C * N
                ks = self._mesh.kernel_bind(
                    B, 2 * d, self.cfg.secure_kernel_shards
                )
                if ks is not None:
                    out["flat"] = kernel_shard.shard_flat(
                        ks, packed, d, F_, N
                    )
                    out["dims"] = (F_, C, N, 2 * d)
                    out["kernel"] = ks
                    return out
                # degraded path (batch fills a single planar block, or
                # secure_kernel_shards pins 1): the pre-PR-10 gather of
                # the packed share bits over ICI onto one device.  The
                # counter is the layout detector (a fully sharded crawl
                # never increments it); the timer records DISPATCH time
                # only — device_put returns before the transfer, which
                # completes lazily under the level's later fetch, so a
                # sync here would block this (possibly frame-arrival)
                # context for a full tunnel RTT
                t0 = time.monotonic()
                packed = self._mesh.gather(packed)
                self.obs.timer_add(
                    "kernel_gather", time.monotonic() - t0, level=int(level)
                )
                self.obs.count("kernel_gathers", level=int(level))
            strs = secure.child_strings(packed, d)  # [F, C, N, S]
            F_, C, N, S = strs.shape
            out["flat"] = strs.reshape(F_ * C * N, S)
            out["dims"] = (F_, C, N, S)
        else:
            # double-buffer: the D2H copy of the packed bits starts NOW
            # and lands while other work (the previous span's exchange)
            # holds the event loop
            _start_host_copy(packed)
        return out

    def _expand_stage(self, level: int, last: bool, shard) -> dict:
        hit = self._expand_ready.pop((bool(last), int(level), shard), None)
        if hit is not None:
            self.obs.count("pipeline_expand_hits", level=int(level))
            return hit
        return self._do_expand(level, last, shard)

    def _maybe_pre_expand(self, verb: str, req) -> None:  # fhh-race: atomic (frame-arrival prefetch: reads frontier/keys and stashes in one event-loop slice; every frontier mutation clears the stash before the next slice)
        """Frame-arrival hook (``_dispatch``, BEFORE the verb lock): run
        the expand stage for a sharded crawl verb while earlier spans
        still hold the lock.  Purely an overlap optimization — any
        refusal or failure here just means the verb recomputes (and
        surfaces the real error) under the lock."""
        if verb not in ("tree_crawl", "tree_crawl_last"):
            return
        shard = self._parse_shard(req)
        if shard is None or self.keys is None or self.frontier is None:
            return
        if shard[1] > self.frontier.f_bucket:
            return  # span from another life (stale replay): let it fail
        level, last = int(req["level"]), verb == "tree_crawl_last"
        key = (last, level, shard)
        # bound the stash: depth-many entries live at a time in practice;
        # 32 is far above any sane pipeline depth
        if key in self._expand_ready or len(self._expand_ready) >= 32:
            return
        try:
            t0 = time.monotonic()
            self._expand_ready[key] = self._do_expand(level, last, shard)
            # dispatch time only, attributed to the fss phase the verb
            # would otherwise have spent it in (no span: another verb's
            # span may be active on this registry right now)
            self.obs.timer_add("fss", time.monotonic() - t0, level=level)
            self.obs.count("pipeline_pre_expands", level=level)
        except Exception:  # fhh-lint: disable=broad-except (prefetch only: the verb recomputes under the lock and surfaces the real error to the leader)
            self._expand_ready.pop(key, None)

    async def _crawl_counts(
        self, level: int, last: bool = False, shard=None
    ) -> np.ndarray:
        # per-level phase taxonomy of the reference (collect.rs:412-503);
        # trusted mode's "GC and OT" slot is the plaintext exchange
        with self.obs.span("fss", level=level) as sp_fss:
            ex = self._expand_stage(level, last, shard)
            packed, children, frontier = (
                ex["packed"], ex["children"], ex["frontier"]
            )
            # forces the device work to finish
            packed_np = await _fetch(packed, self.obs)
        with self.obs.span("gc_ot", level=level) as sp_gc:
            # data plane: swap packed share bits with the peer server
            peer = await self._swap(packed_np)
        with self.obs.span("field", level=level) as sp_field:
            masks = collect.pattern_masks(self.keys.cw_seed.shape[1])
            counts = await self._reduced_fetch(
                level, collect.counts_by_pattern,
                packed, peer, masks, self.alive_keys, frontier.alive,
            )
        self._emit_level_phases(level, sp_fss, sp_gc, sp_field)
        self._stash_children(level, shard, children)
        return counts

    async def _reduced_fetch(self, level: int, single_fn, *args):
        """The per-level reduction + host fetch shared by the trusted
        (``collect.counts_by_pattern``) and secure
        (``secure.node_share_sums``) crawl paths.  Under the multi-chip
        mesh the per-shard client-axis partials fold over ICI (psum)
        BEFORE the fetch — :class:`~..parallel.server_mesh.ServerMesh`
        mirrors the single-device reduction API by name, so the mesh
        form is found via ``single_fn.__name__`` — and the fetch-synced
        ``ici_reduce`` span is the reduction's cost instrument.  Either
        way the caller (and with it the wire) gets host values in the
        single-device layout."""
        if self._mesh is not None:
            self.obs.gauge("data_shards", self._mesh.shards, level=level)
            with self.obs.span("ici_reduce", level=level):
                out = getattr(self._mesh, single_fn.__name__)(*args)
                return await _fetch(out, self.obs)
        return await _fetch(single_fn(*args), self.obs)

    async def _phase_sync(self, x) -> None:
        """Device sync at a secure-kernel phase boundary (OFF the event
        loop — a bare block_until_ready would starve keepalives exactly
        like a bare np.asarray).  Gated by ``cfg.secure_phase_sync``: the
        phases are sequential data-dependent steps, so syncing costs only
        the dispatch-ahead slack, and buys the phase_otext/garble/eval/
        b2a spans real device seconds instead of dispatch time."""
        if self.cfg.secure_phase_sync:
            await asyncio.to_thread(jax.block_until_ready, x)

    def _zero_phases(self, level: int, *names: str) -> None:
        """Materialize zero-valued phase timers so the secure-kernel
        split always carries all four keys on both servers (a garbler
        has no eval phase, the ot2s path has no garble phase — the run
        report must show those as 0, not absent)."""
        for n in names:
            self.obs.timer_add(n, 0.0, level=level)

    async def _crawl_counts_secure(
        self, level: int, count_field, last: bool = False, garbler: int = 0,
        shard=None, ot_path=None,
    ) -> np.ndarray:
        """The real 2PC data plane (ref: collect.rs:419-501): equality +
        OT b2a over the peer socket; returns this server's additive field
        share of every per-(node, pattern) count.  No packed share-bit
        tensor ever crosses the server boundary in this mode.

        ``garbler`` names the server that garbles this level (the leader
        alternates it per level — the reference's ``gc_sender`` flag,
        rpc.rs:20-23 — so garbling cost splits across the servers); each
        direction runs its own OT-extension session (``_setup_secure``).
        Every data-plane message is ONE packed array and a level is ONE
        protocol round trip with exactly one device fetch per message:
        ev u -> sender's whole-level planar message — the 1-of-2^S
        payload table when ``secure.ot_path`` picks "ot2s" (no garbled
        circuit at all), the packed garbled batch with the b2a payloads
        riding the OUTPUT wire labels otherwise — built by ONE fused
        device program per side (secure.gb_step_level/ev_open_level; the
        reference runs per-core GC then a separate OT round here,
        collect.rs:419-482).

        The ``gc_ot`` span splits into the secure-kernel phases
        ``otext`` (extension), ``garble``/``eval`` (circuit work — zero
        on the ot2s path), and ``b2a`` (payload table / open + field
        conversion); wire waits are the gc_ot remainder."""
        with self.obs.span("fss", level=level) as sp_fss:
            # dispatch time only: the FSS expansion itself overlaps the
            # exchange below (no sync — a block_until_ready here would
            # cost a tunnel RTT); a pipelined leader already ran this
            # stage at frame arrival (``_maybe_pre_expand``)
            ex = self._expand_stage(level, last, shard)
            children, frontier, flat = (
                ex["children"], ex["frontier"], ex["flat"]
            )
            F_, C, N, S = ex["dims"]
            B = F_ * C * N
            self.obs.count("gc_tests", B, level=level)
            self.obs.gauge("ot_batch_size", B * S, level=level)
        with self.obs.span("gc_ot", level=level) as sp_gc:
            w = secure.alive_weight(frontier.alive, self.alive_keys, C)
            # crawl counter makes every garbling's randomness unique even
            # if a leader re-crawls a level without reset (seed reuse with
            # a fixed R = s would leak cross-run equality deltas to the
            # evaluator)
            self._crawl_ctr += 1
            gc_seed = secure.derive_seed(self._sec_seed, 1, level, self._crawl_ctr)
            b2a_seed = secure.derive_seed(self._sec_seed, 2, level, self._crawl_ctr)
            # the leader names the path per verb (like ``garbler``) so
            # both servers always agree on the wire format even when a
            # bench/parity leader overrides its own config; absent, the
            # server's config decides
            path = secure.ot_path(S, ot_path or self.cfg.ot_path)
            self.obs.count(f"ot_path_{path}", level=level)
            W = secure.payload_words(count_field)
            ks = ex.get("kernel")
            if self._mesh is not None:
                # per-level kernel layout: the active row-shard count (1
                # = the degraded gather path) feeds the mesh report
                # section and the acceptance gate (kernel_gather ~ 0)
                self.obs.gauge(
                    "kernel_shards", ks.k if ks is not None else 1,
                    level=level,
                )
            if self.server_id == garbler:  # garbler/sender + OT-ext sender
                u = await self._dp_recv()
                if ks is not None:
                    # ROW-SHARDED kernel stage: extension, payload pair,
                    # and the equality kernel all run per mesh shard
                    # (parallel/kernel_shard.py); the frame reads back
                    # per shard and reassembles positionally — nothing
                    # gathers onto one device
                    with self.obs.span("otext", level=level):
                        q, idx0 = kernel_shard.snd_extend(
                            ks, self._ot_snd, u
                        )
                        await self._phase_sync(q)
                    kphase = "b2a" if path == "ot2s" else "garble"
                    with self.obs.span(kphase, level=level):
                        planes, vals = kernel_shard.gb_kernel(
                            ks, self._ot_snd.s_block, q, flat, gc_seed,
                            b2a_seed, count_field, garbler, path, idx0,
                        )
                        await self._phase_sync(planes)
                    self._zero_phases(
                        level, "eval",
                        *(("garble",) if path == "ot2s" else ("b2a",)),
                    )
                    self.obs.count("device_fetches", ks.k, level=level)
                    # msg_wire starts the per-shard D2H copies itself
                    msg_np = await asyncio.to_thread(
                        kernel_shard.msg_wire, ks, planes
                    )
                    await self._dp_send(msg_np)
                else:
                    with self.obs.span("otext", level=level):
                        idx0 = self._ot_snd.consumed
                        q = self._ot_snd.extend(B * S, u)
                        await self._phase_sync(q)
                    with self.obs.span("b2a", level=level):
                        vals, w0, w1 = secure.b2a_payload_pair(
                            count_field, b2a_seed, B, garbler
                        )
                        if path == "ot2s":
                            msg = secure.ot2s_encrypt_packed(
                                q.reshape(B, S, 4),
                                jnp.asarray(self._ot_snd.s_block), flat,
                                w1, w0, W, idx0,
                            )
                        await self._phase_sync(w1 if path != "ot2s" else msg)
                    if path == "ot2s":
                        self._zero_phases(level, "garble", "eval")
                    else:
                        with self.obs.span("garble", level=level):
                            msg, _ = gc.garble_equality_payload_packed(
                                jnp.asarray(self._ot_snd.s_block),
                                q.reshape(B, S, 4), jnp.asarray(gc_seed),
                                flat, w1, w0, W, idx0,
                            )
                            await self._phase_sync(msg)
                        self._zero_phases(level, "eval")
                    await self._dp_send(await _fetch(msg, self.obs))
            else:  # evaluator + OT receiver (inputs stay on device: each
                # np.asarray here would cost a full tunnel round trip)
                if ks is not None:
                    with self.obs.span("otext", level=level):
                        u_arr, t_rows, idx0 = kernel_shard.rcv_extend(
                            ks, self._ot_rcv, flat
                        )
                        self.obs.count("device_fetches", ks.k, level=level)
                        # u_wire starts the per-shard D2H copies itself
                        u_np = await asyncio.to_thread(
                            kernel_shard.u_wire, ks, u_arr
                        )
                    await self._dp_send(u_np)
                    bmsg = await self._dp_recv()
                    kphase = "b2a" if path == "ot2s" else "eval"
                    with self.obs.span(kphase, level=level):
                        vals = kernel_shard.ev_open(
                            ks, t_rows, flat, bmsg, count_field, path, idx0
                        )
                        await self._phase_sync(vals)
                    self._zero_phases(
                        level, "garble",
                        *(("eval",) if path == "ot2s" else ("b2a",)),
                    )
                else:
                    with self.obs.span("otext", level=level):
                        u, t_rows, idx0 = secure.ev_step1_fused(
                            self._ot_rcv, flat
                        )
                        u_np = await _fetch(u, self.obs)  # forces the extension
                    await self._dp_send(u_np)
                    bmsg = await self._dp_recv()
                    if path == "ot2s":
                        with self.obs.span("b2a", level=level):
                            pay = secure.ot2s_decrypt_packed(
                                jnp.asarray(t_rows).reshape(B, S, 4), flat,
                                bmsg, W, idx0,
                            )
                            vals = secure.words_to_field(count_field, pay)
                            await self._phase_sync(vals)
                        self._zero_phases(level, "garble", "eval")
                    else:
                        with self.obs.span("eval", level=level):
                            _, pay = gc.eval_equality_payload_packed(
                                bmsg, jnp.asarray(t_rows).reshape(B, S, 4),
                                W, idx0,
                            )
                            await self._phase_sync(pay)
                        with self.obs.span("b2a", level=level):
                            vals = secure.words_to_field(count_field, pay)
                            await self._phase_sync(vals)
                        self._zero_phases(level, "garble")
        with self.obs.span("field", level=level) as sp_field:
            if ks is not None:
                # test-sharded b2a shares: scatter into the (F, C, N)
                # frame per shard, alive-gate, and psum back over ICI —
                # the kernel-stage twin of ServerMesh.node_share_sums
                self.obs.gauge("data_shards", self._mesh.shards, level=level)
                with self.obs.span("ici_reduce", level=level):
                    out = kernel_shard.share_sums(
                        ks, count_field, vals, w, F_, C, N
                    )
                    shares = await _fetch(out, self.obs)
            else:
                vals = vals.reshape((F_, C, N) + count_field.limb_shape)
                shares = await self._reduced_fetch(
                    level, secure.node_share_sums,
                    count_field, vals, jnp.asarray(w),
                )
        self._emit_level_phases(level, sp_fss, sp_gc, sp_field)
        self._stash_children(level, shard, children)
        return shares

    @staticmethod
    def _parse_shard(req):
        s = (req or {}).get("shard")
        return None if s is None else (int(s[0]), int(s[1]))

    def _mask_rows(self, level: int, shard, C: int, f255: bool) -> np.ndarray:
        """Wire-format mask rows for one (level, shard): the FULL-level
        stream sliced to the shard's node rows — the leader's uniform
        v0 - v1 reconstruction must be shard-oblivious, so a node's mask
        cannot depend on how the level was sharded.  One-entry cache: the
        S shard verbs of a level would otherwise each regenerate the
        whole level's stream (the mask is a pure function of
        (level, size), so staleness is impossible)."""
        F = self.frontier.f_bucket
        key = (level, F, f255)
        if self._mask_cache is None or self._mask_cache[0] != key:
            full = (
                mask_f255(level, F * C).reshape(F, C, 8)
                if f255
                else mask_fe62(level, F * C).reshape(F, C)
            )
            self._mask_cache = (key, full)
        full = self._mask_cache[1]
        return full if shard is None else full[shard[0] : shard[1]]

    async def _mesh_guard(self, level, thunk):
        """Device-loss containment for the multi-chip server: fire any
        scheduled mesh chaos at the crawl boundary (the same consumed-
        once :class:`resilience.chaos.MeshChaos` schedule the 2-D mesh
        path uses), and on a mesh fault recover IN PLACE — a lost device
        is NOT a lost server.  ``state_lost`` (kill) re-shards the
        frontier from the newest on-disk checkpoint and rebuilds the
        keys from the host-side upload chunks; a suspect collective
        (drop) just re-runs.  Either way the crawl re-runs ONCE inside
        the same verb, so the leader sees a slow span, never a fault —
        ``shards_rerun`` counts the cost, ``levels_rerun`` stays zero.

        The chaos hook fires BEFORE any data-plane I/O of the level, so
        the re-run exchanges with the peer exactly once; a real device
        loss mid-exchange desynchronizes the plane and correctly
        escalates through the verb error to the leader's plane_reset +
        retry machinery instead."""
        try:
            if self._mesh_chaos is not None:
                self._mesh_chaos.before_level(self, int(level))
            return await thunk()
        except reschaos.MeshFaultError as err:
            if self._mesh is None:
                raise
            await self._mesh_recover(int(level), err)
            return await thunk()

    async def _mesh_recover(self, level: int, err) -> None:
        """Re-shard after a device loss (see :meth:`_mesh_guard`)."""
        self.obs.count("mesh_faults", level=level)
        self._expand_ready.clear()  # pre-expanded dispatches are suspect
        state_lost = bool(getattr(err, "state_lost", False))
        if state_lost or self.frontier is None:
            prev = level - 1
            if self.ckpt_dir is None or prev not in self._ckpt_levels():
                # nothing to re-shard from: surface the original fault —
                # the supervising leader owns recovery at that point
                raise RuntimeError(
                    f"mesh device lost at level {level} with no level-"
                    f"{prev} checkpoint to re-shard from"
                ) from err
            self.keys = None  # device-resident: lost with the shard
            await self.tree_restore({"level": prev})
            if self.frontier is None or self.keys is None:
                # the level stamp existed but the blob was ingest-only
                # (windowed front door between windows): pools came
                # back, crawl state did not — escalate exactly like the
                # no-checkpoint case instead of re-running on None
                raise RuntimeError(
                    f"mesh device lost at level {level}: the level-"
                    f"{prev} checkpoint is ingest-only — no crawl state "
                    "to re-shard from"
                ) from err
            self.obs.count("mesh_reshards", level=level)
        self.obs.count("shards_rerun", level=level)
        obs.emit(
            "resilience.mesh_reshard",
            severity="warn",
            server=self.server_id,
            level=level,
            state_lost=state_lost,
            error=str(err),
        )

    async def tree_crawl(self, req) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """-> FE62 shares of per-child counts [F, 2^d] (ref: rpc.rs:60).
        An optional ``shard: (lo, hi)`` restricts the crawl to that node
        span (mid-level retry granularity — the leader assembles)."""
        level = req["level"]
        shard = self._parse_shard(req)
        if self.cfg.secure_exchange:
            return await self._mesh_guard(
                level,
                lambda: self._crawl_counts_secure(
                    level, FE62, garbler=int(req.get("garbler", 0)),
                    shard=shard, ot_path=req.get("ot_path"),
                ),
            )
        counts = await self._mesh_guard(
            level, lambda: self._crawl_counts(level, shard=shard)
        )
        # NB: trusted mode — both servers hold these plaintext counts; the
        # shared-seed mask below is a WIRE-FORMAT shim so the leader's
        # uniform v0 - v1 reconstruction works, not a secrecy mechanism
        # (the reference's hardcoded bogus PRG seed plays the same role,
        # server.rs:331-332).  Secrecy comes from secure_exchange above.
        r = self._mask_rows(level, shard, counts.shape[-1], f255=False)
        if self.server_id == 0:
            # counts are already host-side; the mask add stays host-side
            # too (FE62.np_add) — the old device add + _fetch cost a full
            # tunnel RTT per level for a ~KB elementwise op
            return FE62.np_add(counts.astype(np.uint64), r)
        return r

    async def tree_crawl_last(self, req) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """-> F255 shares [F, 2^d, 8] for the final level (ref: rpc.rs:61,
        collect.rs:775-916 — BlockPair double-block OT payloads in secure
        mode).  Shares are retained for final_shares re-serving; sharded
        calls bank their span and ``tree_prune_last`` assembles."""
        level = req["level"]
        shard = self._parse_shard(req)
        if self.cfg.secure_exchange:
            shares = await self._mesh_guard(
                level,
                lambda: self._crawl_counts_secure(
                    level, F255, last=True,
                    garbler=int(req.get("garbler", 0)), shard=shard,
                    ot_path=req.get("ot_path"),
                ),
            )
        else:
            counts = await self._mesh_guard(
                level,
                lambda: self._crawl_counts(level, last=True, shard=shard),
            )
            r = self._mask_rows(level, shard, counts.shape[-1], f255=True)
            if self.server_id == 0:
                c = np.zeros(counts.shape + (8,), np.uint32)
                c[..., 0] = counts
                # host-side limb add (F255.np_add): no device round trip
                shares = F255.np_add(c, r)
            else:
                shares = r
        if shard is None:
            self._last_shares = shares
        else:
            self._last_shares = None
            self._shard_last[int(shard[0])] = shares
        return shares

    async def tree_prune(self, req) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Fused prune+advance: materialize surviving children
        (ref: rpc.rs:63 tree_prune + collect.rs:918-929).  The sketch DPF
        states advance with the same survivor table."""
        level = req["level"]
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(req["parent_idx"], np.int32)
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        pat_bits = np.asarray(req["pattern_bits"], bool)
        n_alive = int(req["n_alive"])
        self._expand_ready.clear()  # the frontier is about to mutate
        if self._children is None and self._shard_children:
            self._children = self._assemble_shard_children()
        if self._children is not None:  # cache from this level's crawl
            self.frontier = collect.advance_from_children(
                self._children, parent, pat_bits, n_alive
            )
            self._children = None
        else:  # prune without a preceding crawl: re-expand
            self.frontier = collect.advance(
                self.keys, self.frontier, level, parent, pat_bits, n_alive,
                use_pallas=False if self._mesh is not None else None,
            )
        if self._sketch is not None:
            self._advance_sketch(int(level), parent, pat_bits, n_alive)
            self._ratchet_digest = sketchmod.transcript_absorb(
                self._ratchet_digest, int(level), parent, pat_bits, n_alive
            )
        self.obs.gauge("survivors", n_alive, level=int(level))
        return True

    def _assemble_shard_children(self):
        """Stitch the per-shard child caches back into one full-level
        cache; refuses a torn level (a missing span would silently
        advance garbage for its nodes)."""
        children = collect.children_cat(sorted(self._shard_children.items()))
        got = (
            children.seed.shape[4]
            if isinstance(children, collect.PlanarChildren)
            else children.seed.shape[0]
        )
        if got != self.frontier.f_bucket:
            raise RuntimeError(
                f"sharded crawl incomplete: child caches cover {got} of "
                f"{self.frontier.f_bucket} frontier slots"
            )
        self._shard_children.clear()
        return children

    async def tree_prune_last(self, req) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Last level keeps no child count states to advance — compact the
        stored leaf count shares down to the survivors
        (ref: collect.rs:931-942).  The sketch DPF does advance once more
        so its F255 leaf payloads can be verified post-prune."""
        self._expand_ready.clear()  # leaf level: nothing expands past it
        if self._last_shares is None and self._shard_last:
            parts = sorted(self._shard_last.items())
            whole = np.concatenate([p for _, p in parts], axis=0)
            if whole.shape[0] != self.frontier.f_bucket:
                raise RuntimeError(
                    f"sharded last crawl incomplete: shares cover "
                    f"{whole.shape[0]} of {self.frontier.f_bucket} slots"
                )
            self._last_shares = whole
            self._shard_last.clear()
        if self._last_shares is None:  # protocol-boundary check: no assert
            raise RuntimeError("tree_prune_last called before tree_crawl_last")
        self._children = None  # leaf level: nothing advances past it
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(req["parent_idx"], np.int64)
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        pattern = np.asarray(req["pattern_bits"], bool)
        n_alive = int(req["n_alive"])
        d = pattern.shape[1]
        child = (pattern[:n_alive] << np.arange(d)).sum(axis=1)
        self._last_shares = self._last_shares[parent[:n_alive], child]
        if self._sketch is not None:
            L = self.keys.cw_seed.shape[-2]
            self._advance_sketch(
                # fhh-lint: disable=host-sync-in-hot-loop (wire input)
                L - 1, np.asarray(req["parent_idx"], np.int32), pattern, n_alive
            )
            self._ratchet_digest = sketchmod.transcript_absorb(
                self._ratchet_digest, L - 1, parent, pattern, n_alive
            )
        self.obs.gauge(
            "survivors", n_alive, level=self.keys.cw_seed.shape[-2] - 1
        )
        return True

    async def final_shares(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Re-serve the surviving leaves' count shares for leader-side
        reconstruction (ref: rpc.rs:65, collect.rs:993-1004; tree paths
        live with the leader in this design, see protocol/collect.py)."""
        return {"server_id": self.server_id, "shares": self._last_shares}

    # -- streaming ingest front door (ROADMAP "Streaming ingestion": the
    # online successor of the one-shot add_keys upload) ------------------

    def _ingest_pool(self, window: int) -> _WindowPool:  # fhh-race: atomic (create-or-get + bounded eviction in one event-loop slice; called from the unlocked ingest fast path and from locked verbs)
        """Create-or-get the pool for ``window``; live-window count is
        BOUNDED (``cfg.ingest_windows_retained``) so a runaway window id
        can never grow server memory — the refusal is loud, never a
        silent drop."""
        pool = self._ingest_pools.get(window)
        if pool is None:
            if len(self._ingest_pools) >= max(
                1, self.cfg.ingest_windows_retained
            ):
                # sealed EMPTY windows are fully consumed (window_load
                # skips them, so only loads drop pools): evict the
                # oldest such before refusing — a quiet stretch of idle
                # windows must not wedge the front door
                idle = [
                    w for w in sorted(self._ingest_pools)
                    if self._ingest_pools[w].sealed
                    and not self._ingest_pools[w].entries
                ]
                if idle:
                    del self._ingest_pools[idle[0]]
            if len(self._ingest_pools) >= max(
                1, self.cfg.ingest_windows_retained
            ):
                raise RuntimeError(
                    f"ingest window {window} would exceed the "
                    f"{self.cfg.ingest_windows_retained} live-window bound "
                    f"(live: {sorted(self._ingest_pools)})"
                )
            pool = self._ingest_pools[window] = _WindowPool(
                window, self._admission.window(window)
            )
        return pool

    async def submit_keys(self, req) -> dict:  # fhh-race: atomic (unlocked ingest fast path: never suspends, so admission+append is one event-loop slice; rides concurrently with a crawl HOLDING the verb lock — that concurrency is the front door's whole point)
        """Streaming key submission into the named window's pool —
        admission-controlled, append-only, idempotent per ``sub_id``.

        Dispatches WITHOUT the verb lock (like ``add_keys``: no awaits,
        so it is atomic on the event loop) — ingest rides concurrently
        with a crawl holding the lock, which is what lets a window
        accrue while the previous window's frozen snapshot is crawled.

        Req: ``{window, sub_id, client_id, keys: chunk}`` plus an
        optional ``mirror`` dict carrying the GATE server's verdict —
        the leader-side driver gets the admission decision from server 0
        and replays it onto server 1, so the two pools stay positionally
        identical (admission must never diverge between the servers).

        Verdicts: ``{"admitted": True, slot}``, ``{"admitted": False,
        "shed": True}`` (reservoir mode — final), or ``{"admitted":
        False, "overloaded": True, scope, retry_after_s}`` (retryable:
        the client's RetryPolicy backs off and re-attempts)."""
        if self.cfg.malicious:
            raise RuntimeError(
                "streaming ingest does not carry sketch material yet — "
                "malicious mode uses the batch add_keys path"
            )
        window = int(req["window"])
        sub_id = str(req["sub_id"])
        # fhh-lint: disable=chunked-device-readback (wire input: pickled host numpy, no device involved)
        chunk = tuple(np.asarray(a) for a in req["keys"])
        n_keys = int(chunk[0].shape[0])
        pool = self._ingest_pool(window)
        self.obs.count("pool_submits")
        prev = pool.verdicts.get(sub_id)
        if prev is not None:
            # at-least-once delivery made safe: a replayed submission
            # (reconnect replay under a new req_id, recovery journal
            # replay) answers its RECORDED verdict — the pool and the
            # reservoir RNG are untouched, so nothing double-admits
            self.obs.count("pool_dup_submits")
            return dict(prev, dup=True)
        if pool.sealed:
            raise RuntimeError(
                f"ingest window {window} is sealed — submit into a later "
                "window"
            )
        mirror = req.get("mirror")
        if mirror is not None:
            resp = pool.apply_mirror(
                sub_id, chunk, mirror, str(req.get("client_id", ""))
            )
        else:
            v = self._admission.admit(
                pool.wa, str(req.get("client_id", "")), n_keys
            )
            resp = pool.apply(sub_id, chunk, v)
        if resp.get("admitted"):
            self.obs.count("pool_admitted_keys", n_keys)
        elif resp.get("shed"):
            self.obs.count("pool_shed_keys", n_keys)
        else:
            self.obs.count("pool_rejected")
        return resp

    async def window_seal(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Freeze the named window at its boundary: no further
        submissions land in it (later ``submit_keys`` name later
        windows); returns the pool stats.  Idempotent — re-sealing a
        sealed window (recovery replays) returns the same stats."""
        w = int(req["window"])
        pool = self._ingest_pools.get(w)
        if pool is None:
            pool = self._ingest_pool(w)  # sealing an idle window is legal
        if not pool.sealed:
            pool.sealed = True
            self.obs.count("windows_sealed")
            obs.emit(
                "ingest.window_sealed",
                server=self.server_id,
                window=w,
                keys=pool.keys,
                subs=len(pool.entries),
                shed=pool.shed_keys,
            )
        return pool.stats()

    async def window_load(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Materialize a SEALED window's frozen pool as the crawl's key
        batch (the streaming twin of the ``add_keys`` upload): the crawl
        state resets to empty, ``keys_parts`` becomes the pool's
        admitted chunks in slot order, and the normal ``tree_init`` →
        level loop runs on it — while ``submit_keys`` keeps landing in
        later windows.  Ingest pools and checkpoint files are untouched;
        consumed EARLIER windows are dropped (bounded live windows)."""
        w = int(req["window"])
        pool = self._ingest_pools.get(w)
        if pool is None:
            raise RuntimeError(f"window_load: no ingest pool for window {w}")
        if not pool.sealed:
            raise RuntimeError(f"window_load: window {w} is not sealed")
        if not pool.entries:
            raise RuntimeError(f"window_load: window {w} admitted no keys")
        self.keys_parts = [IbDcfKeyBatch(*e) for e in pool.entries]
        self.keys = None
        self.alive_keys = None
        self.frontier = None
        self._children = None
        self._last_shares = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        for old in [k for k in self._ingest_pools if k < w]:
            del self._ingest_pools[old]
        obs.emit(
            "ingest.window_loaded",
            server=self.server_id,
            window=w,
            keys=pool.keys,
        )
        return {"window": w, "keys": pool.keys, "subs": len(pool.entries)}

    def _ingest_status(self) -> dict:
        """Front-door health for ``status``: per-window occupancy, the
        unsealed-queue depth, and the admit/shed/reject counters —
        enough for an operator (or a test) to see a stalled or shedding
        ingest plane without scraping logs."""
        pools = [self._ingest_pools[w] for w in sorted(self._ingest_pools)]
        unsealed = [p for p in pools if not p.sealed]
        return {
            "current_window": (
                unsealed[-1].window if unsealed
                else (pools[-1].window if pools else None)
            ),
            "queue_depth": sum(p.keys for p in unsealed),
            "admitted": sum(p.admitted_keys for p in pools),
            "shed": sum(p.shed_keys for p in pools),
            "rejected": sum(p.rejected for p in pools),
            "windows": {
                str(p.window): {
                    "keys": p.keys,
                    "subs": len(p.entries),
                    "sealed": p.sealed,
                }
                for p in pools
            },
        }

    # -- resilience verbs (no reference analogue: the reference's only
    # recovery verb is reset, server.rs:64-69) ---------------------------

    async def status(self, _req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Cheap probe for the supervising leader: the boot id tells a
        reconnecting leader whether this is the same process (replay is
        safe) or a restart (state is gone — restore path), and the dedup
        counter lets recovery tests assert no verb double-applied."""
        return {
            "boot_id": self._boot_id,
            "has_keys": self.keys is not None or bool(self.keys_parts),
            "has_frontier": self.frontier is not None,
            "dedup_hits": int(self.obs.counter_value("dedup_hits")),
            "plane_resets": int(self.obs.counter_value("plane_resets")),
            # numerically-ordered checkpoint levels on disk — the
            # supervisor's "latest checkpoint" source of truth (string
            # sorts would order l9 after l10 from level 10 on)
            "ckpt_levels": self._ckpt_levels(),
            # streaming front-door health (pool occupancy per window,
            # unsealed queue depth, admit/shed/reject counters)
            "ingest": self._ingest_status(),
            # multi-chip mesh health (None on a single-device server):
            # device/shard counts, per-shard client occupancy, and the
            # reduction/recovery instruments the run report rolls up
            "mesh": self._mesh_status(),
        }

    def _mesh_status(self) -> dict | None:
        if self._mesh is None:
            return None
        return {
            "data_devices": self._mesh.n_devices,
            "data_shards": self._mesh.shards,
            "shard_clients": self._mesh.occupancy(),
            "ici_reduce_seconds": round(
                self.obs.timer_seconds("ici_reduce"), 6
            ),
            "reshards": int(self.obs.counter_value("mesh_reshards")),
            "faults": int(self.obs.counter_value("mesh_faults")),
            # row-sharded secure kernel stage (parallel/kernel_shard.py):
            # the last level's active shard count / the crawl's deepest
            # (None before any secure crawl).  The LAYOUT signal is the
            # gauge + the kernel_gathers counter (exactly the levels
            # that ran the degraded gather path — 0 of them on a fully
            # sharded crawl); kernel_gather_seconds is that gather's
            # DISPATCH time (the transfer itself completes lazily under
            # the level's later fetch), a supplement, not the detector
            "kernel_shards": self.obs.gauge_value("kernel_shards"),
            "kernel_shards_max": self.obs.gauge_max("kernel_shards"),
            "kernel_gathers": int(self.obs.counter_value("kernel_gathers")),
            "kernel_gather_seconds": round(
                self.obs.timer_seconds("kernel_gather"), 6
            ),
        }

    def _ckpt_levels(self) -> list:
        """Level stamps of this server's on-disk checkpoints, ascending
        NUMERICALLY (the same ordering :meth:`_ckpt_prune` keeps by)."""
        if self.ckpt_dir is None or not os.path.isdir(self.ckpt_dir):
            return []
        prefix = f"fhh_server{self.server_id}_l"
        levels = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith(prefix) and name.endswith(".npz"):
                try:
                    levels.append(int(name[len(prefix):-4]))
                except ValueError:
                    continue
        return sorted(levels)

    def _ckpt_path(self, level: int) -> str:
        # level-stamped: a torn checkpoint round (one server wrote level k,
        # the other died first) must leave BOTH servers able to restore the
        # same earlier level — the leader names the level, the file for it
        # either exists on both or the stash was never advanced
        return os.path.join(
            self.ckpt_dir, f"fhh_server{self.server_id}_l{level}.npz"
        )

    def _ckpt_prune(self, keep: int = 2) -> None:
        """Drop all but the newest ``keep`` checkpoint levels (the leader
        only ever restores its last acknowledged stash, which is at most
        one boundary behind the newest file)."""
        prefix = f"fhh_server{self.server_id}_l"
        found = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith(prefix) and name.endswith(".npz"):
                try:
                    found.append((int(name[len(prefix):-4]), name))
                except ValueError:
                    continue
        found.sort()
        # NB: found[:-keep] would be the EMPTY slice at keep=0 ([-0] == [0])
        doomed = found[: len(found) - keep] if keep else found
        for _, name in doomed:
            os.remove(os.path.join(self.ckpt_dir, name))

    def _ckpt_clear(self) -> None:
        if self.ckpt_dir is not None and os.path.isdir(self.ckpt_dir):
            self._ckpt_prune(keep=0)

    def _keys_fp(self) -> np.ndarray:
        """Cheap key identity for checkpoint/restore pairing: key_idx +
        root seeds.  Unlike the driver's every-plane fingerprint this is
        an OPERATIONAL check (did the leader re-upload the same batch it
        crawled with), not a cryptographic one — the leader is trusted
        with key halves by definition."""
        h = hashlib.sha256()
        # fhh-lint: disable=host-sync-in-hot-loop (checkpoint/restore identity check: once per checkpoint, not per level)
        h.update(np.ascontiguousarray(np.asarray(self.keys.key_idx)))
        # fhh-lint: disable=host-sync-in-hot-loop (as above)
        h.update(np.ascontiguousarray(np.asarray(self.keys.root_seed)))
        return np.frombuffer(h.digest(), np.uint8)

    async def tree_checkpoint(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Persist the crawl state AFTER the given level completed:
        frontier eval states + node liveness + client liveness + the
        state layout flag (planar Pallas vs interleaved XLA — a restore
        under the other engine converts).  Keys are NOT in the blob (the
        leader re-uploads them on a restart — they are the bulk of the
        bytes and the leader already holds them).  Atomic tmp+rename so
        a crash mid-write never corrupts the previous checkpoint.

        Malicious (sketch) mode checkpoints too: the blob carries the
        frontier-following sketch DPF states, the stored (yet-unopened)
        pair shares, the committed ratchet root, and the transcript
        digest — everything a re-run needs to replay each level's
        challenge bit-identically (see ``sketch.py``'s ratchet note).

        Streaming ingest pools ride EVERY checkpoint (``ing_*`` fields):
        a server killed mid-window restores its admitted pools — entry
        slots, recorded per-``sub_id`` verdicts, quota ledgers, and the
        reservoir sampler's RNG state — so recovery neither loses nor
        double-counts admitted keys and the shed stream resumes
        seed-identically.  A server with pools but no frontier (between
        windows) may checkpoint too: the blob is then ingest-only."""
        if self.ckpt_dir is None:
            raise RuntimeError(
                "tree_checkpoint: no checkpoint dir configured "
                "(start the server with FHH_CKPT_DIR set)"
            )
        ing_only = bool((req or {}).get("ingest_only"))
        if self.frontier is None and not self._ingest_pools:
            raise RuntimeError("tree_checkpoint before tree_init")
        if ing_only and not self._ingest_pools:
            raise RuntimeError("tree_checkpoint: no ingest pools to persist")
        level = int(req["level"])
        if self.frontier is not None and not ing_only:
            st = self.frontier.states
            # ONE stacked fetch for the whole blob (device_get of the
            # pytree), not one sync per plane — through a remote-chip
            # tunnel each fetch is a full round trip
            fetch = {
                "seed": st.seed,
                "bit": st.bit,
                "y_bit": st.y_bit,
                "alive": self.frontier.alive,
            }
            if self._sketch is not None:
                fetch["sk_state_seed"] = self._sketch_states.seed
                fetch["sk_state_t"] = self._sketch_states.t
                if self._sketch_pairs is not None:
                    fetch["sk_pairs"] = self._sketch_pairs[0]
            blob = jax.device_get(fetch)
            blob["alive_keys"] = np.asarray(self.alive_keys)
            blob["planar"] = np.bool_(self._planar())
            blob["keys_fp"] = self._keys_fp()
        else:
            blob = {"ing_only": np.bool_(True)}
        blob["level"] = np.int64(level)
        self._ingest_ckpt_fields(blob)
        if self._sketch is not None:
            blob["sk_pids"] = np.asarray(self._sketch_pids)
            blob["sk_depth"] = np.int64(self._sketch_depth)
            blob["sk_root"] = np.asarray(self._sketch_root, np.uint32)
            blob["sk_digest"] = np.frombuffer(
                self._ratchet_digest, np.uint8
            )
            if self._sketch_pairs is not None:
                blob["sk_pairs_depth"] = np.int64(self._sketch_pairs[1])
                blob["sk_pairs_last"] = np.bool_(
                    self._sketch_pairs_field is F255
                )
        path = self._ckpt_path(level)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **blob)
        os.replace(tmp, path)
        self._ckpt_prune()
        self.obs.count("checkpoint_writes", level=level)
        obs.emit(
            "resilience.server_checkpoint",
            server=self.server_id,
            level=level,
            path=path,
        )
        return {"level": level}

    # verdict codes in the checkpoint blob: slot >= 0, -1 = appended in
    # arrival order (no slot), -2 = reservoir-shed
    _ING_APPEND, _ING_SHED = -1, -2

    def _ingest_ckpt_fields(self, blob: dict) -> None:
        """Flatten every live ingest pool into ``ing_*`` npz fields:
        per window, the meta/counters row, the per-``sub_id`` verdict
        table, the entry slot table (per-leaf concatenation + lengths),
        the quota ledger, and the reservoir RNG state when the shed
        sampler engaged."""
        ws = sorted(self._ingest_pools)
        if not ws:
            return
        blob["ing_windows"] = np.asarray(ws, np.int64)
        for i, w in enumerate(ws):
            p = self._ingest_pools[w]
            blob[f"ing{i}_meta"] = np.array(
                [w, int(p.sealed), p.keys, p.admitted_keys, p.shed_keys,
                 p.rejected, len(p.entries), p.wa.subs, p.wa.keys,
                 -1 if p.wa.sub_keys is None else p.wa.sub_keys,
                 p.wa.pending_draws],
                np.int64,
            )
            sub_ids, codes = [], []
            for sid, resp in p.verdicts.items():
                sub_ids.append(sid)
                if resp.get("shed"):
                    codes.append(self._ING_SHED)
                elif resp.get("slot") is None:
                    codes.append(self._ING_APPEND)
                else:
                    codes.append(int(resp["slot"]))
            blob[f"ing{i}_sub_ids"] = np.array(sub_ids, dtype=str)
            blob[f"ing{i}_sub_codes"] = np.array(codes, np.int64)
            blob[f"ing{i}_lens"] = np.array(
                [int(e[0].shape[0]) for e in p.entries], np.int64
            )
            n_leaf = len(p.entries[0]) if p.entries else 0
            blob[f"ing{i}_nleaf"] = np.int64(n_leaf)
            for j in range(n_leaf):
                # entries are host arrays already (submit_keys converts)
                blob[f"ing{i}_leaf{j}"] = np.concatenate(
                    [e[j] for e in p.entries]
                )
            blob[f"ing{i}_clients"] = np.array(
                list(p.wa.client_keys.keys()), dtype=str
            )
            blob[f"ing{i}_client_keys"] = np.array(
                list(p.wa.client_keys.values()), np.int64
            )
            if p.wa.reservoir is not None:
                blob[f"ing{i}_res"] = p.wa.reservoir.state()

    def _ingest_validate(self, z: dict, path: str) -> list | None:
        """Validate-before-mutate for the ``ing_*`` fields: parse every
        window's record fully (shapes cross-checked) BEFORE any pool is
        touched; a torn tail refuses loudly with live state intact.
        Returns the parsed per-window records, or None when the blob
        carries no ingest fields (a pre-streaming checkpoint)."""
        if "ing_windows" not in z:
            return None
        parsed = []
        # fhh-lint: disable=host-sync-in-hot-loop (checkpoint blob: host npz entries)
        ws = np.asarray(z["ing_windows"], np.int64)  # checkpoint blob: host
        for i, w in enumerate(ws):
            req_keys = {f"ing{i}_meta", f"ing{i}_sub_ids", f"ing{i}_sub_codes",
                        f"ing{i}_lens", f"ing{i}_nleaf"}
            missing = req_keys - set(z)
            if missing:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is missing ingest "
                    f"fields {sorted(missing)} (truncated write?)"
                )
            meta = np.array(z[f"ing{i}_meta"], np.int64)
            if meta.shape != (11,) or int(meta[0]) != int(w):
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} has a malformed "
                    f"ingest meta row for window {int(w)}"
                )
            lens = np.array(z[f"ing{i}_lens"], np.int64)
            n_leaf = int(z[f"ing{i}_nleaf"])
            if lens.shape[0] != int(meta[6]):
                raise RuntimeError(
                    f"tree_restore: ingest window {int(w)} entry table is "
                    f"torn ({lens.shape[0]} lengths vs {int(meta[6])} slots)"
                )
            leaves = []
            for j in range(n_leaf):
                key = f"ing{i}_leaf{j}"
                if key not in z:
                    raise RuntimeError(
                        f"tree_restore: ingest window {int(w)} is missing "
                        f"leaf {j} (truncated write?)"
                    )
                leaf = z[key]  # npz entries are host ndarrays
                if leaf.shape[0] != int(lens.sum()):
                    raise RuntimeError(
                        f"tree_restore: ingest window {int(w)} leaf {j} "
                        f"covers {leaf.shape[0]} keys, lengths sum to "
                        f"{int(lens.sum())}"
                    )
                leaves.append(leaf)
            sub_ids = z[f"ing{i}_sub_ids"]
            codes = np.array(z[f"ing{i}_sub_codes"], np.int64)
            if sub_ids.shape[0] != codes.shape[0]:
                raise RuntimeError(
                    f"tree_restore: ingest window {int(w)} verdict table "
                    "is torn"
                )
            parsed.append({
                "meta": meta,
                "lens": lens,
                "leaves": leaves,
                "sub_ids": sub_ids,
                "codes": codes,
                "clients": np.array(z.get(f"ing{i}_clients", [])),
                "client_keys": np.array(
                    z.get(f"ing{i}_client_keys", []), np.int64
                ),
                "res": (
                    np.array(z[f"ing{i}_res"], np.uint64)
                    if f"ing{i}_res" in z
                    else None
                ),
            })
        return parsed

    def _ingest_restore_apply(self, parsed: list) -> None:
        """Rebuild the ingest pools from validated records (the mutation
        half of the restore contract)."""
        from ..native import Reservoir

        self._ingest_pools.clear()
        for rec in parsed:
            meta = rec["meta"]
            w = int(meta[0])
            wa = self._admission.window(w)
            pool = _WindowPool(w, wa)
            pool.sealed = bool(meta[1])
            pool.keys = int(meta[2])
            pool.admitted_keys = int(meta[3])
            pool.shed_keys = int(meta[4])
            pool.rejected = int(meta[5])
            wa.subs = int(meta[7])
            wa.keys = int(meta[8])
            wa.sub_keys = None if int(meta[9]) < 0 else int(meta[9])
            wa.pending_draws = int(meta[10])
            bounds = np.concatenate([[0], np.cumsum(rec["lens"])])
            pool.entries = [
                tuple(
                    leaf[bounds[e]:bounds[e + 1]] for leaf in rec["leaves"]
                )
                for e in range(len(rec["lens"]))
            ]
            for sid, code in zip(rec["sub_ids"], rec["codes"]):
                code = int(code)
                if code == self._ING_SHED:
                    resp = {"admitted": False, "shed": True, "window": w}
                elif code == self._ING_APPEND:
                    resp = {"admitted": True, "slot": None, "window": w}
                else:
                    resp = {"admitted": True, "slot": code, "window": w}
                pool.verdicts[str(sid)] = resp
            wa.client_keys = {
                str(c): int(n)
                for c, n in zip(rec["clients"], rec["client_keys"])
            }
            if rec["res"] is not None:
                wa.reservoir = Reservoir.from_state(rec["res"])
            self._ingest_pools[w] = pool

    async def tree_restore(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Reload the :meth:`tree_checkpoint` for the level the leader
        names; returns the completed level so the leader re-runs from
        ``level + 1``.  Requires keys: either still held (transient
        fault, same process) or re-uploaded via ``add_keys`` after a
        restart — and refuses a blob written under a different key
        batch.

        Every validation runs BEFORE any state mutates: a mismatched
        fingerprint, a truncated/corrupt npz, or a blob from a deeper
        level than this key batch's tree must fail loudly and leave the
        server's live state exactly as it was.

        Streaming ingest pools restore alongside (``ing_*`` fields, same
        validate-before-mutate contract); an ingest-ONLY blob (written
        between windows, no frontier) restores just the pools and leaves
        the crawl state empty — ``window_load`` rebuilds it."""
        if self.ckpt_dir is None:
            raise RuntimeError("tree_restore: no checkpoint dir configured")
        want_level = int(req["level"])
        path = self._ckpt_path(want_level)
        if not os.path.exists(path):
            raise RuntimeError(f"tree_restore: no checkpoint at {path}")
        try:
            with np.load(path) as npz:
                z = {k: npz[k] for k in npz.files}
        # np.load surfaces torn/partial writes as BadZipFile/ValueError/
        # EOFError depending on where the file was cut; all of them mean
        # the same thing at this boundary
        except Exception as e:  # fhh-lint: disable=broad-except (corrupt-blob classification: every load failure maps to the same loud refusal; state is untouched)
            raise RuntimeError(
                f"tree_restore: corrupt or truncated checkpoint at {path} "
                f"({type(e).__name__}: {e})"
            ) from e
        if "ing_only" in z and bool(z["ing_only"]):
            # ingest-only blob: pools back, crawl state untouched-empty.
            # No key requirement — the keys ARE the pools.
            if int(z.get("level", want_level)) != want_level:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is stamped level "
                    f"{want_level} but records level {int(z['level'])} "
                    "(renamed or tampered file)"
                )
            parsed = self._ingest_validate(z, path)
            if parsed is None:
                raise RuntimeError(
                    f"tree_restore: ingest-only checkpoint at {path} "
                    "carries no ingest pools (truncated write?)"
                )
            self._ingest_restore_apply(parsed)
            self.obs.count("checkpoint_restores", level=want_level)
            obs.emit(
                "resilience.server_restore",
                server=self.server_id,
                level=want_level,
                ingest_only=True,
            )
            return {"level": want_level}
        if self.keys is None:
            if not self.keys_parts:
                raise RuntimeError("tree_restore before add_keys")
            self._concat_keys()
        required = {"seed", "bit", "y_bit", "alive", "alive_keys", "level",
                    "planar", "keys_fp"}
        missing = required - set(z)
        if missing:
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} is missing fields "
                f"{sorted(missing)} (truncated write?)"
            )
        if not np.array_equal(z["keys_fp"], self._keys_fp()):
            raise RuntimeError(
                "tree_restore: checkpoint was written under a different "
                "key batch — re-upload the original keys"
            )
        level = int(z["level"])
        L = self.keys.cw_seed.shape[-2]
        if level != want_level:
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} is stamped level "
                f"{want_level} but records level {level} (renamed or "
                "tampered file)"
            )
        if level >= L - 1:
            raise RuntimeError(
                f"tree_restore: checkpoint level {level} is deeper than "
                f"this key batch's tree (data_len={L}) — wrong collection"
            )
        n = self.keys.cw_seed.shape[0]
        # fhh-lint: disable=host-sync-in-hot-loop (restore path: host npz entry, once per recovery)
        alive_keys = np.asarray(z["alive_keys"])
        if alive_keys.shape[0] != n:
            raise RuntimeError(
                "tree_restore: checkpoint client count != key batch"
            )
        has_sketch = bool(self._sketch_parts) or self._sketch is not None
        if has_sketch != ("sk_root" in z):
            raise RuntimeError(
                "tree_restore: sketch material mismatch — the checkpoint "
                + ("lacks" if has_sketch else "carries")
                + " sketch state relative to the uploaded keys"
            )
        if has_sketch:
            # the validate-before-mutate contract covers the sketch
            # fields too: a blob with sk_root but a torn/tampered tail
            # must refuse here, not KeyError after the frontier mutated
            sk_req = {"sk_state_seed", "sk_state_t", "sk_pids", "sk_depth",
                      "sk_digest"}
            if "sk_pairs" in z:
                sk_req |= {"sk_pairs_depth", "sk_pairs_last"}
            sk_missing = sk_req - set(z)
            if sk_missing:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is missing sketch "
                    f"fields {sorted(sk_missing)} (truncated write?)"
                )
        # ingest pools validate with everything else (a torn ing_* tail
        # refuses before ANY state mutates); None = pre-streaming blob
        parsed_ing = self._ingest_validate(z, path)
        # -- all checks passed: mutate ------------------------------------
        states = EvalState(
            seed=jax.device_put(z["seed"]),
            bit=jax.device_put(z["bit"]),
            y_bit=jax.device_put(z["y_bit"]),
        )
        saved_planar, planar = bool(z["planar"]), self._planar()
        if saved_planar != planar:
            states = (
                collect.to_interleaved(states)
                if saved_planar
                else collect.to_planar(states)
            )
        self.alive_keys = alive_keys
        self.frontier = collect.Frontier(
            states=states, alive=jax.device_put(z["alive"])
        )
        if self._mesh is not None:
            # re-shard from the host-side blob: the frontier lands
            # client-axis-sharded across whatever local devices are
            # live — this is the device-loss recovery primitive (a lost
            # device is re-covered by re-placement, not a server restart)
            self.frontier = self._mesh.shard_frontier(self.frontier)
        self._children = None
        self._last_shares = None
        self._shard_children.clear()
        self._shard_last.clear()
        self._shard_level = None
        self._expand_ready.clear()
        if has_sketch:
            if self._sketch is None:
                self._concat_sketch()
            self._sketch_states = dpf.DpfEvalState(
                seed=jax.device_put(z["sk_state_seed"]),
                t=jax.device_put(z["sk_state_t"]),
            )
            # fhh-lint: disable=host-sync-in-hot-loop (restore path: host npz entries, once per recovery)
            self._sketch_pids = np.asarray(z["sk_pids"])
            self._sketch_depth = int(z["sk_depth"])
            # fhh-lint: disable=host-sync-in-hot-loop (as above)
            self._sketch_root = np.asarray(z["sk_root"], np.uint32).copy()
            # fhh-lint: disable=host-sync-in-hot-loop (as above)
            self._ratchet_digest = np.asarray(
                z["sk_digest"], np.uint8
            ).tobytes()
            if "sk_pairs" in z:
                self._sketch_pairs = (
                    jax.device_put(z["sk_pairs"]), int(z["sk_pairs_depth"])
                )
                self._sketch_pairs_field = (
                    F255 if bool(z["sk_pairs_last"]) else FE62
                )
            else:
                self._sketch_pairs = None
                self._sketch_pairs_field = None
        if parsed_ing is not None:
            self._ingest_restore_apply(parsed_ing)
        self.obs.count("checkpoint_restores", level=level)
        obs.emit(
            "resilience.server_restore", server=self.server_id, level=level
        )
        return {"level": level}

    async def plane_reset(self, _req) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Re-establish the server↔server data plane after a peer loss.

        Only the DIALER (server 0) acts: it drops the dead transport and
        redials under the shared backoff policy; the listener's side is
        re-accepted automatically (``_on_peer`` on its still-bound
        listener).  Both sides re-run ``_plane_handshake`` on the fresh
        connection — new sketch-challenge coin flip, new base-OT/IKNP
        sessions — so the secure exchange is fully re-keyed."""
        if self.server_id != 0:
            return True  # listener: re-accept + re-handshake is automatic
        if self._peer_writer is not None and not self._peer_writer.is_closing():
            self._peer_writer.close()
        await self._dial_peer()
        self.obs.count("plane_resets")
        obs.emit("resilience.plane_reset", server=self.server_id)
        return True

    async def plane_break(self, _req) -> bool:
        """Forcibly close this server's end of the peer data plane WITHOUT
        re-establishing it — the pipelined leader's quiesce primitive.  A
        faulted pipeline can leave a verb on EITHER server blocked in a
        ``_swap`` recv while holding the verb lock (its span reached only
        one server, so the peer's matching frame never comes); this verb
        dispatches OUTSIDE the verb lock (see ``_dispatch``) precisely so
        it can break that wedge: the close fails the blocked read loudly,
        the wedged verb errors out and releases the lock, and the
        leader's subsequent (locked) ``plane_reset`` re-keys the plane
        cleanly."""
        w = self._peer_writer
        if w is not None and not w.is_closing():
            w.close()
        self.obs.count("plane_breaks")
        obs.emit("resilience.plane_break", server=self.server_id)
        return True

    async def warmup(self, req) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the verb lock; sanitizer-validated)
        """Pre-compile the per-``f_bucket`` crawl programs so bucket
        recompiles stop billing into measured (or production) crawl time:
        for every requested bucket (and every shard-span size it implies
        under ``cfg.crawl_shard_nodes``), run the expand stage and — in
        secure mode — the whole 2PC kernel chain against a THROWAWAY
        in-process OT session with the real key batch's shapes.  Touches
        no protocol state: the live OT sessions, frontier, and data plane
        are never involved, so warmup can run any time after ``add_keys``
        (the leader calls it right after ``tree_init``).  Returns the
        number of (bucket, span) shapes warmed."""
        if self.keys is None:
            if not self.keys_parts:
                raise RuntimeError("warmup before add_keys")
            self._concat_keys()
        buckets = sorted(
            {int(b) for b in (req or {}).get("f_buckets", []) if int(b) > 0}
        )
        # the requesting leader may name the equality-test path (its own
        # config's, possibly overriding this server's — the same per-req
        # override the crawl verbs honor) and ask for span-sized shapes
        # (a leader that will crawl with secure_whole_level=False)
        ot_path = (req or {}).get("ot_path") or self.cfg.ot_path
        want_spans = bool((req or {}).get("secure_spans"))
        # the leader names its shard layout so a config skew (a leader
        # that believes this server runs k-way sharded when it does not,
        # or vice versa) surfaces at warmup time instead of as mystery
        # recompiles on the measured clock; the server's own mesh is
        # authoritative — warmup always compiles the programs the LIVE
        # crawl will dispatch
        want_devices = (req or {}).get("data_shards")
        have_shards = 1 if self._mesh is None else self._mesh.shards
        if want_devices is not None and int(want_devices) > 0:
            # the leader names a DEVICE budget; resolve it exactly like
            # this server resolved its own (visible-device cap, then
            # the largest divisor of the bound client batch) so
            # identically-configured pairs never warn — only real
            # config skew does
            want_shards = smesh._largest_divisor_leq(
                self.keys.cw_seed.shape[0],
                smesh.resolve_data_devices(int(want_devices)),
            )
            if want_shards != have_shards:
                obs.emit(
                    "warmup.shard_mismatch",
                    severity="warn",
                    server=self.server_id,
                    leader_data_shards=want_shards,
                    server_data_shards=have_shards,
                )
        L = self.keys.cw_seed.shape[-2]
        shapes = 0
        with self.obs.span("warmup"):
            for b in buckets:
                if (
                    self.cfg.secure_exchange
                    and self.cfg.secure_whole_level
                    and not want_spans
                ):
                    # whole-level secure crawls never shard the GC/OT
                    # batch — warming span-sized programs would compile
                    # shapes no crawl dispatches (and break the
                    # warmed-crawl-compiles-nothing contract's economy)
                    sizes = set()
                else:
                    sizes = {
                        hi - lo
                        for lo, hi in collect.shard_spans(
                            b, self.cfg.crawl_shard_nodes
                        )
                    }
                for fb in sorted(sizes | {b}):
                    self._warm_bucket(fb, L, ot_path)
                    shapes += 1
                    # yield between compiles: each can take seconds, and
                    # the control socket must keep answering keepalives
                    await asyncio.sleep(0)
        self.obs.count("warmup_shapes", shapes)
        return {"shapes": shapes}

    def _warm_bucket(self, fb: int, L: int, ot_path: str | None = None) -> None:
        """Compile (by running on throwaway inputs) every device program
        a crawl at frontier bucket ``fb`` will hit: expand with and
        without children, the trusted count reduction, and in secure
        mode the OT-extension + equality + b2a + share-sum chain for both
        FE62 (inner levels) and F255 (the leaf level).  Under the
        multi-chip mesh every stage warms with the SHARDED layout the
        live crawl dispatches (keys are already client-axis-sharded, the
        frontier pins interleaved, reductions go through the shard_map
        psum kernels) — jit executables key on input shardings, so
        warming unsharded twins would leave every live program cold."""
        mesh = self._mesh
        if mesh is not None:
            fr = mesh.shard_frontier(
                collect.tree_init(self.keys, fb, planar=False)
            )
        else:
            fr = collect.tree_init(self.keys, fb)
        d = self.keys.cw_seed.shape[1]
        lasts = (False, True) if L > 1 else (True,)
        for last in lasts:
            level = L - 1 if last else 0
            packed, _ = collect.expand_share_bits(
                self.keys, fr, level, want_children=not last,
                use_pallas=False if mesh is not None else None,
            )
            if self.cfg.secure_exchange:
                N = self.keys.cw_seed.shape[0]
                ks = (
                    mesh.kernel_bind(
                        fb * (1 << d) * N, 2 * d,
                        self.cfg.secure_kernel_shards,
                    )
                    if mesh is not None
                    else None
                )
                if ks is not None:
                    # the live crawl runs this shape ROW-SHARDED: warm
                    # the sharded flat/extension/kernel/open/psum chain
                    # (both roles, both garbling signs) — warming the
                    # gathered twins would leave every live program cold
                    secure.warm_level_kernels_sharded(
                        ks, packed, d, fb, N, F255 if last else FE62,
                        path=ot_path or self.cfg.ot_path,
                    )
                    continue
                secure.warm_level_kernels(
                    # same pre-kernel gather as the live expand stage
                    # (_do_expand) — warm and live must dispatch the
                    # same single-device 2PC programs
                    packed if mesh is None else mesh.gather(packed),
                    d, F255 if last else FE62,
                    path=ot_path or self.cfg.ot_path,
                    share_sums=mesh.node_share_sums if mesh is not None
                    else None,
                )
            else:
                masks = collect.pattern_masks(d)
                alive = (
                    self.alive_keys
                    if self.alive_keys is not None
                    else np.ones(self.keys.cw_seed.shape[0], bool)
                )
                if mesh is not None:
                    # peer rows arrive as host numpy on the live path
                    peer = np.asarray(packed)  # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (warmup only: deliberately mirrors the live wire round trip, off the measured clock)
                    jax.block_until_ready(
                        mesh.counts_by_pattern(
                            packed, peer, masks, alive, fr.alive
                        )
                    )
                else:
                    jax.block_until_ready(
                        collect.counts_by_pattern(
                            packed, packed, masks, alive, fr.alive
                        )
                    )

    # -- wiring ----------------------------------------------------------

    _VERBS = (
        "reset",
        "add_keys",
        "tree_init",
        "tree_crawl",
        "tree_crawl_last",
        "tree_prune",
        "tree_prune_last",
        "final_shares",
        "sketch_verify",  # the TreeSketchFrontier* verbs' live successor
        # streaming ingest front door (ROADMAP "Streaming ingestion")
        "submit_keys",
        "window_seal",
        "window_load",
        # resilience verbs (no reference analogue)
        "status",
        "tree_checkpoint",
        "tree_restore",
        "plane_reset",
        "plane_break",  # pipelined-crawl quiesce (unlocked dispatch)
        "warmup",  # per-f_bucket compile warmup (no protocol state)
    )

    def _bind_session(self, req) -> _Session | None:  # fhh-race: atomic (serve-loop session table: create-or-attach + eviction never suspends; all connections share one event loop)
        """Create-or-attach the leader session named in a ``__hello__``.
        Sessions are bounded (oldest-idle evicted) so reconnecting leaders
        with fresh session ids cannot grow server memory without bound."""
        sid = (req or {}).get("session")
        if sid is None:
            return None
        sess = self._sessions.get(sid)
        if sess is None:
            while len(self._sessions) >= _SESSION_CAP:
                oldest = min(
                    self._sessions, key=lambda k: self._sessions[k].last_seen
                )
                del self._sessions[oldest]
            sess = self._sessions[sid] = _Session()
        epoch = int((req or {}).get("epoch", 0))
        if epoch > 1:  # epoch 1 is the first connect, not a recovery
            self.obs.count("session_reconnects")
            obs.emit(
                "resilience.session_reconnect",
                server=self.server_id,
                epoch=epoch,
            )
        sess.epoch = epoch
        sess.last_seen = time.monotonic()
        return sess

    async def _dispatch(self, sess: _Session | None, req_id, verb, req):
        """Run one verb AT MOST ONCE per (session, req_id): replays of a
        finished verb answer from the bounded response cache; replays of a
        verb still executing await the same execution.  Errors are
        responses too — a deterministic rejection must replay as the same
        rejection, not as a second execution attempt."""
        self.obs.count("verb_requests")  # denominator of the dedup rate
        if sess is not None:
            sess.last_seen = time.monotonic()
            if req_id in sess.cache:
                self.obs.count("dedup_hits")
                obs.emit(
                    "resilience.replay",
                    severity="debug",
                    server=self.server_id,
                    verb=verb,
                    req_id=req_id,
                )
                sess.cache.move_to_end(req_id)
                return sess.cache[req_id]
            live = sess.inflight.get(req_id)
            if live is not None:
                self.obs.count("dedup_hits")
                return await asyncio.shield(live)
            done = sess.inflight[req_id] = (
                asyncio.get_event_loop().create_future()
            )
        try:
            if verb in ("add_keys", "submit_keys", "plane_break"):
                # add_keys/submit_keys: append-only, no awaits -> atomic;
                # submit_keys MUST bypass the lock so ingest keeps
                # flowing while a windowed crawl holds it (that
                # concurrency is the whole point of the front door).
                # plane_break MUST bypass it too: it exists to break a
                # verb wedged on the data plane while HOLDING the lock
                # (pipelined quiesce) — behind the lock it could never
                # run.
                with guards.unguarded(
                    "unlocked fast-path verb: event-loop-atomic by the "
                    "fhh-race atomic contracts on add_keys/submit_keys"
                ):
                    resp = await getattr(self, verb)(req)
            else:
                # frame-arrival expand stage: overlap a sharded crawl's
                # device work with the span currently holding the lock
                with guards.unguarded(
                    "frame-arrival prefetch: event-loop-atomic by the "
                    "fhh-race atomic contract on _maybe_pre_expand"
                ):
                    self._maybe_pre_expand(verb, req)
                async with self._verb_lock:
                    resp = await getattr(self, verb)(req)
        # fhh-lint: disable=broad-except (RPC boundary: EVERY failure
        # mode must surface to the caller as an error response — a
        # narrowed list would hang the leader on the first unlisted one)
        except Exception as e:
            obs.emit(
                "verb.error", severity="warn", server=self.server_id,
                verb=verb, error=f"{type(e).__name__}: {e}",
            )
            resp = {"__error__": f"{type(e).__name__}: {e}"}
        except asyncio.CancelledError:
            # drain-path cancellation: release any replay waiting on this
            # execution, then propagate
            if sess is not None:
                sess.inflight.pop(req_id, None)
                if not done.done():
                    done.cancel()
            raise
        if sess is not None:
            sess.put(req_id, resp)
            sess.inflight.pop(req_id, None)
            if not done.done():
                done.set_result(resp)
        return resp

    async def _handle_leader(self, reader, writer):
        """Control-plane serve loop with request ids and concurrent
        handling (the reference's tarpc ids + buffer_unordered(100),
        server.rs:359-376): each frame is (req_id, verb, payload) and every
        request runs as its own task, so many in-flight add_keys batches
        deserialize and append while others are still on the wire.  Verbs
        that touch the data plane or mutate protocol state serialize on
        ``_verb_lock``; responses carry the id so completion order is
        free.

        A ``__hello__`` frame (sent by the reconnecting client on every
        connect) binds this connection to a leader session; all later
        verbs on the connection go through that session's replay dedup
        (:meth:`_dispatch`).  A client that never says hello gets the
        legacy at-most-once-per-connection behavior."""
        write_lock = asyncio.Lock()
        sess: _Session | None = None
        self._ctl_writers.add(writer)

        async def respond(req_id, resp):
            try:
                async with write_lock:
                    await _send(
                        writer, (req_id, resp),
                        count=lambda n: self.obs.count("control_bytes_sent", n),
                    )
            except (ConnectionResetError, BrokenPipeError):
                pass  # leader gone; the work itself must still have finished
            except RuntimeError:
                # asyncio raises RuntimeError for writes on a closing
                # transport — swallow only that case; anything else would
                # silently strand the leader awaiting this req_id
                if not writer.is_closing():
                    raise

        async def handle(req_id, verb, req):
            await respond(req_id, await self._dispatch(sess, req_id, verb, req))

        tasks = set()
        try:
            while True:
                req_id, verb, req = await _recv(
                    reader,
                    count=lambda n: self.obs.count("control_bytes_recv", n),
                )
                if verb == "__hello__":
                    with guards.unguarded(
                        "serve-loop session bind: event-loop-atomic by "
                        "the fhh-race atomic contract on _bind_session"
                    ):
                        sess = self._bind_session(req)
                    await respond(
                        req_id,
                        {"boot_id": self._boot_id, "server_id": self.server_id},
                    )
                    continue
                if verb not in self._VERBS:
                    raise ValueError(f"unknown verb {verb!r}")
                t = asyncio.create_task(handle(req_id, verb, req))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            # Drain, don't cancel: a verb may be mid-_swap on the PERSISTENT
            # peer data plane — cancelling between its send and recv would
            # leave the peer's frame unread and desynchronize every later
            # exchange (the old sequential loop always finished the verb in
            # flight; concurrent handling must keep that guarantee), and a
            # verb may legitimately run for minutes (first-call device
            # compiles).  Cancel only on the one condition draining cannot
            # cover: the PEER connection itself is gone — then the data
            # plane is already lost and cancelling costs nothing.
            # A silently-dead peer (partition/power loss, no FIN/RST) is
            # surfaced by the data-plane socket's TCP keepalive (_keepalive,
            # ~2 min): the blocked _swap recv then raises, the verb task
            # finishes on its own, and is_closing() turns true — so this
            # loop needs no wall-clock guess that could misfire on a LIVE
            # peer running legitimately long verbs.
            pending = set(tasks)
            deadline = time.monotonic() + 1800  # generous overall backstop
            while pending:
                done, pending = await asyncio.wait(pending, timeout=30)
                if done:  # progress: push the backstop out again
                    deadline = time.monotonic() + 1800
                if pending and (
                    self._peer_writer is None
                    or self._peer_writer.is_closing()
                    or time.monotonic() > deadline
                    # the backstop covers what keepalive cannot: a verb
                    # blocked while the peer data plane stays OPEN (e.g. a
                    # desynchronized _swap after a peer-side verb error) —
                    # 30 min without a single task completing is not a
                    # legitimate long verb, it is a wedged handler
                ):
                    for t in pending:
                        t.cancel()
                    break
            writer.close()
            self._ctl_writers.discard(writer)

    async def aclose(self) -> None:
        """Tear the whole server down — listeners, leader connections,
        peer data plane.  In-memory protocol state is NOT cleared: this
        is process death as far as peers can observe (the chaos tests'
        kill primitive; a restart is a fresh :class:`CollectorServer`)."""
        for srv in (getattr(self, "_rpc_srv", None), getattr(self, "_peer_srv", None)):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        for w in list(self._ctl_writers):
            if not w.is_closing():
                w.close()
        self._ctl_writers.clear()
        if self._peer_writer is not None and not self._peer_writer.is_closing():
            self._peer_writer.close()

    @staticmethod
    def _keepalive(writer: asyncio.StreamWriter) -> None:
        """Aggressive-ish TCP keepalive on the persistent data plane so a
        SILENTLY dead peer (partition, power loss — no FIN/RST) surfaces as
        a connection error within ~2 minutes instead of hanging a blocked
        ``_swap`` recv forever (kernels default to ~2 hours)."""
        import socket

        sock = writer.get_extra_info("socket")
        if sock is None:
            return
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for opt, val in (
            ("TCP_KEEPIDLE", 60), ("TCP_KEEPINTVL", 20), ("TCP_KEEPCNT", 3)
        ):
            if hasattr(socket, opt):
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)

    async def _dial_peer(self) -> None:
        """Dial the peer data plane under the shared backoff policy (the
        reference's connect_with_retries_tcp, server.rs:235, upgraded from
        fixed sleeps to exponential backoff + full jitter) and run the
        session handshake on the fresh connection."""
        peer_host, peer_port = self._peer_addr

        async def dial():
            return await asyncio.wait_for(
                asyncio.open_connection(peer_host, peer_port),
                respolicy.DIAL_TIMEOUT_S,
            )

        try:
            r, w = await respolicy.retry_async(
                dial,
                respolicy.DIAL_POLICY,
                what=f"peer data plane {peer_host}:{peer_port}",
            )
        except respolicy.TRANSIENT_ERRORS as e:
            raise ConnectionError(
                f"peer data-plane unreachable at {peer_host}:{peer_port}: {e!r}"
            ) from e
        self._peer_reader, self._peer_writer = r, w
        self._keepalive(w)
        await self._plane_handshake()

    async def start(self, host: str, port: int, peer_host: str, peer_port: int):
        """Bring up the data plane FIRST (like the reference: GC mesh before
        the RPC listener, server.rs:344-354), run the base-OT handshake if
        the exchange is secure, then serve the leader."""
        self._peer_addr = (peer_host, peer_port)
        with self.obs.span("setup"):
            if self.server_id == 1:
                srv = await asyncio.start_server(self._on_peer, host, peer_port)
                self._peer_ready = asyncio.Event()
                self._peer_srv = srv
                # fhh-lint: disable=unbounded-await (startup barrier: a
                # listening server legitimately waits as long as it takes
                # its peer to come up; operators bound this externally)
                await self._peer_ready.wait()
            else:
                await self._dial_peer()
            self._rpc_srv = await asyncio.start_server(
                self._handle_leader, host, port
            )
        return self._rpc_srv

    async def _on_peer(self, reader, writer):
        self._peer_reader, self._peer_writer = reader, writer
        self._keepalive(writer)
        await self._plane_handshake()
        self._peer_ready.set()

    async def _plane_handshake(self):
        """Session setup on the fresh peer connection: coin-flip a shared
        sketch-challenge seed (each side contributes 16 random bytes; the
        XOR is uniform if either is honest — and crucially NEVER a public
        constant: a client that can predict the challenge r can forge a
        passing sketch), then the base-OT setup when the exchange is
        secure."""
        mine = _secrets.token_bytes(16)
        theirs = await self._swap(mine)
        self._sketch_seed = np.frombuffer(
            bytes(a ^ b for a, b in zip(mine, theirs)), dtype="<u4"
        ).copy()
        await self._setup_secure()

    async def _setup_secure(self):
        """One-time base-OT setup seeding the IKNP extension (the ocelot
        session init of collect.rs:454-461 — ~128 host-side Chou-Orlandi
        OTs; all per-level OT volume then runs as device kernels).  TWO
        sessions, one per garbling direction, so the leader can alternate
        the garbler per level (the reference's ``gc_sender`` flip,
        rpc.rs:20-23, leader.rs:204-210) and garbling cost splits across
        the servers.  In session ``g`` server ``g`` is the OT-extension
        sender and plays base-OT *receiver* with its secret ``s`` — the
        standard IKNP role flip (ops/otext.py)."""
        if not self.cfg.secure_exchange:
            return
        for g in (0, 1):
            if self.server_id == g:  # extension sender <- base-OT receiver
                s_bits = otext.fresh_s_bits()
                a_msg = await self._dp_recv()
                br = baseot.BaseOtReceiver(s_bits)
                await self._dp_send(br.round1(a_msg))
                self._ot_snd = otext.OtExtSender(s_bits, br.seeds())
            else:  # extension receiver <- base-OT sender
                bs = baseot.BaseOtSender()
                await self._dp_send(bs.round1())
                r_msgs = await self._dp_recv()
                s0, s1 = bs.seeds([baseot.decompress(m) for m in r_msgs])
                self._ot_rcv = otext.OtExtReceiver(s0, s1)
        self._ot = (self._ot_snd, self._ot_rcv)  # marker: secure plane live
        self._sec_seed = np.frombuffer(
            _secrets.token_bytes(16), dtype="<u4"
        ).copy()


# ---------------------------------------------------------------------------
# Leader client
# ---------------------------------------------------------------------------


class ServerRestartedError(ConnectionError):
    """The reconnect handshake found a DIFFERENT server process (new boot
    id): in-memory protocol state is gone, so blind verb replay is not
    safe — the supervising leader must run the restore path (re-upload
    keys, ``tree_restore``) instead.  Subclasses ConnectionError so
    non-supervised callers still see it as a connection-shaped failure."""


class CollectorClient:
    """Leader-side RPC stub (the tarpc-generated client analogue), now
    RECONNECTING.

    The framing carries request ids, so any number of calls may be in
    flight on one connection; a reader task resolves futures by id
    (tarpc's pipelining model, leader.rs:340-364 drives 1000 in-flight
    addkey batches through it).

    Recovery semantics: the client owns a session id for its lifetime.
    Every (re)connect sends ``__hello__ {session, epoch}``; on transport
    loss mid-call, the call redials under ``dial_policy`` (one winner per
    epoch — concurrent failed calls piggyback on the same redial) and
    RESENDS its frame with the SAME req_id.  The server's per-session
    dedup cache answers replays idempotently, so a verb whose response
    was lost in flight is never double-applied.  Two ways out of the
    retry loop: the per-verb wall-clock budget (``VerbBudgets``)
    expires, or the hello discovers a new server boot id
    (:class:`ServerRestartedError` — replay would run against empty
    state)."""

    def __init__(
        self,
        host: str,
        port: int,
        reg: obsmetrics.Registry | None = None,
        *,
        dial_policy: respolicy.RetryPolicy | None = None,
        budgets: respolicy.VerbBudgets | None = None,
    ):
        self._host, self._port = host, port
        self._r = self._w = None
        self._send_lock = asyncio.Lock()
        self._conn_lock = asyncio.Lock()
        self._flush_task: asyncio.Task | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._reader_task: asyncio.Task | None = None
        self._dead: ConnectionError | None = None
        self.session_id = _secrets.token_hex(8)
        self.epoch = 0  # successful connects; >1 means we have reconnected
        self.boot_id: str | None = None  # server identity from last hello
        self.dial_policy = dial_policy or respolicy.DIAL_POLICY
        self.budgets = budgets or respolicy.VerbBudgets()
        # control-plane byte accounting lands on the leader process's
        # default registry unless the caller owns one
        self.obs = obsmetrics.default_registry() if reg is None else reg

    @classmethod
    async def connect(cls, host: str, port: int, **kw) -> "CollectorClient":
        c = cls(host, port, **kw)
        await c._ensure_connected(0)
        return c

    async def aclose(self) -> None:
        self._dead = ConnectionError("client closed")
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._w is not None and not self._w.is_closing():
            self._w.close()
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.set_exception(self._dead)
        self._fail_pending(self._dead)

    def _fail_pending(self, err: ConnectionError) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    async def _ensure_connected(self, seen_epoch: int) -> None:
        """(Re)dial unless someone already did since ``seen_epoch`` (the
        epoch the caller last observed).  All concurrently-failed calls
        funnel here; the first through the lock redials, the rest find a
        fresh epoch and just resend."""
        if self._dead is not None:
            raise self._dead
        async with self._conn_lock:
            if (
                self.epoch > seen_epoch
                and self._w is not None
                and not self._w.is_closing()
            ):
                return  # a concurrent caller already reconnected

            async def dial():
                return await asyncio.wait_for(
                    asyncio.open_connection(self._host, self._port),
                    respolicy.DIAL_TIMEOUT_S,
                )

            try:
                r, w = await respolicy.retry_async(
                    dial,
                    self.dial_policy,
                    what=f"dial {self._host}:{self._port}",
                )
            except respolicy.TRANSIENT_ERRORS as e:
                # NOT permanent: a later call (e.g. the supervisor's next
                # recovery wave) may find the server back up and redial
                err = ConnectionError(
                    f"server {self._host}:{self._port} unreachable: {e!r}"
                )
                self._fail_pending(err)
                raise err from e
            if self._reader_task is not None:
                self._reader_task.cancel()
            if self._w is not None and not self._w.is_closing():
                # close the superseded transport: after a peer FIN (or a
                # reader death that left TCP up, e.g. a corrupt frame)
                # the old fd would otherwise sit in CLOSE_WAIT — and the
                # server would keep a zombie handler bound to it — for
                # the life of the process, one leak per reconnect
                self._w.close()
            # any future still pending belongs to the OLD transport: its
            # response can never arrive — fail it so its owner replays on
            # the fresh epoch instead of waiting out its whole budget
            self._fail_pending(
                ConnectionError("transport replaced by reconnect")
            )
            # a coalesced drain still waiting on the old transport can
            # never finish — fail it (ConnectionError = transient, so its
            # waiters replay on the fresh epoch) and start clean
            if self._flush_task is not None and not self._flush_task.done():
                self._flush_task.set_exception(
                    ConnectionError("transport replaced by reconnect")
                )
            self._flush_task = None
            self._r, self._w = r, w
            self.epoch += 1
            self._reader_task = asyncio.ensure_future(self._read_loop(r))
            # session handshake: bind this connection to our session (the
            # server arms replay dedup) and learn the server's boot id
            self._next_id += 1
            hello = await self._roundtrip(
                self._next_id,
                "__hello__",
                {"session": self.session_id, "epoch": self.epoch},
                respolicy.Deadline(self.budgets.budget("__hello__")),
            )
            new_boot = hello.get("boot_id")
            old_boot, self.boot_id = self.boot_id, new_boot
            if self.epoch > 1:
                self.obs.count("reconnects")
                obs.emit(
                    "resilience.reconnect",
                    host=self._host,
                    port=self._port,
                    epoch=self.epoch,
                    restarted=bool(old_boot and old_boot != new_boot),
                )

    async def _roundtrip(self, req_id, verb, req, deadline: respolicy.Deadline):
        """One send + response wait on the CURRENT transport (no retry —
        :meth:`call` owns the retry loop, and owns the req_id: a REPLAY
        must reuse the original id or the server's dedup cache can never
        recognize it)."""
        if (
            self._w is None
            or self._w.is_closing()
            or self._reader_task is None
            or self._reader_task.done()
        ):
            # transport already known-dead: a write might still "succeed"
            # locally (FIN'd socket) and the dead reader would never
            # resolve the future — fail fast into the reconnect path
            # instead of waiting out the whole verb budget
            raise ConnectionError("transport down")
        fut = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        try:
            async with self._send_lock:
                await _send(
                    self._w, (req_id, verb, req or {}),
                    count=lambda n: self.obs.count("control_bytes_sent", n),
                    flush=False,
                )
            # coalesced drain: a burst of concurrent frames (the 256-deep
            # upload window, a pipelined level's span verbs) shares ONE
            # drain instead of one await per frame — backpressure is
            # still applied, once per burst
            await self._flush()
            return await deadline.wait_for(fut)
        finally:
            # send raised mid-write, the wait timed out, or the reader
            # failed the future: either way the response slot is dead —
            # drop it so _pending can't grow across failed calls
            self._pending.pop(req_id, None)

    async def _flush(self) -> None:
        """Shared, coalesced ``drain()``: every writer since the last
        flush awaits the SAME drain outcome (a future the drain helper
        resolves).  Shielded so one caller's cancellation (a verb
        deadline firing) cannot starve the other writers; a drain
        failure (dead transport) surfaces to every waiter as the same
        connection-shaped error the per-frame drain raised — and a
        reconnect fails the stale flush with ConnectionError (see
        ``_ensure_connected``), which the retry loop classifies as
        transient and replays through."""
        fut = self._flush_task
        if fut is None or fut.done():
            fut = self._flush_task = asyncio.get_event_loop().create_future()
            # no "never retrieved" GC noise if every waiter was cancelled
            fut.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None
            )

            async def do_drain(w=self._w, fut=fut):
                try:
                    await w.drain()
                except Exception as e:  # fhh-lint: disable=broad-except (outcome relay: every drain failure must reach the coalesced waiters, whatever its type)
                    if not fut.done():
                        fut.set_exception(e)
                else:
                    if not fut.done():
                        fut.set_result(None)

            asyncio.ensure_future(do_drain())
        await asyncio.shield(fut)

    async def _read_loop(self, reader):
        try:
            while True:
                req_id, resp = await _recv(
                    reader,
                    count=lambda n: self.obs.count("control_bytes_recv", n),
                )
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except Exception as e:  # reader death fails every in-flight caller
            err = ConnectionError(f"connection lost: {e!r}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            if not isinstance(e, respolicy.TRANSIENT_ERRORS):
                # anything else is a BUG in this client, not a transport
                # death.  Emit it NOW — nothing awaits the reader task, so
                # a bare re-raise would sit unretrieved until GC — then
                # re-raise for any future consumer of the task result.
                obs.emit(
                    "client.reader_error", severity="error",
                    error=f"{type(e).__name__}: {e}",
                )
                raise

    async def call(self, verb: str, req=None):
        """At-most-once verb call with transparent replay: transient
        transport failures redial and resend the SAME req_id (the server
        dedups); the per-verb wall-clock budget bounds the whole affair —
        every redial, every replay, and the server's execution."""
        if self._dead is not None:
            raise self._dead
        deadline = self.budgets.deadline(verb)
        first_boot = self.boot_id
        payload = req or {}
        self._next_id += 1
        req_id = self._next_id  # ONE id for the call's lifetime: replays
        resp = None             # reuse it so the server can dedup them
        while True:
            seen_epoch = self.epoch
            try:
                resp = await self._roundtrip(req_id, verb, payload, deadline)
                break
            except respolicy.TRANSIENT_ERRORS as e:
                if deadline.expired():
                    raise TimeoutError(
                        f"verb {verb!r} exceeded its "
                        f"{self.budgets.budget(verb):g}s budget "
                        f"(last error: {type(e).__name__}: {e})"
                    ) from e
                self.obs.count("call_retries")
                obs.emit(
                    "resilience.call_retry",
                    severity="debug",
                    verb=verb,
                    epoch=seen_epoch,
                    error=f"{type(e).__name__}: {e}",
                )
                await self._ensure_connected(seen_epoch)
                if first_boot is not None and self.boot_id != first_boot:
                    raise ServerRestartedError(
                        f"server {self._host}:{self._port} restarted while "
                        f"{verb!r} was in flight — state lost, replay unsafe"
                    ) from e
        if isinstance(resp, dict) and "__error__" in resp:
            raise RuntimeError(f"server error on {verb}: {resp['__error__']}")
        return resp

    def __getattr__(self, verb):
        if verb.startswith("_"):
            raise AttributeError(verb)

        async def _verb(req=None):
            return await self.call(verb, req)

        return _verb
