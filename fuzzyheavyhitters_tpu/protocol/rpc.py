"""Control plane: leader↔server RPC + server↔server data-plane socket.

Process topology mirrors the reference (SURVEY.md §2 "distributed
communication backend"):

- **Control plane** — leader connects to both servers and drives the 8-verb
  protocol of the reference's tarpc ``Collector`` service (ref: rpc.rs:56-66):
  ``reset, add_keys, tree_init, tree_crawl, tree_crawl_last, tree_prune,
  tree_prune_last, final_shares``.  Transport: length-prefixed pickle over
  TCP via asyncio (the tarpc+bincode analogue; pickle protocol 5 gives
  zero-copy numpy buffers).
- **Data plane** — one server↔server TCP connection carrying the packed
  share-bit tensors per level (server1 listens on ``port+1``, server0 dials
  with retries — the reference's GC-mesh bootstrap order, server.rs:197-262,
  collapsed from ``num_cpus`` sockets to one because the exchange is a single
  batched tensor, not per-thread GC traffic).  On a shared TPU pod the same
  exchange rides ICI via parallel/mesh.py instead.

Counts come back as **field-element shares**: both servers derive a common
pseudorandom mask stream from a shared seed — the reference hardcodes the
same PRG seed on both servers ("XXX This is bogus", server.rs:331-332) — so
server0 returns ``count + r`` and server1 returns ``r``, and the leader
reconstructs ``v0 - v1`` exactly as ``keep_values`` does
(ref: collect.rs:945-989).  Inner levels use FE62, the last level F255
(ref: rpc.rs:60-62 FE vs FieldElm).

Divergence, by design: the reference's ``tree_prune`` carries an alive list
and servers rebuild child nodes eagerly; here prune and child
materialization are fused — the leader sends (parent_idx, pattern, n_alive)
and the server advances only the survivors (see protocol/collect.py's
memory plan).

Fault tolerance (the resilience layer, this repo's addition — the
reference restarts the whole run on any socket error):

- the leader-side :class:`CollectorClient` RECONNECTS: on transport loss
  it redials under the shared backoff policy
  (``resilience.policy.DIAL_POLICY``), bumps its session *epoch*, and
  replays the calls whose responses never arrived;
- the server answers replays IDEMPOTENTLY: each leader session keeps a
  bounded ``(req_id) → response`` dedup cache plus an in-flight table, so
  a stateful verb (``tree_prune``, ``add_keys``) that already ran is
  answered from cache instead of double-applied;
- ``tree_checkpoint``/``tree_restore`` verbs persist/reload the server's
  crawl state at leader-chosen level boundaries, and ``plane_reset``
  re-establishes the server↔server data plane (redial + fresh
  ``_plane_handshake``) after a peer loss — together they let
  ``RpcLeader.run_supervised`` re-run only the lost levels.

Streaming ingestion (the online front door, ROADMAP "Streaming
ingestion"): instead of one bulk ``add_keys`` upload, clients submit key
chunks continuously via ``submit_keys`` into per-window append-only
pools, gated by resilience/admission.py (token-bucket rate limits,
per-client quotas, bounded pools, reject-vs-reservoir shedding);
``window_seal`` freezes a window at its boundary and ``window_load``
materializes the frozen snapshot as the crawl's key batch — the normal
level loop then runs on it while ingest keeps landing in later windows
(``submit_keys`` bypasses the verb lock like ``add_keys``).  Pools ride
``tree_checkpoint``/``tree_restore`` (entry slots, per-``sub_id``
verdicts, reservoir RNG state), so a kill mid-window neither loses nor
double-counts admitted keys.

Multi-tenant collection sessions (ROADMAP "Multi-host, multi-tenant
collector fleet", items b/c): every piece of per-collection state above
lives in a keyed :class:`~.sessions.CollectionSession` selected on the
wire by the ``collection`` field of the existing ``__hello__``
handshake — one server pair serves N independent collections at once,
each with its own frontier, sketch ratchet, expand cache, ingest gate
(token bucket + quotas + pools), replay-dedup namespace, checkpoint
namespace, OT endpoints, and verb lock.  The server↔server data plane
demultiplexes into per-collection channels (:class:`~.sessions.PlaneMux`
— frames are ``(collection, payload)``), so two tenants' 2PC exchanges
interleave on one socket without desynchronizing; per-session base-OT /
coin-flip handshakes run lazily over each session's channel.  Device
work interleaves across sessions through the
:class:`~.tenancy.TenantScheduler`: while tenant A's span waits on the
GC/OT wire, tenant B's expand stage takes a device turn (the
``pipeline_stalls`` gap a second tenant fills — counted as
``tenant_stall_fills``), and warmup's compiled-program ladder is shared
process-wide (:mod:`~.tenancy` WarmLadder) so a new collection on a
warmed shape pays zero fresh compiles.  A connection that never names a
collection works on the DEFAULT session; every single-tenant flow is
unchanged, including checkpoint file names.
"""

from __future__ import annotations

import asyncio
import collections as _collections
import contextlib
import os
import pickle
import secrets as _secrets
import struct
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import alerts as obsalerts
from ..obs import devmem as obsdevmem
from ..obs import exporter as obsexporter
from ..obs import metrics as obsmetrics
from ..obs import trace as obstrace
from ..ops import baseot, dpf, gc, otext
from ..ops.fields import F255, FE62
from ..ops.ibdcf import EvalState, IbDcfKeyBatch
from ..parallel import kernel_shard, server_mesh as smesh, sketch_shard
from ..resilience import chaos as reschaos
from ..resilience import policy as respolicy
from ..utils import guards, taint_guard
from ..utils.config import Config
from . import collect, mpc, secure, sessions, sketch as sketchmod, tenancy
from .sessions import (  # noqa: F401  (re-exports: wire-format helpers kept importable as rpc.*)
    DEFAULT_COLLECTION,
    SHARED_MASK_SEED,
    CollectionSession,
    SessionTable,
    _SKETCH_TREEDEF,
    mask_f255,
    mask_fe62,
)

_HDR = struct.Struct("<Q")


async def _send(writer: asyncio.StreamWriter, obj, count=None,
                flush: bool = True) -> None:
    """``count``, when given, is called with the framed byte size — the
    data-plane accounting hook (obs counters).  ``flush=False`` skips the
    ``drain()`` backpressure wait: asyncio delivers the buffered bytes
    regardless (drain only waits when the write buffer tops the
    high-water mark), so a burst of consecutive frames can coalesce into
    ONE drain on its final frame instead of one await per frame — but
    some frame in every burst MUST flush, or a dead peer lets the buffer
    grow without bound."""
    data = pickle.dumps(obj, protocol=5)
    if count is not None:
        count(len(data) + _HDR.size)
    writer.write(_HDR.pack(len(data)) + data)
    if flush:
        await writer.drain()


async def _recv(reader: asyncio.StreamReader, count=None):
    """Frame reads are DELIBERATELY unbounded: serve/reader loops wait
    indefinitely for the next frame by design — response waits are
    bounded at the caller (per-verb ``Deadline`` on the pending future)
    and the data plane by TCP keepalive, not by a read timeout here."""
    # fhh-lint: disable=unbounded-await (see docstring)
    hdr = await reader.readexactly(_HDR.size)
    (n,) = _HDR.unpack(hdr)
    if count is not None:
        count(n + _HDR.size)
    # fhh-lint: disable=unbounded-await (see docstring)
    return pickle.loads(await reader.readexactly(n))


async def _fetch(
    x, reg: obsmetrics.Registry | None = None, level: int | None = None
) -> np.ndarray:
    """Device->host fetch OFF the event loop.  A bare ``np.asarray`` on a
    device array blocks the whole loop for a full transfer (a ~110 ms RTT
    on remote-chip tunnels) — serializing the two servers' fetches when
    they share a process (the in-process bench/tests) and starving
    keepalives/concurrent verbs in any deployment.  np.asarray of distinct
    arrays is thread-safe in JAX; the GIL releases during the copy.

    ``reg`` counts the fetch: on remote-chip tunnels fetch COUNT, not byte
    count, is the latency floor (each is a full round trip), so the run
    report carries both.  ``level`` attributes the fetch when the call
    site sits outside any span (span-active callers inherit)."""
    if reg is not None:
        reg.count("device_fetches", level=level)
    _start_host_copy(x)
    return await asyncio.to_thread(np.asarray, x)


def _start_host_copy(x) -> None:
    """Kick off the device->host DMA for ``x`` without blocking (the
    ``copy_to_host_async`` half of a double-buffered fetch): the copy
    proceeds while the caller does other work — another span's expand
    dispatch, a peer exchange — and the later ``np.asarray`` completes
    against an already-landed (or in-flight) buffer instead of starting
    the transfer then.  Best-effort: plain numpy inputs and JAX builds
    without the method fall through to the synchronous copy."""
    fn = getattr(x, "copy_to_host_async", None)
    if fn is None:
        return
    try:
        fn()
    except Exception:  # fhh-lint: disable=broad-except (pure prefetch hint: any failure means the sync np.asarray path simply does the whole copy)
        pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

# dedup bounds: the cache must cover every req_id a client could still
# replay — at most its in-flight window (the key-upload window of 256 in
# leader_rpc.upload_keys is the largest) plus slack — and is bounded by
# BYTES as well as count: verb responses carry share arrays (tree_crawl,
# final_shares) that can each be MBs at production scale, and a
# count-only bound would pin ~1024 of them.  Sessions are bounded too, so
# a leader that reconnects under fresh session ids can't grow server
# memory without bound.
_SESSION_CACHE_CAP = 1024
_SESSION_CACHE_BYTES = 128 << 20
_SESSION_CAP = 8


def _resp_nbytes(resp) -> int:
    """Approximate retained size of a cached response (array payloads
    dominate; containers/scalars get a flat floor)."""
    if isinstance(resp, np.ndarray):
        return resp.nbytes + 64
    if isinstance(resp, dict):
        return 64 + sum(_resp_nbytes(v) for v in resp.values())
    if isinstance(resp, (list, tuple)):
        return 64 + sum(_resp_nbytes(v) for v in resp)
    return 64


class _Session:
    """One leader session's idempotent-replay state: responses already
    sent (``cache``) and verbs still executing (``inflight``).  A replay
    of a cached req_id is answered from the cache; a replay of an
    in-flight req_id awaits the SAME execution — the verb never runs
    twice either way."""

    __slots__ = (
        "epoch", "cache", "sizes", "bytes_total", "inflight", "last_seen",
        "collection",
    )

    def __init__(self):
        self.epoch = 0
        self.collection = DEFAULT_COLLECTION  # bound at __hello__
        self.cache: _collections.OrderedDict = _collections.OrderedDict()
        self.sizes: dict[int, int] = {}
        self.bytes_total = 0
        self.inflight: dict[int, asyncio.Future] = {}
        self.last_seen = time.monotonic()

    def put(self, req_id, resp) -> None:
        """Cache a response under the count AND byte bounds.  The newest
        entry always survives even when it alone exceeds the byte cap —
        replay correctness of the in-flight call beats the bound."""
        nb = _resp_nbytes(resp)
        self.cache[req_id] = resp
        self.sizes[req_id] = nb
        self.bytes_total += nb
        while len(self.cache) > 1 and (
            len(self.cache) > _SESSION_CACHE_CAP
            or self.bytes_total > _SESSION_CACHE_BYTES
        ):
            old, _ = self.cache.popitem(last=False)
            self.bytes_total -= self.sizes.pop(old, 0)


# Runtime twin of the fhh-race guard map — the "CollectorServer.*"
# entries of pyproject [tool.fhh-lint.guards] (server-INFRA state; the
# per-collection state moved to sessions.CollectionSession and its
# _SESSION_GUARDS twin).  Drift-tested in tests/test_concurrency.py.
_SERVER_GUARDS = {
    "_sessions": "_verb_lock",
}


def _session_metrics_producer(ref):
    """A /metrics producer bound to a CollectorServer by weakref: per
    scrape, publish live session rows as labeled gauges and run the
    session alert rules over the same snapshot.  Returns ``None`` once
    the server is gone so the exporter prunes it."""

    def produce():
        srv = ref()
        if srv is None:
            return None
        try:
            sess = srv._sessions_status()
        # fhh-lint: disable=broad-except (scrape-thread probe racing the
        # event loop: a torn dict iteration skips one frame, never 500s)
        except Exception:
            return []
        obsalerts.evaluate_sessions(
            sess["per_session"], source=f"server{srv.server_id}"
        )
        reg = f'registry="server{srv.server_id}"'
        lines = [
            "# TYPE fhh_session_last_progress_seconds gauge",
            "# TYPE fhh_session_queue_depth_keys gauge",
            "# TYPE fhh_session_dedup_entries gauge",
        ]
        for key, row in sorted(sess["per_session"].items()):
            lbl = f'{{{reg},collection="{obsexporter._esc(key)}"}}'
            lines.append(
                f"fhh_session_last_progress_seconds{lbl}"
                f" {row['last_progress_s']}"
            )
            lines.append(
                f"fhh_session_queue_depth_keys{lbl} {row['queue_depth']}"
            )
            lines.append(
                f"fhh_session_dedup_entries{lbl} {row['dedup_entries']}"
            )
        return lines

    return produce


class CollectorServer:
    """One collector server process (ref: server.rs:44-172).

    ``server_id`` 0 dials the peer, 1 listens (ref: server.rs:208-233).

    Multi-tenant: the server itself holds only SHARED infrastructure —
    the control listeners, the peer data plane (demultiplexed per
    collection by :class:`~.sessions.PlaneMux`), the replay-dedup
    session table, the tenant scheduler, and the boot id.  Everything a
    single collection owns lives in its
    :class:`~.sessions.CollectionSession` (``self._table``), resolved
    per connection from the ``__hello__`` handshake; the attribute
    properties below delegate to the DEFAULT session so single-tenant
    callers (and the existing tests) see the exact pre-session surface.
    """

    def __init__(self, server_id: int, cfg: Config, *,
                 obs: obsmetrics.Registry | None = None,
                 ckpt_dir: str | None = None,
                 _mesh_chaos: object | None = None):
        self.server_id = server_id
        self.cfg = cfg
        self.ckpt_dir = ckpt_dir
        # telemetry: ONE registry per server for shared-plane accounting
        # (control bytes, replay dedup, plane resets, tenant scheduler);
        # the DEFAULT collection session shares it — so single-tenant
        # runs report exactly as before — and every other session gets
        # its own "server{id}:{collection}" registry (the heartbeat then
        # names the active (session, phase) pair).
        self.obs = obs if obs is not None else obsmetrics.Registry(
            f"server{self.server_id}"
        )
        # per-collection sessions, keyed on the wire (__hello__)
        self._table = SessionTable(server_id, cfg, self.obs, ckpt_dir)
        # device-work interleaving across sessions + stall-fill telemetry
        self._sched = tenancy.TenantScheduler(self.obs)
        # peer data plane: one socket, demuxed per collection; sends are
        # (collection, payload) frames, the pump routes receives
        self._peer_reader: asyncio.StreamReader | None = None
        self._peer_writer: asyncio.StreamWriter | None = None
        self._plane = sessions.PlaneMux(
            route_count=self._plane_count, tag=f"server{server_id}"
        )
        self._peer_addr: tuple | None = None
        # resilience state: boot id (reconnect vs restart), per-leader-
        # session replay dedup, control writers for aclose
        self._boot_id: str = _secrets.token_hex(8)
        self._sessions: dict = {}
        self._ctl_writers: set = set()
        # injected device-loss schedule (resilience.chaos.MeshChaos —
        # tests and bin/server wire FHH_MESH_FAULTS here); fires against
        # whichever session's crawl reaches the scheduled level first
        self._mesh_chaos = _mesh_chaos
        # server-infra lock: guards the replay-session table (and
        # serializes plane_reset); per-collection verbs serialize on
        # their session's OWN _verb_lock instead
        self._verb_lock = asyncio.Lock()
        # live /metrics plane (obs.exporter): when the exporter is up,
        # this server publishes its session rows per scrape and lets the
        # alert engine evaluate them.  Weakref producer: a dropped server
        # (tests construct hundreds) returns None and is pruned instead
        # of pinning its registries forever.  Gated on running() so the
        # disabled path registers nothing at all.
        if obsexporter.running():
            obsexporter.add_producer(_session_metrics_producer(weakref.ref(self)))
        # LAST: the sanitizer (a no-op unless FHH_DEBUG_GUARDS=1 or
        # cfg.debug_guards) wraps the already-constructed guarded state
        guards.install(self, _SERVER_GUARDS, force=self.cfg.debug_guards)

    # -- default-session delegation (single-tenant compat surface) --------
    #
    # The pre-session CollectorServer exposed its per-collection state as
    # plain attributes; tests, chaos harnesses, and operator tooling
    # read (and occasionally write) them.  Each property below is a
    # straight view onto the DEFAULT session's attribute.

    def _default(self) -> CollectionSession:
        return self._table.default()

    @property
    def _mesh(self):
        return self._default()._mesh

    def _mk_state_prop(name):  # noqa: N805  (class-body helper, deleted below)
        def _get(self):
            return getattr(self._default(), name)

        def _set(self, value):
            setattr(self._default(), name, value)

        return property(_get, _set)

    keys = _mk_state_prop("keys")
    keys_parts = _mk_state_prop("keys_parts")
    alive_keys = _mk_state_prop("alive_keys")
    frontier = _mk_state_prop("frontier")
    _children = _mk_state_prop("_children")
    _last_shares = _mk_state_prop("_last_shares")
    _expand_ready = _mk_state_prop("_expand_ready")
    _ingest_pools = _mk_state_prop("_ingest_pools")
    _admission = _mk_state_prop("_admission")
    _sketch = _mk_state_prop("_sketch")
    _sketch_seed = _mk_state_prop("_sketch_seed")
    _sketch_root = _mk_state_prop("_sketch_root")
    _ratchet_digest = _mk_state_prop("_ratchet_digest")
    _ot = _mk_state_prop("_ot")
    _ot_snd = _mk_state_prop("_ot_snd")
    _ot_rcv = _mk_state_prop("_ot_rcv")
    _sec_seed = _mk_state_prop("_sec_seed")
    del _mk_state_prop

    # checkpoint-namespace helpers, default-session view (tests/tooling)
    def _ckpt_path(self, level: int) -> str:
        return self._default().ckpt_path(level)

    def _ckpt_levels(self) -> list:
        return self._default().ckpt_levels()

    def _ckpt_prune(self, keep: int = 2) -> None:
        self._default().ckpt_prune(keep)

    def _ckpt_clear(self) -> None:
        self._default().ckpt_clear()

    # -- verbs (ref: rpc.rs:56-66) ---------------------------------------

    async def reset(self, _req, cs: CollectionSession | None = None) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        cs = cs if cs is not None else self._default()
        # the DEFAULT session shares the SERVER registry: when other
        # tenants are live, its reset must not zero their shared-plane
        # accounting (scheduler fills, dedup hits, control bytes) —
        # per-session registries always wipe
        cs.reset_state(
            reset_obs=(
                cs.key != DEFAULT_COLLECTION or len(self._table) <= 1
            )
        )
        return True

    async def add_keys(self, req, cs: CollectionSession | None = None) -> bool:  # fhh-race: atomic (unlocked upload fast path: append-only, never suspends — many in-flight batches deserialize concurrently by design)
        """req: pytree-of-arrays key batch chunk [B, d, 2] (the tensor form
        of AddKeysRequest, ref: rpc.rs:13-15).  An optional ``sketch`` entry
        carries the clients' malicious-security material (MAC'd payload
        DPFs + triples, protocol/sketch.py)."""
        cs = cs if cs is not None else self._default()
        cs.keys_parts.append(IbDcfKeyBatch(*req["keys"]))
        if req.get("sketch") is not None:
            cs._sketch_parts.append(
                jax.tree.unflatten(
                    jax.tree.structure(_SKETCH_TREEDEF), req["sketch"]
                )
            )
        return True

    async def tree_init(self, req, cs: CollectionSession | None = None) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        cs = cs if cs is not None else self._default()
        if not cs.keys_parts:
            raise RuntimeError("tree_init before add_keys")
        # the session's data-plane channel must be keyed (coin flip +
        # base-OT) before the ratchet root commits or any level crawls
        await self._ensure_session_plane(cs)
        root_bucket = int((req or {}).get("root_bucket", 1))
        cs.concat_keys()
        n = cs.keys.cw_seed.shape[0]
        cs.alive_keys = np.ones(n, bool)
        if cs._mesh is not None:
            cs.frontier = cs._mesh.shard_frontier(
                collect.tree_init(cs.keys, root_bucket, planar=False)
            )
        else:
            cs.frontier = collect.tree_init(cs.keys, root_bucket)
        cs._children = None
        cs._shard_children.clear()
        cs._shard_last.clear()
        cs._shard_level = None
        cs._expand_ready.clear()
        if cs._sketch_parts:
            cs.concat_sketch()
            root = dpf.eval_init(cs._sketch.key)  # [N, d]
            cs._sketch_states = jax.tree.map(
                lambda a: jnp.broadcast_to(a[None], (1,) + a.shape), root
            )
            cs._sketch_pids = np.zeros(
                (1, cs._sketch.key.root_seed.shape[1]), np.int32
            )
            cs._sketch_depth = 0
            cs._sketch_pairs = None
            # commit the challenge ratchet: root = the coin flip of the
            # CURRENT data-plane session (unpredictable to clients, who
            # committed their keys before this point), transcript = empty.
            # Both are checkpointed with the frontier, so later plane
            # resets / restarts cannot perturb any level's challenge.
            # A loaded STREAMING window overrides the root with its own
            # seal-time commitment (sketch.window_root, installed by
            # window_load): the root then survives server restarts via
            # the seal stats + ingest checkpoint, so a recovered
            # window's re-run replays the identical challenge instead
            # of re-opening its slabs under a fresh coin flip.
            root_src = (
                cs._window_sketch_root
                if cs._window_sketch_root is not None
                else cs._sketch_seed
            )
            cs._sketch_root = np.asarray(root_src, np.uint32).copy()
            cs._ratchet_digest = sketchmod.transcript_init()
        return True

    def _sketch_bind(self, cs, n: int, d: int):
        """Resolve the sketch verify's shard binding for this session's
        batch: the data mesh's leading devices under the
        ``Config.sketch_shards`` budget (0 = auto follows the data
        shards), ``None`` on a meshless server or when only one shard
        fits — the single fused program then runs on the default device.
        Pure lru-cached machinery underneath (like ``kernel_bind``)."""
        if cs._mesh is None:
            return None
        budget = (
            cs._mesh.shards
            if self.cfg.sketch_shards <= 0
            else min(int(self.cfg.sketch_shards), cs._mesh.shards)
        )
        return sketch_shard.bind(
            cs._mesh._active_devices(), n, d, budget
        )

    async def sketch_verify(self, req, cs: CollectionSession | None = None) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Malicious-security check (ref intent: the TreeSketchFrontier*
        verb vestiges rpc.rs:40-51, gate at collect.rs:495): sketch inner
        products + Beaver verification over the peer data plane, per
        (client, dim) — a client fails if ANY dim fails; failing clients'
        liveness flags flip before the gated counts are taken.

        Depth semantics: ``level == 0`` verifies the FULL depth-1 level
        (both children of every dim's root, evaluated on the fly) so the
        first threshold never acts on unverified counts; ``level >= 2``
        verifies the depth-``level`` frontier shares stored by the prune
        of ``level - 1``.  Depth 1's frontier re-verify is deliberately
        absent — its Beaver triples were consumed by the level-0 full
        check, and re-opening them under a second challenge would leak
        ``<r - r', x>`` (see protocol/sketch.py scope note).

        The challenge randomness comes from the per-level RATCHET
        (sketch.py): hash(coin-flipped root committed at ``tree_init`` ‖
        level ‖ crawl-transcript digest) — never a public constant (a
        client must not be able to predict r), and DETERMINISTIC given
        the crawl transcript, so a level re-run after checkpoint recovery
        replays the identical challenge instead of re-opening its Beaver
        triple slab under fresh randomness (which would leak
        ``<r - r', x>``)."""
        cs = cs if cs is not None else self._default()
        if cs._sketch is None:
            raise RuntimeError("sketch_verify without sketch keys")
        await self._ensure_session_plane(cs)
        level = int(req["level"])
        k = cs._sketch.key
        L = k.data_len
        n, d = k.root_seed.shape[0], k.root_seed.shape[1]
        if level == 0:
            if cs._sketch_depth != 0:
                # the root check must run before the first prune: the
                # frontier-following states have advanced past the root,
                # so a late call would verify garbage and corrupt honest
                # clients' liveness flags
                raise RuntimeError(
                    "level-0 full check called after the tree advanced"
                )
            # full-width depth-1 check: both children of the root per dim
            last = L == 1
            fld = F255 if last else FE62
            st = jax.tree.map(lambda a: a[0], cs._sketch_states)  # [N, d]
            cw = dpf.level_cw(k, 0)
            cwv = k.cw_val[..., 0, :] if not last else k.cw_val_last
            sides = []
            for c in (False, True):
                _, p = dpf.eval_bit(
                    cw, st, jnp.full((n, d), c), cwv, k.key_idx, fld,
                    sketchmod.LANES,
                )
                sides.append(p)
            pairs_fn = jnp.stack(sides)  # [2, N, d, LANES(, limbs)]
            checks = [(pairs_fn, 0, fld, 0)]
        else:
            if L == 1:
                # the level-0 full check already consumed triples_last; a
                # second opening under a fresh challenge leaks <r - r', x>
                raise RuntimeError(
                    "data_len=1: the leaf check is the level-0 full check"
                )
            if level == 1:
                # the server is the enforcement boundary, not the leader:
                # depth 1's triples (index 0) were consumed by the level-0
                # full check, and opening them again under level 1's
                # different challenge reveals <r - r', x> of honest
                # clients' payloads
                raise RuntimeError(
                    "depth 1 is covered by the level-0 full check; "
                    "re-verifying it would re-open its Beaver triples"
                )
            if cs._sketch_pairs is None or cs._sketch_pairs[-1][1] != level:
                raise RuntimeError(f"no stored sketch shares for depth {level}")
            # radix fusion: the latest prune stored one (pairs, depth,
            # field) entry per bit level it fused — verify each stored
            # depth under its OWN ratcheted challenge and Beaver slab
            # (every slab still opens exactly once).  Depth 1 is dropped:
            # the level-0 full check consumed its triples, and re-opening
            # them under a second challenge would leak <r - r', x>.
            checks = [
                (p, dep - 1, f, dep)
                for (p, dep, f) in cs._sketch_pairs
                if dep >= 2
            ]
        sk = cs._sketch
        ss = self._sketch_bind(cs, n, d)
        cs.obs.gauge(
            "sketch_shards", 1 if ss is None else ss.k, level=level
        )
        # device-resident, row-sharded verify (parallel/sketch_shard.py):
        # each stored depth's check batch runs as one fused program per
        # stage — sharded along the client axis across the data mesh
        # when one is bound — with the challenge stream derived PER
        # SHARD by CTR seek (bit-identical to the single-device draw),
        # per-shard readbacks reassembled positionally into a
        # byte-identical wire, and a single post-depth verdict readback.
        # Both servers walk the identical check list in depth order, so
        # the data-plane swap sequence stays matched.  The old
        # sketch_batch_size host loop (one dispatch + TWO wire round
        # trips per chunk) survives only in the spec helper
        # (sketch.verify_level).
        with cs.obs.span("sketch", level=level):
            ok_all = None
            for pairs_fn, dpf_level, fld, depth in checks:
                if fld is F255:
                    trip, mk, mk2 = (
                        sk.triples_last, sk.mac_key_last, sk.mac_key2_last
                    )
                else:
                    # host slab slice: sketch key leaves are host numpy
                    # (the uploaded chunks), so the per-level slab costs
                    # no dispatch
                    trip = mpc.level_slab(sk.triples, dpf_level)
                    mk, mk2 = sk.mac_key, sk.mac_key2
                challenge = cs.challenge_seed(depth)
                cor, state = sketch_shard.cor_state(
                    ss, fld, pairs_fn, trip, mk, mk2, challenge, depth
                )
                cs.obs.count(
                    "device_fetches", 1 if ss is None else ss.k, level=level
                )
                # cor exchange: per-shard D2H copies assembled positionally
                # into ONE wire message (sketch_shard.wire starts the DMAs)
                cor_np = await asyncio.to_thread(sketch_shard.wire, cor)
                peer_cor = await self._swap(cs, cor_np)
                o = sketch_shard.out_shares(
                    ss, fld, state, cor, peer_cor, bool(self.server_id)
                )
                cs.obs.count(
                    "device_fetches", 1 if ss is None else ss.k, level=level
                )
                o_np = await asyncio.to_thread(sketch_shard.wire, o)
                peer_o = await self._swap(cs, o_np)
                ok_dev = sketch_shard.verdicts(ss, fld, o, peer_o)
                # per-depth verdicts AND on device; exclusion is a
                # conjunction, so one batched readback after the loop is
                # bit-identical to a fetch per depth
                ok_all = ok_dev if ok_all is None else ok_all & ok_dev
            # the check batch's SINGLE post-verify readback: the verdict
            # vector (checks is never empty — level 0 contributes its
            # full check, a fused prune always stores its final depth)
            ok = await _fetch(ok_all, cs.obs, level=level)
            cs.alive_keys &= ok
        if level != 0:
            # one-shot within a boot: each stored depth's pairs open once;
            # a same-boot duplicate call is answered by the session dedup
            # cache, and a post-recovery re-run reloads the pairs from the
            # checkpoint and replays the IDENTICAL ratcheted challenge
            # (same root, same transcript) — a replay, not a second
            # opening.  The level-0 path has no stored pairs and re-runs
            # under the same identical-challenge argument.
            cs._sketch_pairs = None
        return cs.alive_keys.copy()

    # data-plane framing with byte/message accounting; levels attribute
    # via the active span (obs.metrics.Registry.count).  Every frame is
    # (collection, payload): sends interleave freely across sessions
    # (one atomic write per frame), receives demux through the PlaneMux
    # so each session reads only its own FIFO channel.
    def _plane_count(self, chan: str, nbytes: int) -> None:
        """PlaneMux byte-accounting hook: received bytes land on the
        owning session's registry (whose active span attributes them to
        the level being exchanged), unknown channels on the server's."""
        cs = self._table.peek(chan)
        reg = cs.obs if cs is not None else self.obs
        reg.count("data_bytes_recv", nbytes)

    async def _dp_send(self, cs: CollectionSession, obj):
        cs.obs.count("data_msgs_sent")
        # under fhh-trace the frame's session header carries this verb's
        # (trace_id, span_id), so the peer's arrival instant parents
        # under the sender's span in the merged timeline
        hdr = obstrace.wire_tag() if obstrace.enabled() else None
        frame = (cs.key, obj) if hdr is None else (cs.key, obj, hdr)
        await _send(
            self._peer_writer, frame,
            count=lambda n: cs.obs.count("data_bytes_sent", n),
        )

    async def _dp_recv(self, cs: CollectionSession):
        # wire waits are what a SECOND tenant's device work can fill:
        # mark them so the scheduler's stall-fill accounting sees the gap
        with self._sched.wire_wait(cs.key):
            return await self._plane.recv(cs.key)

    async def _swap(self, cs: CollectionSession, obj):
        """Role-ordered data-plane exchange on this session's channel:
        server 0 writes first, server 1 reads first — symmetric
        send-then-recv deadlocks once payloads exceed the combined
        socket buffers (both drains stall)."""
        if self.server_id == 0:
            await self._dp_send(cs, obj)
            return await self._dp_recv(cs)
        peer = await self._dp_recv(cs)
        await self._dp_send(cs, obj)
        return peer

    def _emit_level_phases(self, cs, level: int, fss, gc_ot, field) -> None:
        """Per-level phase line (the successor of the old three prints):
        structured, severity=debug so a 512-level crawl doesn't spam the
        console, totals always available in the run report.  Takes the
        three exited spans — their ``seconds`` is THIS pass's duration,
        where the registry total would inflate on a re-crawled level."""
        obs.emit(
            "level.phases",
            severity="debug",
            server=self.server_id,
            collection=cs.key,
            level=level,
            fss_s=fss.seconds,
            gc_ot_s=gc_ot.seconds,
            field_s=field.seconds,
        )


    # -- expand stage (device) vs open stage (plane I/O) -----------------
    #
    # The per-span crawl is split so the DEVICE half can run ahead of the
    # PLANE half: ``_do_expand`` dispatches the FSS expansion (+ string
    # extraction in secure mode) and starts the host copy; the open stage
    # consumes it under the verb lock.  ``_maybe_pre_expand`` runs the
    # expand stage at FRAME ARRIVAL — while the previous span's open
    # stage is awaiting the data plane — which is what overlaps span k's
    # GC/OT network phase with span k+1's device compute (the leader
    # keeps both frames in flight via ``crawl_pipeline_depth``).

    def _do_expand(self, cs, level: int, last: bool, shard) -> dict:  # fhh-race: atomic (dispatch-only device work, never suspends; called both under the session's verb lock and from the frame-arrival pre-expand)
        """Device half of one crawl span: dispatch-only (no sync — a
        block_until_ready here would cost a tunnel RTT); pure function of
        (keys, frontier, level, span), so a shard re-run may reuse it
        bit-identically."""
        frontier = cs.shard_frontier_view(shard)
        # radix-2^k fusion: this span covers bit levels [level, level+r)
        # — r = 1 is exactly the pre-radix program (expand_share_bits_radix
        # and child_strings_radix delegate to the radix-1 entry points)
        r = cs.crawl_radix(level)
        packed, children = collect.expand_share_bits_radix(
            cs.keys, frontier, level, r, want_children=not last,
            use_pallas=False if cs._mesh is not None else None,
        )
        out = {"packed": packed, "children": children, "frontier": frontier}
        if self.cfg.secure_exchange:
            d = cs.keys.cw_seed.shape[1]
            S = 2 * d * r  # fused equality string width S'
            if cs._mesh is not None:
                # row-sharded kernel stage (parallel/kernel_shard.py):
                # the whole-level planar test batch partitions along its
                # row/block axis across the data mesh — extension,
                # equality kernels, and b2a all run per shard with a
                # byte-identical wire, so nothing between FSS expansion
                # and the frame serializes onto one device
                F_, N = packed.shape
                C = 1 << (d * r)
                B = F_ * C * N
                ks = cs._mesh.kernel_bind(
                    B, S, self.cfg.secure_kernel_shards
                )
                if ks is not None:
                    out["flat"] = kernel_shard.shard_flat(
                        ks, packed, d, F_, N, r
                    )
                    out["dims"] = (F_, C, N, S)
                    out["kernel"] = ks
                    return out
                # degraded path (batch fills a single planar block, or
                # secure_kernel_shards pins 1): the pre-PR-10 gather of
                # the packed share bits over ICI onto one device.  The
                # counter is the layout detector (a fully sharded crawl
                # never increments it); the timer records DISPATCH time
                # only — device_put returns before the transfer, which
                # completes lazily under the level's later fetch, so a
                # sync here would block this (possibly frame-arrival)
                # context for a full tunnel RTT
                t0 = time.monotonic()
                packed = cs._mesh.gather(packed)
                cs.obs.timer_add(
                    "kernel_gather", time.monotonic() - t0, level=int(level)
                )
                cs.obs.count("kernel_gathers", level=int(level))
            strs = secure.child_strings_radix(packed, d, r)  # [F, C, N, S']
            F_, C, N, S = strs.shape
            out["flat"] = strs.reshape(F_ * C * N, S)
            out["dims"] = (F_, C, N, S)
        else:
            # double-buffer: the D2H copy of the packed bits starts NOW
            # and lands while other work (the previous span's exchange)
            # holds the event loop
            _start_host_copy(packed)
        return out

    def _expand_stage(self, cs, level: int, last: bool, shard) -> dict:
        hit = cs._expand_ready.pop((bool(last), int(level), shard), None)
        if hit is not None:
            cs.obs.count("pipeline_expand_hits", level=int(level))
            return hit
        return self._do_expand(cs, level, last, shard)

    def _maybe_pre_expand(self, cs, verb: str, req) -> None:  # fhh-race: atomic (frame-arrival prefetch: reads frontier/keys and stashes in one event-loop slice; every frontier mutation clears the stash before the next slice)
        """Frame-arrival hook (``_dispatch``, BEFORE the verb lock): run
        the expand stage for a sharded crawl verb while earlier spans
        still hold the lock.  Purely an overlap optimization — any
        refusal or failure here just means the verb recomputes (and
        surfaces the real error) under the lock."""
        if verb not in ("tree_crawl", "tree_crawl_last"):
            return
        shard = self._parse_shard(req)
        if shard is None or cs.keys is None or cs.frontier is None:
            return
        if shard[1] > cs.frontier.f_bucket:
            return  # span from another life (stale replay): let it fail
        level, last = int(req["level"]), verb == "tree_crawl_last"
        key = (last, level, shard)
        # bound the stash: depth-many entries live at a time in practice;
        # 32 is far above any sane pipeline depth
        if key in cs._expand_ready or len(cs._expand_ready) >= 32:
            return
        try:
            t0 = time.monotonic()
            cs._expand_ready[key] = self._do_expand(cs, level, last, shard)
            # a device dispatch that ran while ANOTHER tenant's span was
            # on the wire is exactly the gap multi-tenancy fills
            self._sched.note_dispatch(cs.key)
            # dispatch time only, attributed to the fss phase the verb
            # would otherwise have spent it in (no span: another verb's
            # span may be active on this registry right now)
            cs.obs.timer_add("fss", time.monotonic() - t0, level=level)
            cs.obs.count("pipeline_pre_expands", level=level)
        except Exception:  # fhh-lint: disable=broad-except (prefetch only: the verb recomputes under the lock and surfaces the real error to the leader)
            cs._expand_ready.pop(key, None)

    async def _crawl_counts(
        self, cs, level: int, last: bool = False, shard=None
    ) -> np.ndarray:
        # per-level phase taxonomy of the reference (collect.rs:412-503);
        # trusted mode's "GC and OT" slot is the plaintext exchange
        with cs.obs.span("fss", level=level) as sp_fss:
            # a device turn: serialized FIFO across tenants (one
            # accelerator), counted as a stall fill when it ran while
            # another session waited on the wire.  A pre-expanded span
            # already dispatched (and was counted) at frame arrival —
            # don't count the no-op turn, it would inflate the
            # fill-ratio denominator.  The turn covers DISPATCH only;
            # the fetch below blocks on device execution and must not
            # hold other tenants' dispatch out.
            pre = (bool(last), int(level), shard) in cs._expand_ready
            async with self._sched.device_turn(cs.key, count=not pre):
                ex = self._expand_stage(cs, level, last, shard)
            packed, children, frontier = (
                ex["packed"], ex["children"], ex["frontier"]
            )
            # forces the device work to finish
            packed_np = await _fetch(packed, cs.obs)
        with cs.obs.span("gc_ot", level=level) as sp_gc:
            # data plane: swap packed share bits with the peer server
            peer = await self._swap(cs, packed_np)
        with cs.obs.span("field", level=level) as sp_field:
            masks = collect.pattern_masks_radix(
                cs.keys.cw_seed.shape[1], cs.crawl_radix(level)
            )
            counts = await self._reduced_fetch(
                cs, level, collect.counts_by_pattern,
                packed, peer, masks, cs.alive_keys, frontier.alive,
            )
        self._emit_level_phases(cs, level, sp_fss, sp_gc, sp_field)
        # per-level crawl latency histogram (SLO surface): this PASS's
        # three phases, not the registry total a re-run would inflate
        cs.obs.observe(
            "level_latency", sp_fss.seconds + sp_gc.seconds + sp_field.seconds
        )
        cs.stash_children(level, shard, children)
        return counts

    async def _reduced_fetch(self, cs, level: int, single_fn, *args):
        """The per-level reduction + host fetch shared by the trusted
        (``collect.counts_by_pattern``) and secure
        (``secure.node_share_sums``) crawl paths.  Under the multi-chip
        mesh the per-shard client-axis partials fold over ICI (psum)
        BEFORE the fetch — :class:`~..parallel.server_mesh.ServerMesh`
        mirrors the single-device reduction API by name, so the mesh
        form is found via ``single_fn.__name__`` — and the fetch-synced
        ``ici_reduce`` span is the reduction's cost instrument.  Either
        way the caller (and with it the wire) gets host values in the
        single-device layout."""
        if cs._mesh is not None:
            cs.obs.gauge("data_shards", cs._mesh.shards, level=level)
            with cs.obs.span("ici_reduce", level=level):
                out = getattr(cs._mesh, single_fn.__name__)(*args)
                return await _fetch(out, cs.obs)
        return await _fetch(single_fn(*args), cs.obs)

    async def _phase_sync(self, x) -> None:
        """Device sync at a secure-kernel phase boundary (OFF the event
        loop — a bare block_until_ready would starve keepalives exactly
        like a bare np.asarray).  Gated by ``cfg.secure_phase_sync``: the
        phases are sequential data-dependent steps, so syncing costs only
        the dispatch-ahead slack, and buys the phase_otext/garble/eval/
        b2a spans real device seconds instead of dispatch time."""
        if self.cfg.secure_phase_sync:
            await asyncio.to_thread(jax.block_until_ready, x)

    def _zero_phases(self, cs, level: int, *names: str) -> None:
        """Materialize zero-valued phase timers so the secure-kernel
        split always carries all four keys on both servers (a garbler
        has no eval phase, the ot2s path has no garble phase — the run
        report must show those as 0, not absent)."""
        for n in names:
            cs.obs.timer_add(n, 0.0, level=level)

    async def _crawl_counts_secure(
        self, cs, level: int, count_field, last: bool = False, garbler: int = 0,
        shard=None, ot_path=None,
    ) -> np.ndarray:
        """The real 2PC data plane (ref: collect.rs:419-501): equality +
        OT b2a over the peer socket; returns this server's additive field
        share of every per-(node, pattern) count.  No packed share-bit
        tensor ever crosses the server boundary in this mode.

        ``garbler`` names the server that garbles this level (the leader
        alternates it per level — the reference's ``gc_sender`` flag,
        rpc.rs:20-23 — so garbling cost splits across the servers); each
        direction runs its own OT-extension session (``_setup_secure``).
        Every data-plane message is ONE packed array and a level is ONE
        protocol round trip with exactly one device fetch per message:
        ev u -> sender's whole-level planar message — the 1-of-2^S
        payload table when ``secure.ot_path`` picks "ot2s" (no garbled
        circuit at all), the packed garbled batch with the b2a payloads
        riding the OUTPUT wire labels otherwise — built by ONE fused
        device program per side (secure.gb_step_level/ev_open_level; the
        reference runs per-core GC then a separate OT round here,
        collect.rs:419-482).

        The ``gc_ot`` span splits into the secure-kernel phases
        ``otext`` (extension), ``garble``/``eval`` (circuit work — zero
        on the ot2s path), and ``b2a`` (payload table / open + field
        conversion); wire waits are the gc_ot remainder."""
        with cs.obs.span("fss", level=level) as sp_fss:
            # dispatch time only: the FSS expansion itself overlaps the
            # exchange below (no sync — a block_until_ready here would
            # cost a tunnel RTT); a pipelined leader already ran this
            # stage at frame arrival (``_maybe_pre_expand``).  The
            # device turn serializes dispatch FIFO across tenants and
            # counts the stall fills multi-tenancy exists to create —
            # except for a pre-expanded span, whose dispatch was already
            # counted at frame arrival (note_dispatch).
            pre = (bool(last), int(level), shard) in cs._expand_ready
            async with self._sched.device_turn(cs.key, count=not pre):
                ex = self._expand_stage(cs, level, last, shard)
            children, frontier, flat = (
                ex["children"], ex["frontier"], ex["flat"]
            )
            F_, C, N, S = ex["dims"]
            B = F_ * C * N
            cs.obs.count("gc_tests", B, level=level)
            cs.obs.gauge("ot_batch_size", B * S, level=level)
        with cs.obs.span("gc_ot", level=level) as sp_gc:
            w = secure.alive_weight(frontier.alive, cs.alive_keys, C)
            # crawl counter makes every garbling's randomness unique even
            # if a leader re-crawls a level without reset (seed reuse with
            # a fixed R = s would leak cross-run equality deltas to the
            # evaluator)
            cs._crawl_ctr += 1
            gc_seed = secure.derive_seed(cs._sec_seed, 1, level, cs._crawl_ctr)
            b2a_seed = secure.derive_seed(cs._sec_seed, 2, level, cs._crawl_ctr)
            # the leader names the path per verb (like ``garbler``) so
            # both servers always agree on the wire format even when a
            # bench/parity leader overrides its own config; absent, the
            # server's config decides
            path = secure.ot_path(S, ot_path or self.cfg.ot_path)
            cs.obs.count(f"ot_path_{path}", level=level)
            W = secure.payload_words(count_field)
            ks = ex.get("kernel")
            if cs._mesh is not None:
                # per-level kernel layout: the active row-shard count (1
                # = the degraded gather path) feeds the mesh report
                # section and the acceptance gate (kernel_gather ~ 0)
                cs.obs.gauge(
                    "kernel_shards", ks.k if ks is not None else 1,
                    level=level,
                )
            if self.server_id == garbler:  # garbler/sender + OT-ext sender
                u = await self._dp_recv(cs)
                if ks is not None:
                    # ROW-SHARDED kernel stage: extension, payload pair,
                    # and the equality kernel all run per mesh shard
                    # (parallel/kernel_shard.py); the frame reads back
                    # per shard and reassembles positionally — nothing
                    # gathers onto one device
                    with cs.obs.span("otext", level=level):
                        q, idx0 = kernel_shard.snd_extend(
                            ks, cs._ot_snd, u
                        )
                        await self._phase_sync(q)
                    kphase = "b2a" if path == "ot2s" else "garble"
                    with cs.obs.span(kphase, level=level):
                        planes, vals = kernel_shard.gb_kernel(
                            ks, cs._ot_snd.s_block, q, flat, gc_seed,
                            b2a_seed, count_field, garbler, path, idx0,
                        )
                        await self._phase_sync(planes)
                    self._zero_phases(
                        cs,
                        level, "eval",
                        *(("garble",) if path == "ot2s" else ("b2a",)),
                    )
                    cs.obs.count("device_fetches", ks.k, level=level)
                    # msg_wire starts the per-shard D2H copies itself
                    msg_np = await asyncio.to_thread(
                        kernel_shard.msg_wire, ks, planes
                    )
                    await self._dp_send(cs, msg_np)
                else:
                    with cs.obs.span("otext", level=level):
                        idx0 = cs._ot_snd.consumed
                        q = cs._ot_snd.extend(B * S, u)
                        await self._phase_sync(q)
                    with cs.obs.span("b2a", level=level):
                        vals, w0, w1 = secure.b2a_payload_pair(
                            count_field, b2a_seed, B, garbler
                        )
                        if path == "ot2s":
                            msg = secure.ot2s_encrypt_packed(
                                q.reshape(B, S, 4),
                                jnp.asarray(cs._ot_snd.s_block), flat,
                                w1, w0, W, idx0,
                            )
                        await self._phase_sync(w1 if path != "ot2s" else msg)
                    if path == "ot2s":
                        self._zero_phases(cs, level, "garble", "eval")
                    else:
                        with cs.obs.span("garble", level=level):
                            msg, _ = gc.garble_equality_payload_packed(
                                jnp.asarray(cs._ot_snd.s_block),
                                q.reshape(B, S, 4), jnp.asarray(gc_seed),
                                flat, w1, w0, W, idx0,
                            )
                            await self._phase_sync(msg)
                        self._zero_phases(cs, level, "eval")
                    await self._dp_send(cs, await _fetch(msg, cs.obs))
            else:  # evaluator + OT receiver (inputs stay on device: each
                # np.asarray here would cost a full tunnel round trip)
                if ks is not None:
                    with cs.obs.span("otext", level=level):
                        u_arr, t_rows, idx0 = kernel_shard.rcv_extend(
                            ks, cs._ot_rcv, flat
                        )
                        cs.obs.count("device_fetches", ks.k, level=level)
                        # u_wire starts the per-shard D2H copies itself
                        u_np = await asyncio.to_thread(
                            kernel_shard.u_wire, ks, u_arr
                        )
                    await self._dp_send(cs, u_np)
                    bmsg = await self._dp_recv(cs)
                    kphase = "b2a" if path == "ot2s" else "eval"
                    with cs.obs.span(kphase, level=level):
                        vals = kernel_shard.ev_open(
                            ks, t_rows, flat, bmsg, count_field, path, idx0
                        )
                        await self._phase_sync(vals)
                    self._zero_phases(
                        cs,
                        level, "garble",
                        *(("eval",) if path == "ot2s" else ("b2a",)),
                    )
                else:
                    with cs.obs.span("otext", level=level):
                        u, t_rows, idx0 = secure.ev_step1_fused(
                            cs._ot_rcv, flat
                        )
                        u_np = await _fetch(u, cs.obs)  # forces the extension
                    await self._dp_send(cs, u_np)
                    bmsg = await self._dp_recv(cs)
                    if path == "ot2s":
                        with cs.obs.span("b2a", level=level):
                            pay = secure.ot2s_decrypt_packed(
                                jnp.asarray(t_rows).reshape(B, S, 4), flat,
                                bmsg, W, idx0,
                            )
                            vals = secure.words_to_field(count_field, pay)
                            await self._phase_sync(vals)
                        self._zero_phases(cs, level, "garble", "eval")
                    else:
                        with cs.obs.span("eval", level=level):
                            _, pay = gc.eval_equality_payload_packed(
                                bmsg, jnp.asarray(t_rows).reshape(B, S, 4),
                                W, idx0,
                            )
                            await self._phase_sync(pay)
                        with cs.obs.span("b2a", level=level):
                            vals = secure.words_to_field(count_field, pay)
                            await self._phase_sync(vals)
                        self._zero_phases(cs, level, "garble")
        with cs.obs.span("field", level=level) as sp_field:
            if ks is not None:
                # test-sharded b2a shares: scatter into the (F, C, N)
                # frame per shard, alive-gate, and psum back over ICI —
                # the kernel-stage twin of ServerMesh.node_share_sums
                cs.obs.gauge("data_shards", cs._mesh.shards, level=level)
                with cs.obs.span("ici_reduce", level=level):
                    out = kernel_shard.share_sums(
                        ks, count_field, vals, w, F_, C, N
                    )
                    shares = await _fetch(out, cs.obs)
            else:
                vals = vals.reshape((F_, C, N) + count_field.limb_shape)
                shares = await self._reduced_fetch(
                    cs, level, secure.node_share_sums,
                    count_field, vals, jnp.asarray(w),
                )
        self._emit_level_phases(cs, level, sp_fss, sp_gc, sp_field)
        cs.obs.observe(
            "level_latency", sp_fss.seconds + sp_gc.seconds + sp_field.seconds
        )
        cs.stash_children(level, shard, children)
        return shares

    @staticmethod
    def _parse_shard(req):
        s = (req or {}).get("shard")
        return None if s is None else (int(s[0]), int(s[1]))

    async def _mesh_guard(self, cs, level, thunk):
        """Device-loss containment for the multi-chip server: fire any
        scheduled mesh chaos at the crawl boundary (the same consumed-
        once :class:`resilience.chaos.MeshChaos` schedule the 2-D mesh
        path uses), and on a mesh fault recover IN PLACE — a lost device
        is NOT a lost server.  ``state_lost`` (kill) re-shards the
        frontier from the newest on-disk checkpoint and rebuilds the
        keys from the host-side upload chunks; a suspect collective
        (drop) just re-runs.  Either way the crawl re-runs ONCE inside
        the same verb, so the leader sees a slow span, never a fault —
        ``shards_rerun`` counts the cost, ``levels_rerun`` stays zero.

        The chaos hook fires BEFORE any data-plane I/O of the level, so
        the re-run exchanges with the peer exactly once; a real device
        loss mid-exchange desynchronizes the plane and correctly
        escalates through the verb error to the leader's plane_reset +
        retry machinery instead.  The chaos schedule receives the
        SESSION (it clobbers ``frontier``/``_children`` on a kill) —
        whichever tenant's crawl reaches the scheduled level first eats
        the fault, which is exactly the tenant-isolation scenario the
        chaos suite asserts."""
        try:
            if self._mesh_chaos is not None:
                self._mesh_chaos.before_level(cs, int(level))
            return await thunk()
        except reschaos.MeshFaultError as err:
            if cs._mesh is None:
                raise
            await self._mesh_recover(cs, int(level), err)
            return await thunk()

    async def _mesh_recover(self, cs, level: int, err) -> None:
        """Re-shard after a device loss (see :meth:`_mesh_guard`)."""
        cs.obs.count("mesh_faults", level=level)
        cs._expand_ready.clear()  # pre-expanded dispatches are suspect
        state_lost = bool(getattr(err, "state_lost", False))
        if state_lost or cs.frontier is None:
            prev = level - 1
            if cs.ckpt_dir is None or prev not in cs.ckpt_levels():
                # nothing to re-shard from: surface the original fault —
                # the supervising leader owns recovery at that point
                raise RuntimeError(
                    f"mesh device lost at level {level} with no level-"
                    f"{prev} checkpoint to re-shard from"
                ) from err
            cs.keys = None  # device-resident: lost with the shard
            await self.tree_restore({"level": prev}, cs)
            if cs.frontier is None or cs.keys is None:
                # the level stamp existed but the blob was ingest-only
                # (windowed front door between windows): pools came
                # back, crawl state did not — escalate exactly like the
                # no-checkpoint case instead of re-running on None
                raise RuntimeError(
                    f"mesh device lost at level {level}: the level-"
                    f"{prev} checkpoint is ingest-only — no crawl state "
                    "to re-shard from"
                ) from err
            cs.obs.count("mesh_reshards", level=level)
        cs.obs.count("shards_rerun", level=level)
        obs.emit(
            "resilience.mesh_reshard",
            severity="warn",
            server=self.server_id,
            collection=cs.key,
            level=level,
            state_lost=state_lost,
            error=str(err),
        )

    async def tree_crawl(self, req, cs: CollectionSession | None = None) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """-> FE62 shares of per-child counts [F, 2^d] (ref: rpc.rs:60).
        An optional ``shard: (lo, hi)`` restricts the crawl to that node
        span (mid-level retry granularity — the leader assembles)."""
        cs = cs if cs is not None else self._default()
        level = req["level"]
        shard = self._parse_shard(req)
        # a plane reset since the last exchange re-keys this session's
        # channel (fresh coin flip + base-OT) before any wire I/O
        await self._ensure_session_plane(cs)
        if self.cfg.secure_exchange:
            # chip-profiler hook: FHH_PROFILE + FHH_PROFILE_LEVELS=N,...
            # wraps exactly this level's device work in a jax.profiler
            # capture, recorded against the live trace id (obs.trace)
            with obstrace.profile_capture("level", level=int(level)):
                return await self._mesh_guard(
                    cs, level,
                    lambda: self._crawl_counts_secure(
                        cs, level, FE62, garbler=int(req.get("garbler", 0)),
                        shard=shard, ot_path=req.get("ot_path"),
                    ),
                )
        with obstrace.profile_capture("level", level=int(level)):
            counts = await self._mesh_guard(
                cs, level, lambda: self._crawl_counts(cs, level, shard=shard)
            )
        # NB: trusted mode — both servers hold these plaintext counts; the
        # shared-seed mask below is a WIRE-FORMAT shim so the leader's
        # uniform v0 - v1 reconstruction works, not a secrecy mechanism
        # (the reference's hardcoded bogus PRG seed plays the same role,
        # server.rs:331-332).  Secrecy comes from secure_exchange above.
        r = cs.mask_rows(level, shard, counts.shape[-1], f255=False)
        if self.server_id == 0:
            # counts are already host-side; the mask add stays host-side
            # too (FE62.np_add) — the old device add + _fetch cost a full
            # tunnel RTT per level for a ~KB elementwise op
            return FE62.np_add(counts.astype(np.uint64), r)
        return r

    async def tree_crawl_last(self, req, cs: CollectionSession | None = None) -> np.ndarray:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """-> F255 shares [F, 2^d, 8] for the final level (ref: rpc.rs:61,
        collect.rs:775-916 — BlockPair double-block OT payloads in secure
        mode).  Shares are retained for final_shares re-serving; sharded
        calls bank their span and ``tree_prune_last`` assembles."""
        cs = cs if cs is not None else self._default()
        level = req["level"]
        shard = self._parse_shard(req)
        await self._ensure_session_plane(cs)
        if self.cfg.secure_exchange:
            with obstrace.profile_capture("level", level=int(level)):
                shares = await self._mesh_guard(
                    cs, level,
                    lambda: self._crawl_counts_secure(
                        cs, level, F255, last=True,
                        garbler=int(req.get("garbler", 0)), shard=shard,
                        ot_path=req.get("ot_path"),
                    ),
                )
        else:
            with obstrace.profile_capture("level", level=int(level)):
                counts = await self._mesh_guard(
                    cs, level,
                    lambda: self._crawl_counts(
                        cs, level, last=True, shard=shard
                    ),
                )
            r = cs.mask_rows(level, shard, counts.shape[-1], f255=True)
            if self.server_id == 0:
                c = np.zeros(counts.shape + (8,), np.uint32)
                c[..., 0] = counts
                # host-side limb add (F255.np_add): no device round trip
                shares = F255.np_add(c, r)
            else:
                shares = r
        if shard is None:
            cs._last_shares = shares
        else:
            cs._last_shares = None
            cs._shard_last[int(shard[0])] = shares
        return shares

    async def tree_prune(self, req, cs: CollectionSession | None = None) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Fused prune+advance: materialize surviving children
        (ref: rpc.rs:63 tree_prune + collect.rs:918-929).  The sketch DPF
        states advance with the same survivor table."""
        cs = cs if cs is not None else self._default()
        level = req["level"]
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(req["parent_idx"], np.int32)
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        pat_bits = np.asarray(req["pattern_bits"], bool)
        n_alive = int(req["n_alive"])
        # radix wire shape: [F', d] is a radix-1 prune, [F', r, d] a
        # fused one (r bits per dim, step-major — the leader derives it
        # from the same crawl_radix_bits knob this session holds)
        r = 1 if pat_bits.ndim == 2 else pat_bits.shape[1]
        if r != cs.crawl_radix(level):
            raise RuntimeError(
                f"prune pattern carries {r} step bit(s) where this "
                f"session's level-{int(level)} round fuses "
                f"{cs.crawl_radix(level)} (crawl_radix_bits mismatch "
                f"between leader and server?)"
            )
        pb1 = pat_bits if pat_bits.ndim == 2 else pat_bits[:, 0, :]
        cs._expand_ready.clear()  # the frontier is about to mutate
        if cs._children is None and cs._shard_children:
            cs._children = cs.assemble_shard_children()
        if cs._children is not None:  # cache from this level's crawl
            if r == 1:
                cs.frontier = collect.advance_from_children(
                    cs._children, parent, pb1, n_alive
                )
            else:
                cs.frontier = collect.advance_from_children_radix(
                    cs._children, parent, pat_bits, n_alive, r
                )
            cs._children = None
        elif r == 1:  # prune without a preceding crawl: re-expand
            cs.frontier = collect.advance(
                cs.keys, cs.frontier, level, parent, pb1, n_alive,
                use_pallas=False if cs._mesh is not None else None,
            )
        else:  # fused prune without a crawl cache: re-expand r bits
            _, children = collect.expand_share_bits_radix(
                cs.keys, cs.frontier, level, r, want_children=True,
                use_pallas=False if cs._mesh is not None else None,
            )
            cs.frontier = collect.advance_from_children_radix(
                children, parent, pat_bits, n_alive, r
            )
        if cs._sketch is not None:
            cs.advance_sketch(int(level), parent, pat_bits, n_alive)
            cs._ratchet_digest = sketchmod.transcript_absorb(
                cs._ratchet_digest, int(level), parent, pat_bits, n_alive
            )
        cs.obs.gauge("survivors", n_alive, level=int(level))
        return True

    async def tree_prune_last(self, req, cs: CollectionSession | None = None) -> bool:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Last level keeps no child count states to advance — compact the
        stored leaf count shares down to the survivors
        (ref: collect.rs:931-942).  The sketch DPF does advance once more
        so its F255 leaf payloads can be verified post-prune."""
        cs = cs if cs is not None else self._default()
        cs._expand_ready.clear()  # leaf level: nothing expands past it
        if cs._last_shares is None and cs._shard_last:
            parts = sorted(cs._shard_last.items())
            whole = np.concatenate([p for _, p in parts], axis=0)
            if whole.shape[0] != cs.frontier.f_bucket:
                raise RuntimeError(
                    f"sharded last crawl incomplete: shares cover "
                    f"{whole.shape[0]} of {cs.frontier.f_bucket} slots"
                )
            cs._last_shares = whole
            cs._shard_last.clear()
        if cs._last_shares is None:  # protocol-boundary check: no assert
            raise RuntimeError("tree_prune_last called before tree_crawl_last")
        cs._children = None  # leaf level: nothing advances past it
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        parent = np.asarray(req["parent_idx"], np.int64)
        # fhh-lint: disable=host-sync-in-hot-loop (wire input: host numpy)
        pattern = np.asarray(req["pattern_bits"], bool)
        n_alive = int(req["n_alive"])
        L = cs.keys.cw_seed.shape[-2]
        if pattern.ndim == 2:  # radix-1 wire shape [F', d]
            d = pattern.shape[1]
            child = (pattern[:n_alive] << np.arange(d)).sum(axis=1)
            base = L - 1
        else:  # fused leaf prune [F', r, d]: step-major fused child id
            r_, d = pattern.shape[1], pattern.shape[2]
            base = L - r_
            if r_ != cs.crawl_radix(base):
                raise RuntimeError(
                    f"leaf prune pattern carries {r_} step bit(s) where "
                    f"this session's tail round fuses "
                    f"{cs.crawl_radix(base)}"
                )
            shift = np.arange(r_)[:, None] * d + np.arange(d)[None, :]
            child = (
                pattern[:n_alive].astype(np.int64) << shift
            ).sum(axis=(1, 2))
        cs._last_shares = cs._last_shares[parent[:n_alive], child]
        if cs._sketch is not None:
            cs.advance_sketch(
                # fhh-lint: disable=host-sync-in-hot-loop (wire input)
                base, np.asarray(req["parent_idx"], np.int32), pattern, n_alive
            )
            cs._ratchet_digest = sketchmod.transcript_absorb(
                cs._ratchet_digest, base, parent, pattern, n_alive
            )
        cs.obs.gauge(
            "survivors", n_alive, level=cs.keys.cw_seed.shape[-2] - 1
        )
        return True

    async def final_shares(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Re-serve the surviving leaves' count shares for leader-side
        reconstruction (ref: rpc.rs:65, collect.rs:993-1004; tree paths
        live with the leader in this design, see protocol/collect.py)."""
        cs = cs if cs is not None else self._default()
        if cs._window_seal_ts is not None:
            # window seal -> hitters served: the server-visible half of
            # the seal-to-hitters SLO (the driver observes its own
            # leader-side copy in WindowedIngest.crawl_window)
            cs.obs.observe(
                "seal_to_hitters", max(0.0, time.time() - cs._window_seal_ts)
            )
            cs._window_seal_ts = None  # one observation per loaded window
        return {"server_id": self.server_id, "shares": cs._last_shares}

    # -- streaming ingest front door (ROADMAP "Streaming ingestion": the
    # online successor of the one-shot add_keys upload).  Pools and the
    # admission gate are PER SESSION (sessions.CollectionSession): each
    # collection has its own token bucket, quotas, and reservoir, so a
    # flooding tenant exhausts only its own gate. --------------------------

    async def submit_keys(self, req, cs: CollectionSession | None = None) -> dict:
        """Streaming key submission into the named window's pool —
        admission-controlled, append-only, idempotent per ``sub_id``.

        Dispatches WITHOUT the verb lock (like ``add_keys``) — ingest
        rides concurrently with a crawl holding the lock, which is what
        lets a window accrue while the previous window's frozen snapshot
        is crawled.  The admission arithmetic (token bucket, quotas,
        reservoir draws) runs in an EXECUTOR behind the session's
        ``_adm_gate``, so a flooding tenant's admission math cannot
        stall the shared event loop; the one suspension point
        re-validates the dup/seal state before any pool mutates (the
        append itself never suspends).

        Req: ``{window, sub_id, client_id, keys: chunk}`` plus an
        optional ``mirror`` dict carrying the GATE server's verdict —
        the leader-side driver gets the admission decision from server 0
        and replays it onto server 1, so the two pools stay positionally
        identical (admission must never diverge between the servers).

        Verdicts: ``{"admitted": True, slot}``, ``{"admitted": False,
        "shed": True}`` (reservoir mode — final), or ``{"admitted":
        False, "overloaded": True, scope, retry_after_s}`` (retryable:
        the client's RetryPolicy backs off and re-attempts)."""
        cs = cs if cs is not None else self._default()
        window = int(req["window"])
        sub_id = str(req["sub_id"])
        # fhh-lint: disable=chunked-device-readback (wire input: pickled host numpy, no device involved)
        chunk = tuple(np.asarray(a) for a in req["keys"])
        n_keys = int(chunk[0].shape[0])
        # malicious mode: the client's sketch material (MAC'd payload
        # DPFs + triples) rides the SAME entry tuple — the pool's slot
        # semantics (append, reservoir replace) and the checkpoint's
        # generic leaf flattening then cover it for free, and
        # window_load splits the leaves back apart
        if req.get("sketch") is not None:
            if not self.cfg.malicious:
                raise RuntimeError(
                    "sketch material submitted to a semi-honest "
                    "collector (cfg.malicious is off)"
                )
            # fhh-lint: disable=chunked-device-readback (wire input: pickled host numpy, no device involved)
            chunk = chunk + tuple(np.asarray(a) for a in req["sketch"])
        elif self.cfg.malicious:
            raise RuntimeError(
                "malicious mode: submit_keys requires the client's "
                "sketch chunk alongside its keys"
            )
        pool = cs.ingest_pool(window)
        cs.obs.count("pool_submits")
        prev = pool.verdicts.get(sub_id)
        if prev is not None:
            # at-least-once delivery made safe: a replayed submission
            # (reconnect replay under a new req_id, recovery journal
            # replay) answers its RECORDED verdict — the pool and the
            # reservoir RNG are untouched, so nothing double-admits
            cs.obs.count("pool_dup_submits")
            return dict(prev, dup=True)
        if pool.sealed:
            raise RuntimeError(
                f"ingest window {window} is sealed — submit into a later "
                "window"
            )
        mirror = req.get("mirror")
        if mirror is not None:
            # mirror replay never suspends: the gate server's verdict is
            # applied positionally, so the two pools stay identical
            resp = pool.apply_mirror(
                sub_id, chunk, mirror, str(req.get("client_id", ""))
            )
        else:
            v = await cs._admission.admit_offloaded(
                pool.wa, str(req.get("client_id", "")), n_keys,
                gate=cs._adm_gate,
            )
            # the executor await suspended this task: another frame may
            # have replayed this sub_id or sealed the window meanwhile —
            # re-validate before the (non-suspending) append mutates
            prev = pool.verdicts.get(sub_id)
            if prev is not None:
                cs.obs.count("pool_dup_submits")
                return dict(prev, dup=True)
            if pool.sealed:
                raise RuntimeError(
                    f"ingest window {window} sealed during admission — "
                    "submit into a later window"
                )
            resp = pool.apply(sub_id, chunk, v)
        if resp.get("admitted"):
            cs.obs.count("pool_admitted_keys", n_keys)
        elif resp.get("shed"):
            cs.obs.count("pool_shed_keys", n_keys)
        else:
            cs.obs.count("pool_rejected")
        return resp

    async def window_seal(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Freeze the named window at its boundary: no further
        submissions land in it (later ``submit_keys`` name later
        windows); returns the pool stats.  Idempotent — re-sealing a
        sealed window (recovery replays) returns the same stats."""
        cs = cs if cs is not None else self._default()
        w = int(req["window"])
        pool = cs._ingest_pools.get(w)
        if pool is None:
            pool = cs.ingest_pool(w)  # sealing an idle window is legal
        want_root = req.get("sk_root")
        if want_root is not None:
            # recovery re-seal: the driver hands back the ORIGINAL
            # window root it banked at first seal, so a journal-rebuilt
            # pool (restarted server, no usable ingest checkpoint)
            # still commits the identical challenge root — never a
            # fresh one (same slabs under a new root = <r - r', x>)
            want_root = np.array(want_root, np.uint32)
            if pool.sk_root is None:
                pool.sk_root = want_root
            elif not np.array_equal(pool.sk_root, want_root):
                raise RuntimeError(
                    f"window_seal: window {w} already committed a "
                    "different sketch root (recovery replayed stats "
                    "from another life?)"
                )
        if not pool.sealed:
            if self.cfg.malicious and pool.sk_root is None:
                # the window's coin-flip commitment: derived from the
                # SESSION coin flip + window id at the seal boundary,
                # then carried by the seal stats and every ingest
                # checkpoint — the per-window twin of the batch path's
                # tree_init root commit
                await self._ensure_session_plane(cs)
                pool.sk_root = sketchmod.window_root(cs._sketch_seed, w)
            pool.sealed = True
            # the seal instant starts this window's seal-to-hitters SLO
            # clock (observed at final_shares of the crawl that loads it)
            pool.sealed_at = time.time()
            cs.obs.count("windows_sealed")
            obs.emit(
                "ingest.window_sealed",
                server=self.server_id,
                collection=cs.key,
                window=w,
                keys=pool.keys,
                subs=len(pool.entries),
                shed=pool.shed_keys,
            )
        return pool.stats()

    async def window_load(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Materialize a SEALED window's frozen pool as the crawl's key
        batch (the streaming twin of the ``add_keys`` upload): the crawl
        state resets to empty, ``keys_parts`` becomes the pool's
        admitted chunks in slot order, and the normal ``tree_init`` →
        level loop runs on it — while ``submit_keys`` keeps landing in
        later windows.  Ingest pools and checkpoint files are untouched;
        consumed EARLIER windows are dropped (bounded live windows)."""
        cs = cs if cs is not None else self._default()
        w = int(req["window"])
        pool = cs._ingest_pools.get(w)
        if pool is None:
            raise RuntimeError(f"window_load: no ingest pool for window {w}")
        if not pool.sealed:
            raise RuntimeError(f"window_load: window {w} is not sealed")
        if not pool.entries:
            raise RuntimeError(f"window_load: window {w} admitted no keys")
        nk = len(IbDcfKeyBatch._fields)
        has_sketch = len(pool.entries[0]) > nk
        # every refusal BEFORE any state mutates (the PR-4 contract): a
        # half-loaded window must never leave the session with this
        # window's sketch material but no committed root — a later
        # tree_init would commit the live coin flip instead, and a
        # retried window would re-open the same Beaver slabs under a
        # different challenge (<r - r', x>)
        if self.cfg.malicious and not has_sketch:
            raise RuntimeError(
                f"window_load: malicious mode but window {w} carries no "
                "sketch material (submitted before cfg.malicious?)"
            )
        if has_sketch and pool.sk_root is None:
            raise RuntimeError(
                f"window_load: window {w} carries sketch material "
                "but no committed challenge root (sealed by a "
                "pre-sketch server?)"
            )
        cs.clear_crawl_state()  # per-window sketch state clears with it
        cs.keys_parts = [IbDcfKeyBatch(*e[:nk]) for e in pool.entries]
        if has_sketch:
            # the sketch leaves ride each entry tuple (submit_keys
            # appended them): split them back into upload-chunk form and
            # install the window's committed challenge root for the
            # coming tree_init — a recovered window re-loads the SAME
            # root from the restored pool, so its re-run replays the
            # identical challenge sequence
            treedef = jax.tree.structure(_SKETCH_TREEDEF)
            cs._sketch_parts = [
                jax.tree.unflatten(treedef, list(e[nk:]))
                for e in pool.entries
            ]
            cs._window_sketch_root = np.array(pool.sk_root, np.uint32)
        cs._window_seal_ts = pool.sealed_at  # seal-to-hitters SLO clock
        for old in [k for k in cs._ingest_pools if k < w]:
            del cs._ingest_pools[old]
        obs.emit(
            "ingest.window_loaded",
            server=self.server_id,
            collection=cs.key,
            window=w,
            keys=pool.keys,
            sketch=has_sketch,
        )
        return {
            "window": w, "keys": pool.keys, "subs": len(pool.entries),
            "sketch": has_sketch,
        }

    # -- fleet migration verbs (protocol/fleet.py: live session
    # placement across host pairs) ----------------------------------------

    async def session_export(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Bank this session's migratable state as a stamped blob for a
        ``session_import`` on another host pair, or — ``retire`` mode —
        drop the source copy after a confirmed transfer.

        Export quiesces at a window/level boundary only: a session with
        mid-level crawl caches (children banked between crawl and prune,
        sharded spans in flight) refuses loudly — migrating a torn level
        would strand half an exchange on each pair.  The blob is the
        ingest form of the PR-4/7 checkpoint (pools, per-``sub_id``
        verdicts, quota ledgers, reservoir RNG state, and each sealed
        window's committed challenge root), stamped with this server's
        boot id and a per-session export epoch so the importer can
        refuse a replayed or double-applied transfer.  Crawl state is
        NOT exported: windowed crawls rebuild it from the pools
        (``window_load``), and the destination pair re-keys its own
        base-OT/coin-flip plane — per-window challenge roots ride the
        pools, so a migrated malicious window still replays the
        IDENTICAL challenge.

        Retire mode (``{"retire": True, "epoch": E}``): called after the
        destination confirmed its import — drops every retained window
        pool (sealed ones included: bounded retention only evicts on
        idle, and a migrated-away tenant would otherwise pin its pools
        on this host forever) and the crawl state, leaving the session
        idle-evictable."""
        cs = cs if cs is not None else self._default()
        req = req or {}
        if req.get("retire"):
            epoch = int(req.get("epoch", -1))
            if epoch <= 0 or epoch != cs._export_epoch:
                raise RuntimeError(
                    f"session_export: retire epoch {epoch} does not match "
                    f"the last export ({cs._export_epoch}) — refusing to "
                    "drop state that was never transferred"
                )
            dropped = len(cs._ingest_pools)
            cs._ingest_pools.clear()
            cs.clear_crawl_state()
            cs.keys = None
            cs.keys_parts.clear()
            cs.alive_keys = None
            if cs.ckpt_dir is not None and os.path.exists(self._export_path(cs)):
                os.remove(self._export_path(cs))
            # the migrated-away tenant must not hold this pair's
            # progress-age placement signal high forever
            self._sched.forget(cs.key)
            cs.obs.count("sessions_retired")
            obs.emit(
                "fleet.session_retired",
                server=self.server_id,
                collection=cs.key,
                pools_dropped=dropped,
            )
            return {"retired": True, "pools_dropped": dropped}
        # mid-level = in-flight expand caches (children banked between
        # crawl and prune, sharded spans mid-assembly).  _last_shares
        # alone is NOT mid-level: it lingers after a COMPLETED crawl
        # (final_shares re-serves it) and the destination rebuilds crawl
        # state from the pools via window_load anyway.
        if (cs._children is not None or cs._shard_children
                or cs._shard_last):
            raise RuntimeError(
                "session_export: session is mid-level — a migration "
                "quiesces at a window/level boundary only"
            )
        if cs.ckpt_dir is None:
            raise RuntimeError(
                "session_export: no checkpoint dir configured "
                "(start the server with FHH_CKPT_DIR set)"
            )
        blob = {
            "ing_only": np.bool_(True),
            "sess": np.str_(cs.key),
            "level": np.int64(-1),
            # crawl-radix stamp: the destination session must crawl the
            # same fused level grid (its window_load rebuilds crawl state
            # and its sketch ratchet absorbs one fused level per step)
            "radix": np.int64(cs._radix),
        }
        cs.ingest_ckpt_fields(blob)
        cs._export_epoch += 1
        blob["xp_boot"] = np.str_(self._boot_id)
        blob["xp_epoch"] = np.int64(cs._export_epoch)
        path = self._export_path(cs)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **blob)
        os.replace(tmp, path)
        cs.obs.count("session_exports")
        obs.emit(
            "fleet.session_exported",
            server=self.server_id,
            collection=cs.key,
            epoch=cs._export_epoch,
            windows=sorted(cs._ingest_pools),
            path=path,
        )
        return {
            "path": path,
            "boot": self._boot_id,
            "epoch": cs._export_epoch,
            "windows": sorted(cs._ingest_pools),
        }

    def _export_path(self, cs: CollectionSession) -> str:
        # inside the session's checkpoint namespace but OUTSIDE the
        # level-stamp grammar ("xport" never parses as an int), so
        # ckpt_levels/ckpt_prune ignore it
        return os.path.join(cs.ckpt_dir, f"{cs.ckpt_prefix()}xport.npz")

    async def session_import(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Adopt a migrated (or orphaned) session's banked state.

        Two sources: ``{"path", "boot", "epoch"}`` names a
        ``session_export`` blob (live migration — the stamps must match
        the announced export, and a (boot, epoch) pair imports at most
        ONCE: double-applying a transfer would double-land its in-flight
        ``sub_id`` replays); ``{"level": N}`` names this session's own
        checkpoint-namespace blob (whole-host failover — the orphan's
        newest ingest checkpoint in the shared store, no stamps).

        Validate-before-mutate: a torn/corrupt blob, a wrong-collection
        stamp, a replayed stamp, or a torn ``ing_*`` tail refuses with
        the live state of BOTH hosts untouched.  Only ingest-form blobs
        import — crawl state rebuilds from the pools via ``window_load``,
        and the per-session secure plane is force re-keyed (fresh
        coin flip + base-OT against THIS pair's peer at the next
        data-plane verb), never carried across hosts."""
        cs = cs if cs is not None else self._default()
        req = req or {}
        if cs.ckpt_dir is None:
            raise RuntimeError("session_import: no checkpoint dir configured")
        stamp = None
        if req.get("path") is not None:
            path = str(req["path"])
            stamp = (str(req.get("boot", "")), int(req.get("epoch", 0)))
        else:
            path = cs.ckpt_path(int(req["level"]))
        if not os.path.exists(path):
            raise RuntimeError(f"session_import: no blob at {path}")
        try:
            with np.load(path) as npz:
                z = {k: npz[k] for k in npz.files}
        # np.load surfaces torn/partial writes as BadZipFile/ValueError/
        # EOFError depending on where the file was cut (same boundary as
        # tree_restore)
        except Exception as e:  # fhh-lint: disable=broad-except (corrupt-blob classification: every load failure maps to the same loud refusal; state is untouched)
            raise RuntimeError(
                f"session_import: corrupt or truncated blob at {path} "
                f"({type(e).__name__}: {e})"
            ) from e
        if "sess" in z and str(z["sess"]) != cs.key:
            raise RuntimeError(
                f"session_import: blob at {path} is stamped for "
                f"collection {str(z['sess'])!r}, not {cs.key!r}"
            )
        if stamp is not None:
            got = (str(z.get("xp_boot", "")), int(z.get("xp_epoch", 0)))
            if got != stamp:
                raise RuntimeError(
                    f"session_import: blob at {path} carries stamp {got}, "
                    f"not the announced export {stamp} (stale file?)"
                )
            if stamp in cs._import_seen:
                raise RuntimeError(
                    f"session_import: export {stamp} was already imported "
                    "— double-applying a transfer would double-land its "
                    "in-flight submissions"
                )
        if not ("ing_only" in z and bool(z["ing_only"])):
            raise RuntimeError(
                "session_import: only ingest-form blobs migrate (crawl "
                "state rebuilds from the pools via window_load); use "
                "add_keys + tree_restore for a crawl-level checkpoint"
            )
        # crawl-radix stamp (validate-before-mutate, both directions):
        # an exported k=2 session refuses to land in a k=1 session and
        # vice versa — the fused level grid and the sketch ratchet's
        # per-step absorption must match across the migration
        xp_radix = int(z["radix"]) if "radix" in z else 1
        if xp_radix != cs._radix:
            raise RuntimeError(
                f"session_import: blob at {path} was exported under "
                f"crawl_radix_bits={xp_radix}; this session runs "
                f"crawl_radix_bits={cs._radix}"
            )
        # validate the whole ing_* tail BEFORE any state mutates
        parsed = cs.ingest_validate(z, path)
        # -- all checks passed: mutate ------------------------------------
        if parsed is not None:
            cs.ingest_restore_apply(parsed)
            if taint_guard.enabled():
                # the reconstructed pool entries are the clients' key
                # SHARES (and in malicious mode their sketch material):
                # secret bytes this host never saw before the import —
                # register them so the obs sinks keep refusing them
                for pool in cs._ingest_pools.values():
                    for entry in pool.entries:
                        for leaf in entry:
                            taint_guard.register(
                                "CollectionSession._imported_pool_shares",
                                leaf,
                            )
        else:
            cs._ingest_pools.clear()  # an empty export imports as empty
        if stamp is not None:
            cs._import_seen.add(stamp)
        # force a fresh per-session plane handshake against THIS pair's
        # peer: OT endpoints and the coin flip never migrate across
        # hosts (epoch 0 = never keyed — _ensure_session_plane re-keys
        # lazily at the next data-plane verb)
        cs._ot = None
        cs._ot_snd = None
        cs._ot_rcv = None
        cs._sec_seed = None
        cs.plane_epoch = 0
        cs.obs.count("session_imports")
        obs.emit(
            "fleet.session_imported",
            server=self.server_id,
            collection=cs.key,
            windows=sorted(cs._ingest_pools),
            path=path,
        )
        return {"windows": sorted(cs._ingest_pools)}

    # -- resilience verbs (no reference analogue: the reference's only
    # recovery verb is reset, server.rs:64-69) ---------------------------

    async def status(self, _req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Cheap probe for the supervising leader: the boot id tells a
        reconnecting leader whether this is the same process (replay is
        safe) or a restart (state is gone — restore path), and the dedup
        counter lets recovery tests assert no verb double-applied.

        The crawl/ingest/mesh fields describe the CALLING session (so
        single-tenant probes read exactly as before); ``sessions`` is
        the multi-tenant rollup — every live collection's phase, level,
        queue depth, replay-dedup entries, and checkpoint levels, plus
        the tenant scheduler's stall-fill accounting."""
        cs = cs if cs is not None else self._default()
        # live device-memory sample (obs.devmem): the status probe IS
        # the operator's HBM tick — watermark/delta land on this
        # server's registry, scrape-visible and report-visible
        obsdevmem.sample(self.obs, phase="status")
        sess = self._sessions_status()
        # alert tick: session rules over the rows just built, registry
        # rules over everything live — fire-once per (rule, subject), so
        # repeated probes of a stalled tenant yield ONE alert event
        obsalerts.evaluate_sessions(
            sess["per_session"], source=f"server{self.server_id}"
        )
        obsalerts.evaluate_registries()
        return {
            "boot_id": self._boot_id,
            "collection": cs.key,
            # wall clock for the leader's trace clock-offset handshake
            # (obs.trace: NTP-style midpoint against the caller's
            # send/recv instants; piggybacked here and on __hello__)
            "clock": round(time.time(), 6),
            "has_keys": cs.keys is not None or bool(cs.keys_parts),
            "has_frontier": cs.frontier is not None,
            "dedup_hits": int(self.obs.counter_value("dedup_hits")),
            "plane_resets": int(self.obs.counter_value("plane_resets")),
            # numerically-ordered checkpoint levels on disk — the
            # supervisor's "latest checkpoint" source of truth (string
            # sorts would order l9 after l10 from level 10 on)
            "ckpt_levels": cs.ckpt_levels(),
            # streaming front-door health (pool occupancy per window,
            # unsealed queue depth, admit/shed/reject counters)
            "ingest": cs.ingest_status(),
            # multi-chip mesh health (None on a single-device server):
            # device/shard counts, per-shard client occupancy, and the
            # reduction/recovery instruments the run report rolls up
            "mesh": self._mesh_status(cs),
            # multi-tenant rollup (sessions.SessionTable + tenancy)
            "sessions": sess,
            # fleet identity + migration accounting (protocol/fleet.py):
            # which registered pair this server is half of, and how many
            # sessions moved through it
            "fleet": {
                "pair": os.environ.get("FHH_FLEET_PAIR", ""),
                "boot_id": self._boot_id,
                "session_exports": int(
                    cs.obs.counter_value("session_exports")
                ),
                "session_imports": int(
                    cs.obs.counter_value("session_imports")
                ),
                # placement signals in the shape FleetDirectory.note_load
                # consumes — the supervisor's probe loop forwards them
                "load": self._sched.fleet_load(),
            },
            # live SLO quantiles (obs.hist): per-level crawl latency,
            # per-verb RPC latency, seal-to-hitters — p50/p95/p99 from
            # the calling session's fixed-bucket histograms
            "slo": cs.obs.hists_summary(),
            # alert transitions (obs.alerts): every rule that fired in
            # this process, newest last — the supervisor's "is anything
            # wrong" answer without scraping /metrics
            "alerts": obsalerts.status_section(),
        }

    def _sessions_status(self) -> dict:  # fhh-race: atomic (read-only rollup over the session table in one event-loop slice; per-session reads are point-in-time probes for an operator, not protocol state)
        """The ``status.sessions`` section: one row per live collection
        plus the tenant scheduler's stall-fill accounting."""
        dedup_by: dict[str, int] = {}
        with guards.unguarded(
            "status rollup: point-in-time operator probe over the "
            "replay table (atomic contract on _sessions_status)"
        ):
            for sess in self._sessions.values():
                key = getattr(sess, "collection", DEFAULT_COLLECTION)
                dedup_by[key] = dedup_by.get(key, 0) + len(sess.cache)
            rows = {}
            for key, cs in self._table.items():
                sp = cs.obs.current_span()
                pools = list(cs._ingest_pools.values())
                rows[key] = {
                    "phase": sp.name if sp is not None else None,
                    "level": sp.level if sp is not None else None,
                    "queue_depth": sum(
                        p.keys for p in pools if not p.sealed
                    ),
                    "dedup_entries": dedup_by.get(key, 0),
                    "ckpt_levels": cs.ckpt_levels(),
                    "has_frontier": cs.frontier is not None,
                    "plane_epoch": cs.plane_epoch,
                    # seconds since this session last COMPLETED a verb:
                    # a wedged tenant shows a growing gap here while the
                    # process-wide heartbeat only names the active one
                    "last_progress_s": round(
                        max(0.0, time.monotonic() - cs.last_progress), 3
                    ),
                }
        return {
            "count": len(self._table),
            "scheduler": self._sched.stats(),
            "per_session": rows,
        }

    def _mesh_status(self, cs: CollectionSession) -> dict | None:
        if cs._mesh is None:
            return None
        return {
            "data_devices": cs._mesh.n_devices,
            "data_shards": cs._mesh.shards,
            "shard_clients": cs._mesh.occupancy(),
            "ici_reduce_seconds": round(
                cs.obs.timer_seconds("ici_reduce"), 6
            ),
            "reshards": int(cs.obs.counter_value("mesh_reshards")),
            "faults": int(cs.obs.counter_value("mesh_faults")),
            # row-sharded secure kernel stage (parallel/kernel_shard.py):
            # the last level's active shard count / the crawl's deepest
            # (None before any secure crawl).  The LAYOUT signal is the
            # gauge + the kernel_gathers counter (exactly the levels
            # that ran the degraded gather path — 0 of them on a fully
            # sharded crawl); kernel_gather_seconds is that gather's
            # DISPATCH time (the transfer itself completes lazily under
            # the level's later fetch), a supplement, not the detector
            "kernel_shards": cs.obs.gauge_value("kernel_shards"),
            "kernel_shards_max": cs.obs.gauge_max("kernel_shards"),
            "kernel_gathers": int(cs.obs.counter_value("kernel_gathers")),
            "kernel_gather_seconds": round(
                cs.obs.timer_seconds("kernel_gather"), 6
            ),
            # malicious-secure sketch verify layout (parallel/
            # sketch_shard.py): the last verify's active shard count
            # (None before any sketch level; 1 = the single fused
            # program / the meshless path)
            "sketch_shards": cs.obs.gauge_value("sketch_shards"),
        }

    async def tree_checkpoint(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Persist the crawl state AFTER the given level completed:
        frontier eval states + node liveness + client liveness + the
        state layout flag (planar Pallas vs interleaved XLA — a restore
        under the other engine converts).  Keys are NOT in the blob (the
        leader re-uploads them on a restart — they are the bulk of the
        bytes and the leader already holds them).  Atomic tmp+rename so
        a crash mid-write never corrupts the previous checkpoint.

        Session-namespaced: each collection writes into its own filename
        namespace (sessions.CollectionSession.ckpt_prefix — the default
        session keeps the legacy names) and every blob is STAMPED with
        its collection key (``sess`` field), so a blob renamed across
        namespaces refuses to restore instead of resurrecting another
        tenant's tree.

        Malicious (sketch) mode checkpoints too: the blob carries the
        frontier-following sketch DPF states, the stored (yet-unopened)
        pair shares, the committed ratchet root, and the transcript
        digest — everything a re-run needs to replay each level's
        challenge bit-identically (see ``sketch.py``'s ratchet note).

        Streaming ingest pools ride EVERY checkpoint (``ing_*`` fields):
        a server killed mid-window restores its admitted pools — entry
        slots, recorded per-``sub_id`` verdicts, quota ledgers, and the
        reservoir sampler's RNG state — so recovery neither loses nor
        double-counts admitted keys and the shed stream resumes
        seed-identically.  A server with pools but no frontier (between
        windows) may checkpoint too: the blob is then ingest-only."""
        cs = cs if cs is not None else self._default()
        if cs.ckpt_dir is None:
            raise RuntimeError(
                "tree_checkpoint: no checkpoint dir configured "
                "(start the server with FHH_CKPT_DIR set)"
            )
        ing_only = bool((req or {}).get("ingest_only"))
        if cs.frontier is None and not cs._ingest_pools:
            raise RuntimeError("tree_checkpoint before tree_init")
        if ing_only and not cs._ingest_pools:
            raise RuntimeError("tree_checkpoint: no ingest pools to persist")
        level = int(req["level"])
        if cs.frontier is not None and not ing_only:
            st = cs.frontier.states
            # ONE stacked fetch for the whole blob (device_get of the
            # pytree), not one sync per plane — through a remote-chip
            # tunnel each fetch is a full round trip
            fetch = {
                "seed": st.seed,
                "bit": st.bit,
                "y_bit": st.y_bit,
                "alive": cs.frontier.alive,
            }
            if cs._sketch is not None:
                fetch["sk_state_seed"] = cs._sketch_states.seed
                fetch["sk_state_t"] = cs._sketch_states.t
                if cs._sketch_pairs is not None:
                    # one entry per fused bit level (radix-2^k crawls
                    # store several); indexed keys keep the npz flat
                    for i, (p, _, _) in enumerate(cs._sketch_pairs):
                        fetch[f"sk_pairs_{i}"] = p
            blob = jax.device_get(fetch)
            blob["alive_keys"] = np.asarray(cs.alive_keys)
            blob["planar"] = np.bool_(cs.planar())
            blob["keys_fp"] = cs.keys_fp()
        else:
            blob = {"ing_only": np.bool_(True)}
        blob["level"] = np.int64(level)
        # the session stamp: restore validates it against the restoring
        # session BEFORE any state mutates (satellite of the PR-4
        # validate-before-mutate contract)
        blob["sess"] = np.str_(cs.key)
        # crawl-radix stamp: a blob written under k=2 holds a frontier at
        # a depth grid (0, 2, 4, …) a k=1 session never visits — restore
        # refuses a mismatch before any state mutates
        blob["radix"] = np.int64(cs._radix)
        cs.ingest_ckpt_fields(blob)
        if cs._sketch is not None:
            blob["sk_pids"] = np.asarray(cs._sketch_pids)
            blob["sk_depth"] = np.int64(cs._sketch_depth)
            blob["sk_root"] = np.asarray(cs._sketch_root, np.uint32)
            blob["sk_digest"] = np.frombuffer(
                cs._ratchet_digest, np.uint8
            )
            if cs._sketch_pairs is not None:
                blob["sk_pairs_depth"] = np.asarray(
                    [dep for (_, dep, _) in cs._sketch_pairs], np.int64
                )
                blob["sk_pairs_last"] = np.asarray(
                    [f is F255 for (_, _, f) in cs._sketch_pairs], bool
                )
        path = cs.ckpt_path(level)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **blob)
        os.replace(tmp, path)
        cs.ckpt_prune()
        cs.obs.count("checkpoint_writes", level=level)
        obs.emit(
            "resilience.server_checkpoint",
            server=self.server_id,
            collection=cs.key,
            level=level,
            path=path,
        )
        return {"level": level}

    async def tree_restore(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Reload the :meth:`tree_checkpoint` for the level the leader
        names; returns the completed level so the leader re-runs from
        ``level + 1``.  Requires keys: either still held (transient
        fault, same process) or re-uploaded via ``add_keys`` after a
        restart — and refuses a blob written under a different key
        batch.

        Every validation runs BEFORE any state mutates: a mismatched
        fingerprint, a truncated/corrupt npz, a blob from a deeper
        level than this key batch's tree, or a blob STAMPED for a
        different collection session must fail loudly and leave the
        server's live state exactly as it was.

        Streaming ingest pools restore alongside (``ing_*`` fields, same
        validate-before-mutate contract); an ingest-ONLY blob (written
        between windows, no frontier) restores just the pools and leaves
        the crawl state empty — ``window_load`` rebuilds it."""
        cs = cs if cs is not None else self._default()
        if cs.ckpt_dir is None:
            raise RuntimeError("tree_restore: no checkpoint dir configured")
        want_level = int(req["level"])
        path = cs.ckpt_path(want_level)
        if not os.path.exists(path):
            raise RuntimeError(f"tree_restore: no checkpoint at {path}")
        try:
            with np.load(path) as npz:
                z = {k: npz[k] for k in npz.files}
        # np.load surfaces torn/partial writes as BadZipFile/ValueError/
        # EOFError depending on where the file was cut; all of them mean
        # the same thing at this boundary
        except Exception as e:  # fhh-lint: disable=broad-except (corrupt-blob classification: every load failure maps to the same loud refusal; state is untouched)
            raise RuntimeError(
                f"tree_restore: corrupt or truncated checkpoint at {path} "
                f"({type(e).__name__}: {e})"
            ) from e
        if "sess" in z and str(z["sess"]) != cs.key:
            # session-namespace stamp: a blob renamed (or copied) across
            # collection namespaces must refuse — restoring another
            # tenant's tree into this session would silently serve its
            # heavy hitters under the wrong collection
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} is stamped for "
                f"collection {str(z['sess'])!r}, not {cs.key!r} "
                "(renamed across session namespaces?)"
            )
        # crawl-radix stamp (validate-before-mutate, both directions): a
        # k=2 blob into a k=1 session — or vice versa — refuses with live
        # state untouched (blobs predating the stamp are radix-1 crawls)
        saved_radix = int(z["radix"]) if "radix" in z else 1
        if saved_radix != cs._radix:
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} was written under "
                f"crawl_radix_bits={saved_radix}; this session runs "
                f"crawl_radix_bits={cs._radix} — its level grid never "
                "visits the blob's frontier depth"
            )
        if "ing_only" in z and bool(z["ing_only"]):
            # ingest-only blob: pools back, crawl state untouched-empty.
            # No key requirement — the keys ARE the pools.
            if int(z.get("level", want_level)) != want_level:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is stamped level "
                    f"{want_level} but records level {int(z['level'])} "
                    "(renamed or tampered file)"
                )
            parsed = cs.ingest_validate(z, path)
            if parsed is None:
                raise RuntimeError(
                    f"tree_restore: ingest-only checkpoint at {path} "
                    "carries no ingest pools (truncated write?)"
                )
            cs.ingest_restore_apply(parsed)
            cs.obs.count("checkpoint_restores", level=want_level)
            obs.emit(
                "resilience.server_restore",
                server=self.server_id,
                collection=cs.key,
                level=want_level,
                ingest_only=True,
            )
            return {"level": want_level}
        if cs.keys is None:
            if not cs.keys_parts:
                raise RuntimeError("tree_restore before add_keys")
            cs.concat_keys()
        required = {"seed", "bit", "y_bit", "alive", "alive_keys", "level",
                    "planar", "keys_fp"}
        missing = required - set(z)
        if missing:
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} is missing fields "
                f"{sorted(missing)} (truncated write?)"
            )
        if not np.array_equal(z["keys_fp"], cs.keys_fp()):
            raise RuntimeError(
                "tree_restore: checkpoint was written under a different "
                "key batch — re-upload the original keys"
            )
        level = int(z["level"])
        L = cs.keys.cw_seed.shape[-2]
        if level != want_level:
            raise RuntimeError(
                f"tree_restore: checkpoint at {path} is stamped level "
                f"{want_level} but records level {level} (renamed or "
                "tampered file)"
            )
        if level >= L - 1:
            raise RuntimeError(
                f"tree_restore: checkpoint level {level} is deeper than "
                f"this key batch's tree (data_len={L}) — wrong collection"
            )
        n = cs.keys.cw_seed.shape[0]
        # fhh-lint: disable=host-sync-in-hot-loop (restore path: host npz entry, once per recovery)
        alive_keys = np.asarray(z["alive_keys"])
        if alive_keys.shape[0] != n:
            raise RuntimeError(
                "tree_restore: checkpoint client count != key batch"
            )
        has_sketch = bool(cs._sketch_parts) or cs._sketch is not None
        if has_sketch != ("sk_root" in z):
            raise RuntimeError(
                "tree_restore: sketch material mismatch — the checkpoint "
                + ("lacks" if has_sketch else "carries")
                + " sketch state relative to the uploaded keys"
            )
        if has_sketch:
            # the validate-before-mutate contract covers the sketch
            # fields too: a blob with sk_root but a torn/tampered tail
            # must refuse here, not KeyError after the frontier mutated
            sk_req = {"sk_state_seed", "sk_state_t", "sk_pids", "sk_depth",
                      "sk_digest"}
            if "sk_pairs" in z:
                sk_req |= {"sk_pairs_depth", "sk_pairs_last"}
            sk_missing = sk_req - set(z)
            if sk_missing:
                raise RuntimeError(
                    f"tree_restore: checkpoint at {path} is missing sketch "
                    f"fields {sorted(sk_missing)} (truncated write?)"
                )
        # ingest pools validate with everything else (a torn ing_* tail
        # refuses before ANY state mutates); None = pre-streaming blob
        parsed_ing = cs.ingest_validate(z, path)
        # -- all checks passed: mutate ------------------------------------
        states = EvalState(
            seed=jax.device_put(z["seed"]),
            bit=jax.device_put(z["bit"]),
            y_bit=jax.device_put(z["y_bit"]),
        )
        saved_planar, planar = bool(z["planar"]), cs.planar()
        if saved_planar != planar:
            states = (
                collect.to_interleaved(states)
                if saved_planar
                else collect.to_planar(states)
            )
        cs.alive_keys = alive_keys
        cs.frontier = collect.Frontier(
            states=states, alive=jax.device_put(z["alive"])
        )
        if cs._mesh is not None:
            # re-shard from the host-side blob: the frontier lands
            # client-axis-sharded across whatever local devices are
            # live — this is the device-loss recovery primitive (a lost
            # device is re-covered by re-placement, not a server restart)
            cs.frontier = cs._mesh.shard_frontier(cs.frontier)
        cs._children = None
        cs._last_shares = None
        cs._shard_children.clear()
        cs._shard_last.clear()
        cs._shard_level = None
        cs._expand_ready.clear()
        if has_sketch:
            if cs._sketch is None:
                cs.concat_sketch()
            cs._sketch_states = dpf.DpfEvalState(
                seed=jax.device_put(z["sk_state_seed"]),
                t=jax.device_put(z["sk_state_t"]),
            )
            # fhh-lint: disable=host-sync-in-hot-loop (restore path: host npz entries, once per recovery)
            cs._sketch_pids = np.asarray(z["sk_pids"])
            cs._sketch_depth = int(z["sk_depth"])
            # fhh-lint: disable=host-sync-in-hot-loop (as above)
            cs._sketch_root = np.asarray(z["sk_root"], np.uint32).copy()
            # fhh-lint: disable=host-sync-in-hot-loop (as above)
            cs._ratchet_digest = np.asarray(
                z["sk_digest"], np.uint8
            ).tobytes()
            if "sk_pairs_0" in z:
                # fhh-lint: disable=host-sync-in-hot-loop (as above)
                depths = np.atleast_1d(np.asarray(z["sk_pairs_depth"]))
                # fhh-lint: disable=host-sync-in-hot-loop (as above)
                lasts = np.atleast_1d(np.asarray(z["sk_pairs_last"]))
                cs._sketch_pairs = [
                    (
                        jax.device_put(z[f"sk_pairs_{i}"]),
                        int(depths[i]),
                        F255 if bool(lasts[i]) else FE62,
                    )
                    for i in range(len(depths))
                ]
            elif "sk_pairs" in z:
                # pre-radix blob form: a single stored pair
                cs._sketch_pairs = [(
                    jax.device_put(z["sk_pairs"]),
                    int(z["sk_pairs_depth"]),
                    F255 if bool(z["sk_pairs_last"]) else FE62,
                )]
            else:
                cs._sketch_pairs = None
        if parsed_ing is not None:
            cs.ingest_restore_apply(parsed_ing)
        cs.obs.count("checkpoint_restores", level=level)
        obs.emit(
            "resilience.server_restore",
            server=self.server_id,
            collection=cs.key,
            level=level,
        )
        return {"level": level}

    async def plane_reset(self, _req, cs: CollectionSession = None) -> bool:  # fhh-race: holds=_verb_lock (dispatched by _dispatch under the SERVER infra lock — the plane is shared infrastructure across sessions; sanitizer-validated)
        """Re-establish the server↔server data plane after a peer loss.

        Only the DIALER (server 0) acts: it drops the dead transport and
        redials under the shared backoff policy; the listener's side is
        re-accepted automatically (``_on_peer`` on its still-bound
        listener).  The plane is SHARED infrastructure: a reset bumps the
        PlaneMux epoch, so EVERY session re-keys its channel (fresh coin
        flip + base-OT) lazily at its next data-plane verb
        (``_ensure_session_plane``) — a tenant that was mid-level fails
        its wedged exchange loudly and its own supervisor re-runs the
        level, exactly the per-tenant recovery story."""
        if self.server_id != 0:
            return True  # listener: re-accept + re-key is automatic
        if self._peer_writer is not None and not self._peer_writer.is_closing():
            self._peer_writer.close()
        await self._dial_peer()
        self.obs.count("plane_resets")
        obs.emit("resilience.plane_reset", server=self.server_id)
        return True

    async def plane_break(self, _req, cs: CollectionSession = None) -> bool:
        """Forcibly close this server's end of the peer data plane WITHOUT
        re-establishing it — the pipelined leader's quiesce primitive.  A
        faulted pipeline can leave a verb on EITHER server blocked in a
        ``_swap`` recv while holding its session's verb lock (its span
        reached only one server, so the peer's matching frame never
        comes); this verb dispatches OUTSIDE the verb locks (see
        ``_dispatch``) precisely so it can break that wedge: the close
        kills the mux pump, every channel's blocked recv raises, the
        wedged verbs error out and release their locks, and the leader's
        subsequent ``plane_reset`` re-keys the plane cleanly."""
        w = self._peer_writer
        if w is not None and not w.is_closing():
            w.close()
        self.obs.count("plane_breaks")
        obs.emit("resilience.plane_break", server=self.server_id)
        return True

    async def warmup(self, req, cs: CollectionSession | None = None) -> dict:  # fhh-race: holds=_verb_lock (dispatched only by _dispatch, which holds the session's verb lock; sanitizer-validated)
        """Pre-compile the per-``f_bucket`` crawl programs so bucket
        recompiles stop billing into measured (or production) crawl time:
        for every requested bucket (and every shard-span size it implies
        under ``cfg.crawl_shard_nodes``), run the expand stage and — in
        secure mode — the whole 2PC kernel chain against a THROWAWAY
        in-process OT session with the real key batch's shapes.  Touches
        no protocol state: the live OT sessions, frontier, and data plane
        are never involved, so warmup can run any time after ``add_keys``
        (the leader calls it right after ``tree_init``).

        Multi-tenant: the compiled-program ladder is PROCESS-shared
        (tenancy.WarmLadder) — a shape any session already warmed is
        skipped (``ladder_hits`` in the response), so a new collection
        on a warmed shape pays zero fresh compiles AND zero redundant
        warm executions.  Returns the number of (bucket, span) shapes
        warmed plus the ladder hits."""
        cs = cs if cs is not None else self._default()
        if cs.keys is None:
            if not cs.keys_parts:
                raise RuntimeError("warmup before add_keys")
            cs.concat_keys()
        buckets = sorted(
            {int(b) for b in (req or {}).get("f_buckets", []) if int(b) > 0}
        )
        # the requesting leader may name the equality-test path (its own
        # config's, possibly overriding this server's — the same per-req
        # override the crawl verbs honor) and ask for span-sized shapes
        # (a leader that will crawl with secure_whole_level=False)
        ot_path = (req or {}).get("ot_path") or self.cfg.ot_path
        want_spans = bool((req or {}).get("secure_spans"))
        # the leader names its shard layout so a config skew (a leader
        # that believes this server runs k-way sharded when it does not,
        # or vice versa) surfaces at warmup time instead of as mystery
        # recompiles on the measured clock; the server's own mesh is
        # authoritative — warmup always compiles the programs the LIVE
        # crawl will dispatch
        want_devices = (req or {}).get("data_shards")
        have_shards = 1 if cs._mesh is None else cs._mesh.shards
        if want_devices is not None and int(want_devices) > 0:
            # the leader names a DEVICE budget; resolve it exactly like
            # this server resolved its own (visible-device cap, then
            # the largest divisor of the bound client batch) so
            # identically-configured pairs never warn — only real
            # config skew does
            want_shards = smesh._largest_divisor_leq(
                cs.keys.cw_seed.shape[0],
                smesh.resolve_data_devices(int(want_devices)),
            )
            if want_shards != have_shards:
                obs.emit(
                    "warmup.shard_mismatch",
                    severity="warn",
                    server=self.server_id,
                    leader_data_shards=want_shards,
                    server_data_shards=have_shards,
                )
        L = cs.keys.cw_seed.shape[-2]
        shapes = 0
        ladder_hits = 0
        with cs.obs.span("warmup"):
            for b in buckets:
                if (
                    self.cfg.secure_exchange
                    and self.cfg.secure_whole_level
                    and not want_spans
                ):
                    # whole-level secure crawls never shard the GC/OT
                    # batch — warming span-sized programs would compile
                    # shapes no crawl dispatches (and break the
                    # warmed-crawl-compiles-nothing contract's economy)
                    sizes = set()
                else:
                    sizes = {
                        hi - lo
                        for lo, hi in collect.shard_spans(
                            b, self.cfg.crawl_shard_nodes
                        )
                    }
                for fb in sorted(sizes | {b}):
                    if self._warm_bucket(cs, fb, L, ot_path):
                        shapes += 1
                    else:
                        ladder_hits += 1
                    # yield between compiles: each can take seconds, and
                    # the control socket must keep answering keepalives
                    await asyncio.sleep(0)
        cs.obs.count("warmup_shapes", shapes)
        if ladder_hits:
            cs.obs.count("warmup_ladder_hits", ladder_hits)
        # the warmup ladder is now the compile baseline: every fresh XLA
        # compile from here on is a NAMED, counted anomaly
        # (devmem.fresh_compiles_post_warmup -> recompile_after_warmup
        # alert), and the post-warmup HBM watermark is the crawl's floor
        obsdevmem.note_warmup_done()
        obsdevmem.sample(cs.obs, phase="warmup")
        return {"shapes": shapes, "ladder_hits": ladder_hits}

    def _warm_key(self, cs: CollectionSession, fb: int, L: int,
                  ot_path: str | None) -> tuple:
        """Everything that feeds the identity of the compiled programs a
        (bucket ``fb``, this session's key batch) crawl dispatches —
        the WarmLadder's skip key.  Two sessions with the same batch
        shape/config hit the same jit executables, so re-warming for
        the second is pure waste."""
        mesh_shards = 0 if cs._mesh is None else cs._mesh.shards
        return (
            "warm",
            cs.keys.cw_seed.shape,  # client batch + dims + depth
            fb,
            L,
            bool(self.cfg.secure_exchange),
            bool(self.cfg.secure_whole_level),
            str(ot_path or self.cfg.ot_path),
            mesh_shards,
            int(self.cfg.secure_kernel_shards),
            cs.planar(),
            # radix-2^k fusion: k changes every compiled shape downstream
            # of expand (packed bit layout, S' = 2·d·k equality width,
            # C = 2^(d·k) count columns, fused sketch advance)
            int(cs._radix),
            # malicious lane: the sketch verify ladder compiles its own
            # fused per-bucket programs, sharded by the sketch plan
            bool(cs._sketch_parts) or cs._sketch is not None,
            int(self.cfg.sketch_shards),
        )

    def _warm_bucket(self, cs: CollectionSession, fb: int, L: int,
                     ot_path: str | None = None) -> bool:
        """Compile (by running on throwaway inputs) every device program
        a crawl at frontier bucket ``fb`` will hit: expand with and
        without children, the trusted count reduction, and in secure
        mode the OT-extension + equality + b2a + share-sum chain for both
        FE62 (inner levels) and F255 (the leaf level).  Under the
        multi-chip mesh every stage warms with the SHARDED layout the
        live crawl dispatches.  Returns False when the process-level
        WarmLadder says some session already warmed this exact shape
        (the compiled programs are in the process jit cache — a new
        tenant on a warmed shape pays zero fresh compiles)."""
        ladder_key = self._warm_key(cs, fb, L, ot_path)
        if tenancy.warmed(ladder_key):
            return False
        mesh = cs._mesh
        if mesh is not None:
            fr = mesh.shard_frontier(
                collect.tree_init(cs.keys, fb, planar=False)
            )
        else:
            fr = collect.tree_init(cs.keys, fb)
        d = cs.keys.cw_seed.shape[1]
        # radix-2^k fusion: the live crawl dispatches at most two fused
        # shapes — (r = k, inner level, FE62, with children) and
        # (r = tail, leaf-bearing level, F255, no children), where the
        # tail radix is L - k·⌊(L-1)/k⌋ (== k when k divides L).  At
        # k = 1 this reduces exactly to the historical (False, True)
        # `lasts` ladder, via the radix==1 delegation in collect/secure.
        rdx = cs._radix
        base_last = rdx * ((L - 1) // rdx)
        steps = (
            [(min(rdx, L), True)]
            if base_last == 0
            else [(rdx, False), (L - base_last, True)]
        )
        for r, last in steps:
            level = base_last if last else 0
            packed, _ = collect.expand_share_bits_radix(
                cs.keys, fr, level, r, want_children=not last,
                use_pallas=False if mesh is not None else None,
            )
            if self.cfg.secure_exchange:
                N = cs.keys.cw_seed.shape[0]
                ks = (
                    mesh.kernel_bind(
                        fb * (1 << (d * r)) * N, 2 * d * r,
                        self.cfg.secure_kernel_shards,
                    )
                    if mesh is not None
                    else None
                )
                if ks is not None:
                    # the live crawl runs this shape ROW-SHARDED: warm
                    # the sharded flat/extension/kernel/open/psum chain
                    # (both roles, both garbling signs) — warming the
                    # gathered twins would leave every live program cold
                    secure.warm_level_kernels_sharded(
                        ks, packed, d, fb, N, F255 if last else FE62,
                        path=ot_path or self.cfg.ot_path, radix=r,
                    )
                    continue
                secure.warm_level_kernels(
                    # same pre-kernel gather as the live expand stage
                    # (_do_expand) — warm and live must dispatch the
                    # same single-device 2PC programs
                    packed if mesh is None else mesh.gather(packed),
                    d, F255 if last else FE62,
                    path=ot_path or self.cfg.ot_path,
                    share_sums=mesh.node_share_sums if mesh is not None
                    else None,
                    radix=r,
                )
            else:
                masks = collect.pattern_masks_radix(d, r)
                alive = (
                    cs.alive_keys
                    if cs.alive_keys is not None
                    else np.ones(cs.keys.cw_seed.shape[0], bool)
                )
                if mesh is not None:
                    # peer rows arrive as host numpy on the live path
                    peer = np.asarray(packed)  # fhh-lint: disable=chunked-device-readback,host-sync-in-hot-loop (warmup only: deliberately mirrors the live wire round trip, off the measured clock)
                    jax.block_until_ready(
                        mesh.counts_by_pattern(
                            packed, peer, masks, alive, fr.alive
                        )
                    )
                else:
                    jax.block_until_ready(
                        collect.counts_by_pattern(
                            packed, packed, masks, alive, fr.alive
                        )
                    )
        if cs._sketch_parts or cs._sketch is not None:
            # malicious lane: compile the fused sketch-verify ladder at
            # this bucket rung (and, once per batch, the level-0
            # full-width check) so a warmed malicious crawl dispatches
            # zero fresh programs
            self._warm_sketch(cs, fb, L)
        tenancy.mark_warmed(ladder_key)
        return True

    def _warm_sketch(self, cs: CollectionSession, fb: int, L: int) -> None:
        """Compile every device program the malicious verify lane
        dispatches at frontier bucket ``fb``: the frontier-following
        advance (``advance_sketch``'s eval_bit + liveness gating at the
        bucket shape, both fields), the level-0 full-width check (root
        eval_bit at the batch shape + the m = 2 verify chain), and the
        fused sharded cor/out/verdict chain at m = ``fb`` for FE62
        (inner levels) and F255 (the leaf) — all on throwaway inputs,
        never the live sketch state, with the wire arrays
        round-tripping through host numpy exactly like the live path
        (sketch_shard.warm_verify)."""
        if cs._sketch is None:
            cs.concat_sketch()
        k = cs._sketch.key
        n, d = k.root_seed.shape[0], k.root_seed.shape[1]
        ss = self._sketch_bind(cs, n, d)
        idx = bool(self.server_id)
        # level-0 full-width check: root states + both children per dim
        root = dpf.eval_init(k)
        st0 = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (1,) + a.shape), root
        )
        last0 = L == 1
        fld0 = F255 if last0 else FE62
        cw0 = dpf.level_cw(k, 0)
        cwv0 = k.cw_val[..., 0, :] if not last0 else k.cw_val_last
        st_r = jax.tree.map(lambda a: a[0], st0)
        sides = [
            dpf.eval_bit(
                cw0, st_r, jnp.full((n, d), c), cwv0, k.key_idx, fld0,
                sketchmod.LANES,
            )[1]
            for c in (False, True)
        ]
        jax.block_until_ready(jnp.stack(sides))
        sketch_shard.warm_verify(ss, fld0, 2, n, d, idx)
        if L == 1:
            return  # data_len=1: the level-0 full check IS the leaf check
        # frontier advance + per-rung verify, both fields (FE62 inner
        # levels via tree_prune, F255 leaf via tree_prune_last)
        parent = jnp.zeros(fb, jnp.int32)
        direction = jnp.zeros((fb, 1, d), bool)
        for last in (False, True):
            fld = F255 if last else FE62
            lvl = L - 1 if last else 0
            cw = tuple(a[None] for a in dpf.level_cw(k, lvl))
            cwv = (k.cw_val_last if last else k.cw_val[..., lvl, :])[None]
            st = jax.tree.map(lambda a: a[parent], st0)
            _, pair = dpf.eval_bit(
                cw, st, direction, cwv, k.key_idx[None], fld,
                sketchmod.LANES,
            )
            gate = jnp.ones((fb, 1, d) + (1,) * (pair.ndim - 3), bool)
            jax.block_until_ready(jnp.where(gate, pair, 0))
            sketch_shard.warm_verify(ss, fld, fb, n, d, idx)

    # -- wiring ----------------------------------------------------------

    _VERBS = (
        "reset",
        "add_keys",
        "tree_init",
        "tree_crawl",
        "tree_crawl_last",
        "tree_prune",
        "tree_prune_last",
        "final_shares",
        "sketch_verify",  # the TreeSketchFrontier* verbs' live successor
        # streaming ingest front door (ROADMAP "Streaming ingestion")
        "submit_keys",
        "window_seal",
        "window_load",
        # resilience verbs (no reference analogue)
        "status",
        "tree_checkpoint",
        "tree_restore",
        "plane_reset",
        "plane_break",  # pipelined-crawl quiesce (unlocked dispatch)
        "warmup",  # per-f_bucket compile warmup (no protocol state)
        # fleet migration/failover (protocol/fleet.py)
        "session_export",
        "session_import",
    )

    # verbs that run under the SERVER infra lock instead of the calling
    # session's: the peer data plane is shared across sessions
    _SERVER_VERBS = ("plane_reset",)

    def _bind_session(self, req) -> _Session | None:  # fhh-race: atomic (serve-loop session table: create-or-attach + eviction never suspends; all connections share one event loop)
        """Create-or-attach the leader session named in a ``__hello__``.
        Sessions are bounded (oldest-idle evicted) so reconnecting leaders
        with fresh session ids cannot grow server memory without bound."""
        sid = (req or {}).get("session")
        if sid is None:
            return None
        sess = self._sessions.get(sid)
        if sess is None:
            while len(self._sessions) >= _SESSION_CAP:
                oldest = min(
                    self._sessions, key=lambda k: self._sessions[k].last_seen
                )
                del self._sessions[oldest]
            sess = self._sessions[sid] = _Session()
        epoch = int((req or {}).get("epoch", 0))
        if epoch > 1:  # epoch 1 is the first connect, not a recovery
            self.obs.count("session_reconnects")
            obs.emit(
                "resilience.session_reconnect",
                server=self.server_id,
                epoch=epoch,
            )
        sess.epoch = epoch
        sess.last_seen = time.monotonic()
        return sess

    async def _dispatch(self, sess: _Session | None,
                        cs: CollectionSession | None, req_id, verb, req):
        """Run one verb AT MOST ONCE per (session, req_id): replays of a
        finished verb answer from the bounded response cache; replays of a
        verb still executing await the same execution.  Errors are
        responses too — a deterministic rejection must replay as the same
        rejection, not as a second execution attempt.

        ``cs`` is the collection session the connection bound at hello
        (None = a legacy client that never said hello: the DEFAULT
        session).  Per-collection verbs serialize on the SESSION's verb
        lock — two collections' verbs interleave on the event loop,
        which is the whole multi-tenant point — while plane verbs take
        the server infra lock (the plane is shared)."""
        self.obs.count("verb_requests")  # denominator of the dedup rate
        if cs is None:
            with guards.unguarded(
                "serve-loop session bind: event-loop-atomic by the "
                "fhh-race atomic contract on SessionTable.get"
            ):
                cs = self._table.get()
        cs.last_used = time.monotonic()
        if sess is not None:
            sess.last_seen = time.monotonic()
            if req_id in sess.cache:
                self.obs.count("dedup_hits")
                obs.emit(
                    "resilience.replay",
                    severity="debug",
                    server=self.server_id,
                    verb=verb,
                    req_id=req_id,
                )
                sess.cache.move_to_end(req_id)
                return sess.cache[req_id]
            live = sess.inflight.get(req_id)
            if live is not None:
                self.obs.count("dedup_hits")
                return await asyncio.shield(live)
            done = sess.inflight[req_id] = (
                asyncio.get_event_loop().create_future()
            )
        # distributed trace context: the request dict carries the
        # leader's {"t": trace_id, "s": span_id} (stamped once per
        # CollectorClient.call and replayed VERBATIM with the req_id),
        # so every span the verb opens below records as a child of the
        # leader's call.  Replays never reach this point for an
        # already-executed verb (the dedup cache answered above), so a
        # (trace_id, span_id) records exactly once per execution.
        ttok = (
            obstrace.activate((req or {}).get("trace"))
            if obstrace.enabled() and isinstance(req, dict)
            else None
        )
        t_verb = time.monotonic()
        try:
            try:
                # verb span: arrival-to-response on the session's
                # registry — the trace parent of the phase spans inside,
                # and (via the rpc:{verb} histogram below) the per-verb
                # RPC-latency SLO.  An exception unwinding through it
                # (a severed data plane mid-exchange) marks the trace
                # span error=true instead of leaving it dangling.
                # status is exempt: its sessions rollup reads every
                # session's CURRENT span as "the phase", and a probe
                # must not report itself.
                span_ctx = (
                    contextlib.nullcontext() if verb == "status"
                    # fhh-lint: disable=span-discipline (bound to a name only so status can swap in a nullcontext; the `with` on the next line enters/exits it normally)
                    else cs.obs.span(f"verb:{verb}")
                )
                with span_ctx:
                    if verb in ("add_keys", "submit_keys", "plane_break"):
                        # add_keys: append-only, no awaits -> atomic;
                        # submit_keys MUST bypass the lock so ingest
                        # keeps flowing while a windowed crawl holds it
                        # (that concurrency is the whole point of the
                        # front door) — its one suspension (executor
                        # admission) re-validates before mutating.
                        # plane_break MUST bypass it too: it exists to
                        # break a verb wedged on the data plane while
                        # HOLDING the lock (pipelined quiesce) — behind
                        # the lock it could never run.
                        with guards.unguarded(
                            "unlocked fast-path verb: add_keys is "
                            "event-loop-atomic by its fhh-race contract; "
                            "submit_keys re-validates after its one "
                            "suspension (executor admission)"
                        ):
                            resp = await getattr(self, verb)(req, cs)
                    elif verb in self._SERVER_VERBS:
                        # shared-plane verbs serialize on the SERVER
                        # lock: two tenants' concurrent plane_resets
                        # must not interleave redials
                        async with self._verb_lock:
                            resp = await getattr(self, verb)(req, cs)
                    else:
                        # frame-arrival expand stage: overlap a sharded
                        # crawl's device work with the span currently
                        # holding the lock
                        with guards.unguarded(
                            "frame-arrival prefetch: event-loop-atomic "
                            "by the fhh-race atomic contract on "
                            "_maybe_pre_expand"
                        ):
                            self._maybe_pre_expand(cs, verb, req)
                        async with cs._verb_lock:
                            resp = await getattr(self, verb)(req, cs)
            # fhh-lint: disable=broad-except (RPC boundary: EVERY failure
            # mode must surface to the caller as an error response — a
            # narrowed list would hang the leader on the first unlisted one)
            except Exception as e:
                obs.emit(
                    "verb.error", severity="warn", server=self.server_id,
                    verb=verb, error=f"{type(e).__name__}: {e}",
                )
                resp = {"__error__": f"{type(e).__name__}: {e}"}
            except asyncio.CancelledError:
                # drain-path cancellation: release any replay waiting on
                # this execution, then propagate
                if sess is not None:
                    sess.inflight.pop(req_id, None)
                    if not done.done():
                        done.cancel()
                raise
        finally:
            obstrace.deactivate(ttok)
        # per-verb RPC latency histogram (SLO surface: status.slo +
        # the run report's slo.verbs) and the per-session heartbeat-gap
        # instrument: last_progress marks verb COMPLETION — a wedged
        # tenant keeps bumping last_used at frame arrival while
        # last_progress stalls, which is the visible signal.  status is
        # exempt from BOTH (like the verb span): a probe is not
        # progress — an operator polling a stalled tenant's collection
        # must not reset the very gap the probe exists to read — and
        # probe counts would flood the verbs latency table.
        if verb != "status":
            cs.obs.observe(f"rpc:{verb}", time.monotonic() - t_verb)
            cs.last_progress = time.monotonic()
            cs.obs.gauge("last_progress_ts", round(time.time(), 3))
        if sess is not None:
            sess.put(req_id, resp)
            sess.inflight.pop(req_id, None)
            if not done.done():
                done.set_result(resp)
        return resp

    async def _handle_leader(self, reader, writer):
        """Control-plane serve loop with request ids and concurrent
        handling (the reference's tarpc ids + buffer_unordered(100),
        server.rs:359-376): each frame is (req_id, verb, payload) and every
        request runs as its own task, so many in-flight add_keys batches
        deserialize and append while others are still on the wire.  Verbs
        that touch the data plane or mutate protocol state serialize on
        their session's verb lock; responses carry the id so completion
        order is free.

        A ``__hello__`` frame (sent by the reconnecting client on every
        connect) binds this connection to a leader session AND to a
        collection session (``collection`` field; absent = the default
        collection); all later verbs on the connection go through that
        session's replay dedup (:meth:`_dispatch`) and run against that
        collection's state.  A client that never says hello gets the
        legacy at-most-once-per-connection, default-collection
        behavior."""
        write_lock = asyncio.Lock()
        sess: _Session | None = None
        cs: CollectionSession | None = None
        self._ctl_writers.add(writer)

        async def respond(req_id, resp):
            try:
                async with write_lock:
                    await _send(
                        writer, (req_id, resp),
                        count=lambda n: self.obs.count("control_bytes_sent", n),
                    )
            except (ConnectionResetError, BrokenPipeError):
                pass  # leader gone; the work itself must still have finished
            except RuntimeError:
                # asyncio raises RuntimeError for writes on a closing
                # transport — swallow only that case; anything else would
                # silently strand the leader awaiting this req_id
                if not writer.is_closing():
                    raise

        async def handle(req_id, verb, req):
            await respond(
                req_id, await self._dispatch(sess, cs, req_id, verb, req)
            )

        tasks = set()
        try:
            while True:
                req_id, verb, req = await _recv(
                    reader,
                    count=lambda n: self.obs.count("control_bytes_recv", n),
                )
                if verb == "__hello__":
                    try:
                        with guards.unguarded(
                            "serve-loop session bind: event-loop-atomic by "
                            "the fhh-race atomic contracts on _bind_session "
                            "and SessionTable.get"
                        ):
                            sess = self._bind_session(req)
                            new_cs = self._table.get(
                                (req or {}).get("collection")
                            )
                            if new_cs is not cs:
                                # refcount the binding: a bound session
                                # is never idle-evicted (see
                                # CollectionSession.bound)
                                if cs is not None:
                                    cs.bound -= 1
                                new_cs.bound += 1
                            cs = new_cs
                            if sess is not None:
                                sess.collection = cs.key
                    except (ValueError, RuntimeError) as e:
                        # a refused collection (bad key, table at cap)
                        # answers the hello with an error instead of
                        # binding the connection to the wrong session
                        await respond(
                            req_id,
                            {"__error__": f"{type(e).__name__}: {e}"},
                        )
                        continue
                    await respond(
                        req_id,
                        {
                            "boot_id": self._boot_id,
                            "server_id": self.server_id,
                            "collection": cs.key,
                            # trace clock-offset handshake (see status)
                            "clock": round(time.time(), 6),
                        },
                    )
                    continue
                if verb not in self._VERBS:
                    raise ValueError(f"unknown verb {verb!r}")
                t = asyncio.create_task(handle(req_id, verb, req))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            # Drain, don't cancel: a verb may be mid-_swap on the PERSISTENT
            # peer data plane — cancelling between its send and recv would
            # leave the peer's frame unread and desynchronize every later
            # exchange (the old sequential loop always finished the verb in
            # flight; concurrent handling must keep that guarantee), and a
            # verb may legitimately run for minutes (first-call device
            # compiles).  Cancel only on the one condition draining cannot
            # cover: the PEER connection itself is gone — then the data
            # plane is already lost and cancelling costs nothing.
            # A silently-dead peer (partition/power loss, no FIN/RST) is
            # surfaced by the data-plane socket's TCP keepalive (_keepalive,
            # ~2 min): the blocked recv then raises through the mux, the
            # verb task finishes on its own, and is_closing() turns true —
            # so this loop needs no wall-clock guess that could misfire on
            # a LIVE peer running legitimately long verbs.
            pending = set(tasks)
            deadline = time.monotonic() + 1800  # generous overall backstop
            while pending:
                done, pending = await asyncio.wait(pending, timeout=30)
                if done:  # progress: push the backstop out again
                    deadline = time.monotonic() + 1800
                if pending and (
                    self._peer_writer is None
                    or self._peer_writer.is_closing()
                    or time.monotonic() > deadline
                    # the backstop covers what keepalive cannot: a verb
                    # blocked while the peer data plane stays OPEN (e.g. a
                    # desynchronized _swap after a peer-side verb error) —
                    # 30 min without a single task completing is not a
                    # legitimate long verb, it is a wedged handler
                ):
                    for t in pending:
                        t.cancel()
                    break
            writer.close()
            self._ctl_writers.discard(writer)
            if cs is not None:
                cs.bound -= 1  # connection gone: release the binding

    async def aclose(self) -> None:
        """Tear the whole server down — listeners, leader connections,
        peer data plane.  In-memory protocol state is NOT cleared: this
        is process death as far as peers can observe (the chaos tests'
        kill primitive; a restart is a fresh :class:`CollectorServer`)."""
        for srv in (getattr(self, "_rpc_srv", None), getattr(self, "_peer_srv", None)):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        for w in list(self._ctl_writers):
            if not w.is_closing():
                w.close()
        self._ctl_writers.clear()
        if self._peer_writer is not None and not self._peer_writer.is_closing():
            self._peer_writer.close()
        self._plane.close()

    @staticmethod
    def _keepalive(writer: asyncio.StreamWriter) -> None:
        """Aggressive-ish TCP keepalive on the persistent data plane so a
        SILENTLY dead peer (partition, power loss — no FIN/RST) surfaces as
        a connection error within ~2 minutes instead of hanging a blocked
        ``_swap`` recv forever (kernels default to ~2 hours)."""
        import socket

        sock = writer.get_extra_info("socket")
        if sock is None:
            return
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for opt, val in (
            ("TCP_KEEPIDLE", 60), ("TCP_KEEPINTVL", 20), ("TCP_KEEPCNT", 3)
        ):
            if hasattr(socket, opt):
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)

    @staticmethod
    async def _recv_plane_frame(reader):
        """One framed data-plane read for the PlaneMux pump: returns
        (framed byte size, frame).  Byte accounting happens in the mux's
        route hook (the channel is only known after unpickling)."""
        # fhh-lint: disable=unbounded-await (serve-loop read: the pump waits indefinitely for the next frame by design; liveness comes from the socket's TCP keepalive)
        hdr = await reader.readexactly(_HDR.size)
        (n,) = _HDR.unpack(hdr)
        # fhh-lint: disable=unbounded-await (as above)
        return n + _HDR.size, pickle.loads(await reader.readexactly(n))

    def _attach_plane(self, reader, writer) -> None:
        """Bind a fresh peer transport: keepalive on, mux pump attached
        (failing every session's blocked recv from the OLD transport).
        Sessions re-key their channels lazily (``_ensure_session_plane``
        compares ``cs.plane_epoch`` to the mux epoch)."""
        self._peer_reader, self._peer_writer = reader, writer
        self._keepalive(writer)
        self._plane.attach(reader, self._recv_plane_frame)

    async def _dial_peer(self) -> None:
        """Dial the peer data plane under the shared backoff policy (the
        reference's connect_with_retries_tcp, server.rs:235, upgraded from
        fixed sleeps to exponential backoff + full jitter).  Per-session
        channel handshakes (coin flip + base-OT) run lazily over the
        fresh transport — see ``_ensure_session_plane``."""
        peer_host, peer_port = self._peer_addr

        async def dial():
            return await asyncio.wait_for(
                asyncio.open_connection(peer_host, peer_port),
                respolicy.DIAL_TIMEOUT_S,
            )

        try:
            r, w = await respolicy.retry_async(
                dial,
                respolicy.DIAL_POLICY,
                what=f"peer data plane {peer_host}:{peer_port}",
            )
        except respolicy.TRANSIENT_ERRORS as e:
            raise ConnectionError(
                f"peer data-plane unreachable at {peer_host}:{peer_port}: {e!r}"
            ) from e
        self._attach_plane(r, w)

    async def start(self, host: str, port: int, peer_host: str, peer_port: int):
        """Bring up the data plane FIRST (like the reference: GC mesh before
        the RPC listener, server.rs:344-354), then serve the leader.  The
        per-session secure handshakes (base-OT etc.) run lazily when each
        collection first touches the plane."""
        self._peer_addr = (peer_host, peer_port)
        with self.obs.span("setup"):
            if self.server_id == 1:
                srv = await asyncio.start_server(self._on_peer, host, peer_port)
                self._peer_ready = asyncio.Event()
                self._peer_srv = srv
                # fhh-lint: disable=unbounded-await (startup barrier: a
                # listening server legitimately waits as long as it takes
                # its peer to come up; operators bound this externally)
                await self._peer_ready.wait()
            else:
                await self._dial_peer()
            self._rpc_srv = await asyncio.start_server(
                self._handle_leader, host, port
            )
        return self._rpc_srv

    async def _on_peer(self, reader, writer):
        self._attach_plane(reader, writer)
        self._peer_ready.set()

    async def _ensure_session_plane(self, cs: CollectionSession) -> None:
        """Key this session's data-plane channel against the CURRENT
        transport: coin-flip the shared sketch-challenge seed and (in
        secure mode) run the base-OT handshakes — all over the session's
        OWN mux channel, so N sessions key up concurrently on one
        socket.  Runs once per (session, plane epoch): a plane reset
        bumps the mux epoch and every session lazily re-keys at its next
        data-plane verb.  Callers hold the session's verb lock, so the
        handshake can never interleave with the session's own exchanges;
        both servers reach this from the same leader verb
        (tree_init/tree_crawl*/sketch_verify via ``_both``), so the
        channel's FIFO carries matching handshake frames."""
        if cs.plane_epoch == self._plane.epoch and (
            cs._ot is not None or not self.cfg.secure_exchange
        ):
            return
        with cs.obs.span("plane_handshake"):
            # coin flip: each side contributes 16 random bytes; the XOR
            # is uniform if either is honest — and crucially NEVER a
            # public constant (a client that can predict the challenge r
            # can forge a passing sketch)
            mine = _secrets.token_bytes(16)
            theirs = await self._swap(cs, mine)
            cs._sketch_seed = np.frombuffer(
                bytes(a ^ b for a, b in zip(mine, theirs)), dtype="<u4"
            ).copy()
            taint_guard.register(
                "CollectionSession._sketch_seed", cs._sketch_seed
            )
            await self._setup_secure(cs)
        cs.plane_epoch = self._plane.epoch
        obs.emit(
            "plane.session_keyed",
            severity="debug",
            server=self.server_id,
            collection=cs.key,
            epoch=cs.plane_epoch,
        )

    async def _setup_secure(self, cs: CollectionSession) -> None:
        """One-time base-OT setup seeding this SESSION's IKNP extension
        (the ocelot session init of collect.rs:454-461 — ~128 host-side
        Chou-Orlandi OTs; all per-level OT volume then runs as device
        kernels).  TWO sessions, one per garbling direction, so the
        leader can alternate the garbler per level (the reference's
        ``gc_sender`` flip, rpc.rs:20-23, leader.rs:204-210) and
        garbling cost splits across the servers.  In session ``g``
        server ``g`` is the OT-extension sender and plays base-OT
        *receiver* with its secret ``s`` — the standard IKNP role flip
        (ops/otext.py).  Per collection: two tenants' OT streams are
        fully independent (independent secrets, independent cursors), so
        their 2PC transcripts are bit-identical to solo runs by
        construction."""
        if not self.cfg.secure_exchange:
            return
        for g in (0, 1):
            if self.server_id == g:  # extension sender <- base-OT receiver
                s_bits = otext.fresh_s_bits()
                a_msg = await self._dp_recv(cs)
                br = baseot.BaseOtReceiver(s_bits)
                await self._dp_send(cs, br.round1(a_msg))
                cs._ot_snd = otext.OtExtSender(s_bits, br.seeds())
            else:  # extension receiver <- base-OT sender
                bs = baseot.BaseOtSender()
                await self._dp_send(cs, bs.round1())
                r_msgs = await self._dp_recv(cs)
                s0, s1 = bs.seeds([baseot.decompress(m) for m in r_msgs])
                cs._ot_rcv = otext.OtExtReceiver(s0, s1)
        cs._ot = (cs._ot_snd, cs._ot_rcv)  # marker: secure plane live
        cs._sec_seed = np.frombuffer(
            _secrets.token_bytes(16), dtype="<u4"
        ).copy()
        taint_guard.register("CollectionSession._sec_seed", cs._sec_seed)


class ServerRestartedError(ConnectionError):
    """The reconnect handshake found a DIFFERENT server process (new boot
    id): in-memory protocol state is gone, so blind verb replay is not
    safe — the supervising leader must run the restore path (re-upload
    keys, ``tree_restore``) instead.  Subclasses ConnectionError so
    non-supervised callers still see it as a connection-shaped failure."""


class CollectorClient:
    """Leader-side RPC stub (the tarpc-generated client analogue), now
    RECONNECTING.

    The framing carries request ids, so any number of calls may be in
    flight on one connection; a reader task resolves futures by id
    (tarpc's pipelining model, leader.rs:340-364 drives 1000 in-flight
    addkey batches through it).

    Recovery semantics: the client owns a session id for its lifetime.
    Every (re)connect sends ``__hello__ {session, epoch}``; on transport
    loss mid-call, the call redials under ``dial_policy`` (one winner per
    epoch — concurrent failed calls piggyback on the same redial) and
    RESENDS its frame with the SAME req_id.  The server's per-session
    dedup cache answers replays idempotently, so a verb whose response
    was lost in flight is never double-applied.  Two ways out of the
    retry loop: the per-verb wall-clock budget (``VerbBudgets``)
    expires, or the hello discovers a new server boot id
    (:class:`ServerRestartedError` — replay would run against empty
    state)."""

    def __init__(
        self,
        host: str,
        port: int,
        reg: obsmetrics.Registry | None = None,
        *,
        dial_policy: respolicy.RetryPolicy | None = None,
        budgets: respolicy.VerbBudgets | None = None,
        collection: str | None = None,
    ):
        self._host, self._port = host, port
        # the collection session this client's connections bind to on
        # the server (multi-tenant wire keying; None/"" = the default
        # collection — every single-tenant flow unchanged)
        self.collection = collection or DEFAULT_COLLECTION
        self._r = self._w = None
        self._send_lock = asyncio.Lock()
        self._conn_lock = asyncio.Lock()
        self._flush_task: asyncio.Task | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._reader_task: asyncio.Task | None = None
        self._dead: ConnectionError | None = None
        self.session_id = _secrets.token_hex(8)
        self.epoch = 0  # successful connects; >1 means we have reconnected
        self.boot_id: str | None = None  # server identity from last hello
        # trace clock-handshake component tag ("server0"/"server1"),
        # learned from the hello's server_id
        self._clock_tag: str | None = None
        self.dial_policy = dial_policy or respolicy.DIAL_POLICY
        self.budgets = budgets or respolicy.VerbBudgets()
        # control-plane byte accounting lands on the leader process's
        # default registry unless the caller owns one
        self.obs = obsmetrics.default_registry() if reg is None else reg

    @classmethod
    async def connect(cls, host: str, port: int, **kw) -> "CollectorClient":
        c = cls(host, port, **kw)
        await c._ensure_connected(0)
        return c

    async def aclose(self) -> None:
        self._dead = ConnectionError("client closed")
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._w is not None and not self._w.is_closing():
            self._w.close()
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.set_exception(self._dead)
        self._fail_pending(self._dead)

    def _fail_pending(self, err: ConnectionError) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    async def _ensure_connected(self, seen_epoch: int) -> None:
        """(Re)dial unless someone already did since ``seen_epoch`` (the
        epoch the caller last observed).  All concurrently-failed calls
        funnel here; the first through the lock redials, the rest find a
        fresh epoch and just resend."""
        if self._dead is not None:
            raise self._dead
        async with self._conn_lock:
            if (
                self.epoch > seen_epoch
                and self._w is not None
                and not self._w.is_closing()
            ):
                return  # a concurrent caller already reconnected

            async def dial():
                return await asyncio.wait_for(
                    asyncio.open_connection(self._host, self._port),
                    respolicy.DIAL_TIMEOUT_S,
                )

            try:
                r, w = await respolicy.retry_async(
                    dial,
                    self.dial_policy,
                    what=f"dial {self._host}:{self._port}",
                )
            except respolicy.TRANSIENT_ERRORS as e:
                # NOT permanent: a later call (e.g. the supervisor's next
                # recovery wave) may find the server back up and redial
                err = ConnectionError(
                    f"server {self._host}:{self._port} unreachable: {e!r}"
                )
                self._fail_pending(err)
                raise err from e
            if self._reader_task is not None:
                self._reader_task.cancel()
            if self._w is not None and not self._w.is_closing():
                # close the superseded transport: after a peer FIN (or a
                # reader death that left TCP up, e.g. a corrupt frame)
                # the old fd would otherwise sit in CLOSE_WAIT — and the
                # server would keep a zombie handler bound to it — for
                # the life of the process, one leak per reconnect
                self._w.close()
            # any future still pending belongs to the OLD transport: its
            # response can never arrive — fail it so its owner replays on
            # the fresh epoch instead of waiting out its whole budget
            self._fail_pending(
                ConnectionError("transport replaced by reconnect")
            )
            # a coalesced drain still waiting on the old transport can
            # never finish — fail it (ConnectionError = transient, so its
            # waiters replay on the fresh epoch) and start clean
            if self._flush_task is not None and not self._flush_task.done():
                self._flush_task.set_exception(
                    ConnectionError("transport replaced by reconnect")
                )
            self._flush_task = None
            self._r, self._w = r, w
            self.epoch += 1
            self._reader_task = asyncio.ensure_future(self._read_loop(r))
            # session handshake: bind this connection to our session (the
            # server arms replay dedup) and learn the server's boot id
            self._next_id += 1
            t_hello = time.time()
            hello = await self._roundtrip(
                self._next_id,
                "__hello__",
                {
                    "session": self.session_id,
                    "epoch": self.epoch,
                    "collection": self.collection,
                },
                respolicy.Deadline(self.budgets.budget("__hello__")),
            )
            self._note_clock(hello, t_hello)
            if isinstance(hello, dict) and "__error__" in hello:
                # the server refused the collection (bad key / session
                # table at cap): NOT transport-shaped — retrying the
                # dial cannot help, the caller must change its ask
                raise RuntimeError(
                    f"hello refused by {self._host}:{self._port}: "
                    f"{hello['__error__']}"
                )
            new_boot = hello.get("boot_id")
            old_boot, self.boot_id = self.boot_id, new_boot
            if self.epoch > 1:
                self.obs.count("reconnects")
                obs.emit(
                    "resilience.reconnect",
                    host=self._host,
                    port=self._port,
                    epoch=self.epoch,
                    restarted=bool(old_boot and old_boot != new_boot),
                )

    def _note_clock(self, resp, t_sent: float) -> None:
        """Trace clock-offset handshake: a hello/status response carries
        the server's wall clock — record the NTP-style midpoint offset
        (server_clock - leader_clock) so ``obs.trace merge`` can place
        both servers' spans on the leader's timeline.  No-op without
        tracing or when the response carries no clock."""
        if not obstrace.enabled() or not isinstance(resp, dict):
            return
        clock = resp.get("clock")
        if clock is None:
            return
        sid = resp.get("server_id")
        if sid is not None:
            self._clock_tag = f"server{sid}"
        if self._clock_tag is None:
            return
        t_recv = time.time()
        obstrace.note_clock(
            self._clock_tag,
            float(clock) - (t_sent + t_recv) / 2.0,
            t_recv - t_sent,
        )

    async def _roundtrip(self, req_id, verb, req, deadline: respolicy.Deadline):
        """One send + response wait on the CURRENT transport (no retry —
        :meth:`call` owns the retry loop, and owns the req_id: a REPLAY
        must reuse the original id or the server's dedup cache can never
        recognize it)."""
        if (
            self._w is None
            or self._w.is_closing()
            or self._reader_task is None
            or self._reader_task.done()
        ):
            # transport already known-dead: a write might still "succeed"
            # locally (FIN'd socket) and the dead reader would never
            # resolve the future — fail fast into the reconnect path
            # instead of waiting out the whole verb budget
            raise ConnectionError("transport down")
        fut = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        try:
            async with self._send_lock:
                await _send(
                    self._w, (req_id, verb, req or {}),
                    count=lambda n: self.obs.count("control_bytes_sent", n),
                    flush=False,
                )
            # coalesced drain: a burst of concurrent frames (the 256-deep
            # upload window, a pipelined level's span verbs) shares ONE
            # drain instead of one await per frame — backpressure is
            # still applied, once per burst
            await self._flush()
            return await deadline.wait_for(fut)
        finally:
            # send raised mid-write, the wait timed out, or the reader
            # failed the future: either way the response slot is dead —
            # drop it so _pending can't grow across failed calls
            self._pending.pop(req_id, None)

    async def _flush(self) -> None:
        """Shared, coalesced ``drain()``: every writer since the last
        flush awaits the SAME drain outcome (a future the drain helper
        resolves).  Shielded so one caller's cancellation (a verb
        deadline firing) cannot starve the other writers; a drain
        failure (dead transport) surfaces to every waiter as the same
        connection-shaped error the per-frame drain raised — and a
        reconnect fails the stale flush with ConnectionError (see
        ``_ensure_connected``), which the retry loop classifies as
        transient and replays through."""
        fut = self._flush_task
        if fut is None or fut.done():
            fut = self._flush_task = asyncio.get_event_loop().create_future()
            # no "never retrieved" GC noise if every waiter was cancelled
            fut.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None
            )

            async def do_drain(w=self._w, fut=fut):
                try:
                    await w.drain()
                except Exception as e:  # fhh-lint: disable=broad-except (outcome relay: every drain failure must reach the coalesced waiters, whatever its type)
                    if not fut.done():
                        fut.set_exception(e)
                else:
                    if not fut.done():
                        fut.set_result(None)

            asyncio.ensure_future(do_drain())
        await asyncio.shield(fut)

    async def _read_loop(self, reader):
        try:
            while True:
                req_id, resp = await _recv(
                    reader,
                    count=lambda n: self.obs.count("control_bytes_recv", n),
                )
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except Exception as e:  # reader death fails every in-flight caller
            err = ConnectionError(f"connection lost: {e!r}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            if not isinstance(e, respolicy.TRANSIENT_ERRORS):
                # anything else is a BUG in this client, not a transport
                # death.  Emit it NOW — nothing awaits the reader task, so
                # a bare re-raise would sit unretrieved until GC — then
                # re-raise for any future consumer of the task result.
                obs.emit(
                    "client.reader_error", severity="error",
                    error=f"{type(e).__name__}: {e}",
                )
                raise

    async def call(self, verb: str, req=None):
        """At-most-once verb call with transparent replay: transient
        transport failures redial and resend the SAME req_id (the server
        dedups); the per-verb wall-clock budget bounds the whole affair —
        every redial, every replay, and the server's execution."""
        if self._dead is not None:
            raise self._dead
        deadline = self.budgets.deadline(verb)
        first_boot = self.boot_id
        payload = req or {}
        # distributed trace: stamp this call's span id into the request
        # ONCE — replays resend the identical {"t","s","p"} with the
        # req_id, so the server records the span exactly once per
        # execution (dedup by (trace_id, span_id), like req_ids).  The
        # payload is COPIED before stamping: callers share one req dict
        # across both servers' calls (RpcLeader._both), and each call
        # owns its own span.
        twire = obstrace.wire_ctx() if obstrace.enabled() else None
        if twire is not None:
            payload = dict(payload)
            payload["trace"] = twire[0]
        self._next_id += 1
        req_id = self._next_id  # ONE id for the call's lifetime: replays
        resp = None             # reuse it so the server can dedup them
        retried = False
        try:
            while True:
                seen_epoch = self.epoch
                try:
                    t_sent = time.time()
                    resp = await self._roundtrip(
                        req_id, verb, payload, deadline
                    )
                    if verb == "status" and not retried:
                        # periodic clock-offset refresh rides the probe.
                        # First-attempt responses only: a retried status
                        # may be answered from the replay-dedup cache,
                        # whose "clock" is the ORIGINAL execution's —
                        # pairing it with this attempt's send/recv
                        # instants would skew the offset by the whole
                        # reconnect backoff.
                        self._note_clock(resp, t_sent)
                    break
                except respolicy.TRANSIENT_ERRORS as e:
                    retried = True
                    if deadline.expired():
                        raise TimeoutError(
                            f"verb {verb!r} exceeded its "
                            f"{self.budgets.budget(verb):g}s budget "
                            f"(last error: {type(e).__name__}: {e})"
                        ) from e
                    self.obs.count("call_retries")
                    obs.emit(
                        "resilience.call_retry",
                        severity="debug",
                        verb=verb,
                        epoch=seen_epoch,
                        error=f"{type(e).__name__}: {e}",
                    )
                    await self._ensure_connected(seen_epoch)
                    if first_boot is not None and self.boot_id != first_boot:
                        raise ServerRestartedError(
                            f"server {self._host}:{self._port} restarted "
                            f"while {verb!r} was in flight — state lost, "
                            "replay unsafe"
                        ) from e
        except BaseException:
            if twire is not None:
                # the call span closes error=true — a severed transport
                # or blown budget never leaves it dangling in the trace
                obstrace.call_event(verb, self.obs.name, twire[1], error=True)
            raise
        # a server-side failure travels as an __error__ RESPONSE: the
        # call span must close error=true too (filtering the merged
        # timeline by error has to surface server failures, not just
        # transport ones)
        server_err = isinstance(resp, dict) and "__error__" in resp
        if twire is not None:
            obstrace.call_event(
                verb, self.obs.name, twire[1], error=server_err
            )
        if server_err:
            raise RuntimeError(f"server error on {verb}: {resp['__error__']}")
        return resp

    def __getattr__(self, verb):
        if verb.startswith("_"):
            raise AttributeError(verb)

        async def _verb(req=None):
            return await self.call(verb, req)

        return _verb
